"""Azure-Search-style indexing sink: push DataFrame rows as documents into
a search index, creating the index from its JSON definition if missing.

Reference: io/http/src/main/scala/services/AzureSearch.scala:143
(AzureSearchWriter.write: parse indexJson -> SearchIndex.createIfNoneExists
-> checkSchemaParity -> batched AddDocuments POSTs with @search.action per
row) and AzureSearchAPI.scala (index existence check + creation calls).

Endpoint-agnostic like the other cognitive clients (tests run a local mock;
this build has no egress): `base_url` is whatever speaks the contract —
  GET  {base_url}/indexes/{name}?api-version=...        existence probe
  POST {base_url}/indexes?api-version=...               index creation
  POST {base_url}/indexes/{name}/docs/index?api-version=...  uploads
The admin key rides the `api-key` header (Azure Search's convention, unlike
the Ocp-Apim header of the other services).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType
from mmlspark_tpu.io.http.schema import HTTPRequestData, entity_to_string
from mmlspark_tpu.io.http.transformer import HTTPTransformer

_API_VERSION = "2017-11-11"  # the reference's pinned default
_ACTION_COL = "@search.action"


def _jsonable(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def _send(client: HTTPTransformer, request: HTTPRequestData):
    df = DataFrame({"request": Column(np.array([request], object), DataType.STRUCT)})
    return client.transform(df)["response"][0]


def _headers(key: Optional[str]) -> Dict[str, str]:
    h = {"Content-Type": "application/json"}
    if key:
        h["api-key"] = key
    return h


def create_index_if_missing(
    base_url: str,
    index_json: str,
    key: Optional[str] = None,
    api_version: str = _API_VERSION,
) -> bool:
    """Probe GET /indexes/{name}; on 404 POST the definition to /indexes.
    Returns True when the index was created, False when it already existed.
    (SearchIndex.createIfNoneExists, AzureSearchAPI.scala.)"""
    index = json.loads(index_json)
    name = index.get("name")
    if not name:
        raise ValueError("index_json must carry a 'name' field")
    client = HTTPTransformer(input_col="request", output_col="response")
    probe = HTTPRequestData.get(
        f"{base_url}/indexes/{name}?api-version={api_version}", _headers(key)
    )
    resp = _send(client, probe)
    if 200 <= resp.status_line.status_code < 300:
        return False
    if resp.status_line.status_code != 404:
        raise RuntimeError(
            f"index probe failed: HTTP {resp.status_line.status_code} "
            f"{entity_to_string(resp)!r}"
        )
    created = _send(
        client,
        HTTPRequestData.post_json(
            f"{base_url}/indexes?api-version={api_version}", index_json,
            _headers(key),
        ),
    )
    if not 200 <= created.status_line.status_code < 300:
        raise RuntimeError(
            f"index creation failed: HTTP {created.status_line.status_code} "
            f"{entity_to_string(created)!r}"
        )
    return True


def write(
    df: DataFrame,
    base_url: str,
    index_json: str,
    key: Optional[str] = None,
    action: str = "upload",
    action_col: Optional[str] = None,
    batch_size: int = 100,
    api_version: str = _API_VERSION,
) -> int:
    """Upload every row as a search document; returns the number of batches.

    - The index is created from `index_json` if missing (reference
      AzureSearchWriter.write step 1).
    - Schema parity: every DataFrame column must be a declared index field
      (checkSchemaParity — a mismatched upload would 400 on the real
      service; failing fast here keeps the contract honest).
    - Each document carries `@search.action` — `action` for all rows, or
      per-row values from `action_col` (reference actionCol).
    """
    index = json.loads(index_json)
    declared = {f["name"] for f in index.get("fields", [])}
    doc_cols = [c for c in df.columns if c != action_col]
    missing = [c for c in doc_cols if c not in declared]
    if missing:
        raise ValueError(
            f"columns {missing} are not fields of index "
            f"{index.get('name')!r}; declared: {sorted(declared)}"
        )

    create_index_if_missing(base_url, index_json, key, api_version)

    url = (
        f"{base_url}/indexes/{index['name']}/docs/index"
        f"?api-version={api_version}"
    )
    client = HTTPTransformer(input_col="request", output_col="response")
    n = len(df)
    n_batches = 0
    for start in range(0, n, batch_size):
        stop = min(start + batch_size, n)
        docs = []
        for i in range(start, stop):
            doc = {c: _jsonable(df[c][i]) for c in doc_cols}
            doc[_ACTION_COL] = (
                str(df[action_col][i]) if action_col else action
            )
            docs.append(doc)
        resp = _send(
            client,
            HTTPRequestData.post_json(
                url, json.dumps({"value": docs}), _headers(key)
            ),
        )
        if not 200 <= resp.status_line.status_code < 300:
            raise RuntimeError(
                f"document upload failed at batch {n_batches}: HTTP "
                f"{resp.status_line.status_code} {entity_to_string(resp)!r}"
            )
        n_batches += 1
    return n_batches
