"""Whole-file binary reader with zip-walking and subsampling.

Reference: io/binary/src/main/scala/BinaryFileFormat.scala —
BinaryRecordReader walks regular files AND entries inside .zip files
(:34-113), with `subsample` pseudo-random row skipping and `inspectZip`
toggling the zip walk. Rows are (path, bytes) matching
core/schema/BinaryFileSchema.
"""

from __future__ import annotations

import fnmatch
import os
import random
import zipfile
from typing import List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType


def _walk_files(path: str, recursive: bool, pattern: Optional[str]) -> List[str]:
    if os.path.isfile(path):
        return [path]
    out: List[str] = []
    if recursive:
        for root, _, files in os.walk(path):
            out.extend(os.path.join(root, f) for f in files)
    else:
        out = [
            os.path.join(path, f)
            for f in os.listdir(path)
            if os.path.isfile(os.path.join(path, f))
        ]
    if pattern:
        out = [f for f in out if fnmatch.fnmatch(os.path.basename(f), pattern)]
    return sorted(out)


def read_binary(
    path: str,
    recursive: bool = True,
    sample_ratio: float = 1.0,
    inspect_zip: bool = True,
    seed: int = 0,
    pattern: Optional[str] = None,
    num_partitions: int = 1,
) -> DataFrame:
    """Read files under `path` as (path, bytes) rows.

    inspect_zip: descend into .zip archives, one row per entry, with the
    reference's "zipfile.zip/entry" path convention. sample_ratio: keep each
    row with this probability (BinaryFileFormat's subsample).
    """
    rng = random.Random(seed)
    paths: List[str] = []
    blobs: List[bytes] = []

    def keep() -> bool:
        return sample_ratio >= 1.0 or rng.random() < sample_ratio

    for fpath in _walk_files(path, recursive, pattern):
        if inspect_zip and zipfile.is_zipfile(fpath):
            with zipfile.ZipFile(fpath) as zf:
                for name in zf.namelist():
                    if name.endswith("/"):
                        continue
                    if keep():
                        paths.append(f"{fpath}/{name}")
                        blobs.append(zf.read(name))
        else:
            if keep():
                paths.append(fpath)
                with open(fpath, "rb") as f:
                    blobs.append(f.read())

    value = np.empty(len(blobs), dtype=object)
    for i, b in enumerate(blobs):
        value[i] = b
    return DataFrame(
        {
            "path": Column(np.array(paths, dtype=object), DataType.STRING),
            "value": Column(value, DataType.BINARY),
        },
        num_partitions,
    )
