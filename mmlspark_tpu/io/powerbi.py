"""PowerBI sink: push DataFrame rows to a Power BI streaming-dataset URL.

Reference: io/powerbi/src/main/scala/PowerBIWriter.scala:25-118 — rows
mini-batch (fixed/dynamic/timed), each batch serializes to a JSON array and
POSTs to the push URL through the HTTP-on-Spark client tier; HTTP errors
surface to the caller. Same composition here over the io.http stages. Works
against any endpoint speaking the push contract (tests run a local server;
this build has no network egress).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.io.http.schema import HTTPRequestData, entity_to_string
from mmlspark_tpu.io.http.transformer import HTTPTransformer
from mmlspark_tpu.stages.batching import (
    DynamicMiniBatchTransformer,
    FixedMiniBatchTransformer,
    TimeIntervalMiniBatchTransformer,
)

_APPLICABLE = {
    "concurrency", "concurrentTimeout", "minibatcher",
    "maxBatchSize", "batchSize", "millisToWait",
}


def _jsonable(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def write(df: DataFrame, url: str, options: Optional[Dict[str, str]] = None) -> int:
    """POST every row to `url` as JSON-array batches; returns the number of
    batches sent. Raises RuntimeError on any non-2xx response (the
    reference's HttpResponseException path)."""
    options = dict(options or {})
    unknown = set(options) - _APPLICABLE
    if unknown:
        raise ValueError(f"{sorted(unknown)} not applicable; use {sorted(_APPLICABLE)}")

    minibatcher = options.get("minibatcher", "fixed")
    if minibatcher == "fixed":
        mb = FixedMiniBatchTransformer(batch_size=int(options.get("batchSize", 10)))
    elif minibatcher == "dynamic":
        mb = DynamicMiniBatchTransformer(
            max_batch_size=int(options.get("maxBatchSize", 10 ** 9))
        )
    elif minibatcher == "timed":
        mb = TimeIntervalMiniBatchTransformer(
            millis_to_wait=int(options.get("millisToWait", 1000))
        )
    else:
        raise ValueError(f"unknown minibatcher {minibatcher!r}")

    batched = mb.transform(df)
    cols = list(batched.columns)
    n = len(batched)
    requests = np.empty(n, object)
    for i in range(n):
        rows = None
        for name in cols:
            vals = batched[name][i]
            vals = list(np.asarray(vals).tolist()) if not isinstance(vals, list) else vals
            if rows is None:
                rows = [{} for _ in vals]
            for r, v in zip(rows, vals):
                r[name] = _jsonable(v)
        body = json.dumps(rows or [])
        requests[i] = HTTPRequestData.post_json(url, body)

    from mmlspark_tpu.core.dataframe import Column

    client = HTTPTransformer(input_col="request", output_col="response")
    concurrency = int(options.get("concurrency", 1))
    client.set(client.concurrency, concurrency)
    if "concurrentTimeout" in options:
        client.set(client.concurrent_timeout, float(options["concurrentTimeout"]))
    # Send in concurrency-sized waves, checking each before the next, so a
    # failing endpoint aborts at the failing batch (reference PowerBIWriter
    # fails the write there) instead of burning retries on the whole rest.
    wave = max(1, concurrency)
    for start in range(0, n, wave):
        chunk = requests[start : start + wave]
        req_df = DataFrame({"request": Column(chunk, DataType.STRUCT)})
        out = client.transform(req_df)
        for resp in out["response"]:
            code = resp.status_line.status_code
            if not 200 <= code < 300:
                raise RuntimeError(
                    f"PowerBI push failed: HTTP {code} "
                    f"{resp.status_line.reason_phrase} "
                    f"{entity_to_string(resp)!r}"
                )
    return n
