"""Image file reading / decoding to the IMAGE row schema.

Reference: io/image PatchedImageFileFormat.scala:23 + ImageUtils. Decoded
rows follow core/schema.make_image_row: HxWxC uint8, BGR channel order
(OpenCV convention, like the reference), mode = OpenCV type code.

Codec backend: Pillow (baked into the environment) for jpg/png/bmp/...;
raw .npy arrays load directly.
"""

from __future__ import annotations

import io as _io
import os
from typing import Dict, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType
from mmlspark_tpu.core.schema import make_image_row
from mmlspark_tpu.io.binary import read_binary

IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".ppm", ".npy")

# The errors a codec actually raises on corrupt/unsupported bytes: Pillow
# signals UnidentifiedImageError (an OSError) or truncated-stream OSErrors,
# SyntaxError from broken PNG chunk parsing, DecompressionBombError (a
# direct Exception subclass) past MAX_IMAGE_PIXELS; np.load raises
# ValueError on a bad .npy header. Anything else (MemoryError,
# KeyboardInterrupt, bugs in our own code) must propagate — a bare
# `except Exception` here once silently swallowed every failure mode into
# a shorter DataFrame.
try:
    from PIL.Image import DecompressionBombError as _BombError
except ImportError:  # Pillow absent: raw-.npy decoding still works
    _BombError = OSError
DECODE_ERRORS = (OSError, ValueError, SyntaxError, _BombError)


def invalid_image_row(path: str, error: str) -> Dict:
    """Marker row for an undecodable image (Spark ImageSchema's invalid
    image, `ImageSchema.invalidImageRow`): data None, dims -1, and the
    decode failure recorded on the row so callers can see WHY."""
    return {
        "path": path,
        "height": -1,
        "width": -1,
        "nChannels": -1,
        "mode": -1,
        "data": None,
        "error": error,
    }


def decode_image(data: bytes, path: str = "") -> Dict:
    """bytes -> image row dict (BGR uint8)."""
    if path.endswith(".npy") or data[:6] == b"\x93NUMPY":
        arr = np.load(_io.BytesIO(data), allow_pickle=False)
        return make_image_row(np.asarray(arr, np.uint8), path)
    from PIL import Image

    with Image.open(_io.BytesIO(data)) as im:
        if im.mode in ("L", "I;16", "I"):
            arr = np.asarray(im.convert("L"), np.uint8)
        elif im.mode == "RGBA":
            arr = np.asarray(im, np.uint8)[:, :, [2, 1, 0, 3]]  # -> BGRA
        else:
            arr = np.asarray(im.convert("RGB"), np.uint8)[:, :, ::-1]  # -> BGR
        return make_image_row(arr, path)


def encode_image(row: Dict, fmt: str = "png") -> bytes:
    """image row dict -> encoded bytes (inverse of decode_image)."""
    from PIL import Image

    data = np.asarray(row["data"])
    if data.ndim == 3 and data.shape[2] == 3:
        data = data[:, :, ::-1]  # BGR -> RGB
    elif data.ndim == 3 and data.shape[2] == 4:
        data = data[:, :, [2, 1, 0, 3]]
    elif data.ndim == 3 and data.shape[2] == 1:
        data = data[:, :, 0]
    buf = _io.BytesIO()
    Image.fromarray(data).save(buf, format=fmt.upper())
    return buf.getvalue()


def read_images(
    path: str,
    recursive: bool = True,
    sample_ratio: float = 1.0,
    inspect_zip: bool = True,
    seed: int = 0,
    drop_invalid: bool = True,
    num_partitions: int = 1,
) -> DataFrame:
    """Read images under `path` into an IMAGE-schema DataFrame
    (columns: path STRING, image STRUCT).

    drop_invalid=True drops undecodable files (Spark ImageSource semantics);
    drop_invalid=False keeps them as invalid_image_row markers carrying the
    decode error, so a corrupt file is visible in the output instead of a
    silently shorter DataFrame.
    """
    raw = read_binary(
        path, recursive=recursive, sample_ratio=sample_ratio,
        inspect_zip=inspect_zip, seed=seed, num_partitions=num_partitions,
    )
    paths, images = [], []
    for p, blob in zip(raw["path"], raw["value"]):
        base = os.path.basename(p).lower()
        if not base.endswith(IMAGE_EXTENSIONS):
            if drop_invalid:
                continue
        try:
            images.append(decode_image(bytes(blob), p))
            paths.append(p)
        except DECODE_ERRORS as e:
            if not drop_invalid:
                images.append(invalid_image_row(p, repr(e)))
                paths.append(p)
    img_col = np.empty(len(images), dtype=object)
    for i, im in enumerate(images):
        img_col[i] = im
    return DataFrame(
        {
            "path": Column(np.array(paths, dtype=object), DataType.STRING),
            "image": Column(img_col, DataType.STRUCT),
        },
        num_partitions,
    )
