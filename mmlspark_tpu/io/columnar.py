"""Streaming columnar ingestion: Arrow/Parquet and numpy shard readers.

The L2 io tier's answer to the reference's per-element marshalling
bottleneck (generateDenseDataset, LightGBMUtils.scala:316-395): instead of
materializing a whole dataset in host RAM and copying it element-wise into
the training buffer, shard readers yield BOUNDED column-batch chunks —
at most ``chunk_rows`` rows each — straight into the device dataplane,
where `core/prefetch.DeviceChunkPrefetcher` double-buffers the host→HBM
uploads behind device compute. Peak host footprint is O(chunk), not O(n),
which is what makes the out-of-core GBDT fit (gbdt/trainer.py streamed
path) and 100M+-row ingestion possible on a fixed budget (ROADMAP
"Streaming ingestion for larger-than-HBM data").

Formats:

- ``ParquetShardReader`` — Arrow/Parquet shards via pyarrow (optional
  dependency, import gated); chunks come from ``ParquetFile.iter_batches``
  so no whole-table materialization ever happens (the graftcheck rule
  ``full-materialize-in-stream-path`` keeps it that way).
- ``NumpyShardReader`` — ``.npy`` shards opened with ``mmap_mode="r"`` and
  sliced per chunk; the tier-1-safe fallback with zero dependencies.
- ``ArrayReader`` — in-memory columns chunked as zero-copy row views; the
  `stream_chunk_rows` estimator path and test harness source.

All readers are RE-ITERABLE: every ``iter_chunks()`` call starts a fresh
pass (multi-pass consumers — binner sample pass, bin/spill pass — rely on
it). Per-shard read/decode metrics land in the obs registry
(``io_columnar_*``; docs/observability.md).
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import os
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

from mmlspark_tpu.core.params import Param, TypeConverters, Wrappable
from mmlspark_tpu.core.pipeline import Transformer

DEFAULT_CHUNK_ROWS = 65536

_METRICS: Dict[str, Any] = {}


def _metrics() -> Dict[str, Any]:
    """Process-wide reader instruments, created on first use."""
    if not _METRICS:
        from mmlspark_tpu.obs.metrics import registry

        reg = registry()
        _METRICS["shards"] = reg.counter(
            "io_columnar_shards_total",
            "Shards opened by the streaming columnar readers", ("format",))
        _METRICS["chunks"] = reg.counter(
            "io_columnar_chunks_total",
            "Bounded column-batch chunks yielded", ("format",))
        _METRICS["rows"] = reg.counter(
            "io_columnar_rows_total", "Rows streamed", ("format",))
        _METRICS["bytes"] = reg.counter(
            "io_columnar_read_bytes_total",
            "Host bytes of decoded chunk columns", ("format",))
        _METRICS["read_s"] = reg.histogram(
            "io_columnar_shard_read_seconds",
            "Wall seconds spent reading+decoding one shard", ("format",))
    return _METRICS


@dataclasses.dataclass
class ColumnChunk:
    """One bounded slice of the stream: named host columns plus provenance.

    ``columns`` values are 1-D arrays (or a single 2-D feature block from
    `ArrayReader`); ``index`` is the global chunk ordinal of this pass —
    the fixed accumulation order streamed consumers key on.
    ``shard_index`` is the ordinal of the shard this chunk came from —
    the unit the sharded ingestion tier assigns device ownership by
    (`round_robin_owners`; docs/dataplane.md "Sharded ingestion").
    """

    columns: Dict[str, np.ndarray]
    shard: str
    index: int
    rows: int
    shard_index: int = 0

    def matrix(self, feature_cols: Sequence[str],
               dtype: Any = np.float32) -> np.ndarray:
        """(rows, F) matrix of the named columns. A single 2-D column
        passes through (cast only); 1-D columns stack in the given order.
        One bounded chunk-sized copy — never a whole-table one."""
        if len(feature_cols) == 1:
            arr = self.columns[feature_cols[0]]
            if arr.ndim == 2:
                return np.asarray(arr, dtype)
        return np.column_stack(
            [np.asarray(self.columns[c], dtype) for c in feature_cols]
        )

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.columns.values())


def _record_chunk(fmt: str, chunk: ColumnChunk) -> None:
    m = _metrics()
    m["chunks"].labels(format=fmt).inc()
    m["rows"].labels(format=fmt).inc(chunk.rows)
    m["bytes"].labels(format=fmt).inc(chunk.nbytes)


class ShardReader:
    """Base contract: bounded, re-iterable chunk streams over shards."""

    format = "base"

    def __init__(self, chunk_rows: int = DEFAULT_CHUNK_ROWS):
        if int(chunk_rows) <= 0:
            raise ValueError("chunk_rows must be positive")
        self.chunk_rows = int(chunk_rows)

    @property
    def num_rows(self) -> Optional[int]:
        """Total rows, when knowable without reading data (Parquet footers,
        npy headers, array shapes); None for opaque sources."""
        return None

    @property
    def num_shards(self) -> int:
        """How many shards back this reader (1 for in-memory sources) —
        the unit count `round_robin_owners` maps onto mesh devices."""
        return 1

    @property
    def column_names(self) -> List[str]:
        raise NotImplementedError

    def iter_chunks(self) -> Iterator[ColumnChunk]:
        """A FRESH bounded chunk pass (re-iterable by contract)."""
        raise NotImplementedError

    def __iter__(self) -> Iterator[ColumnChunk]:
        return self.iter_chunks()


def _expand_paths(paths: Union[str, Sequence[str]], suffix: str) -> List[str]:
    """Directory -> sorted shard files; glob pattern -> sorted matches;
    explicit list -> as given (order is the stream order)."""
    if isinstance(paths, str):
        if os.path.isdir(paths):
            return sorted(
                os.path.join(paths, f) for f in os.listdir(paths)
                if f.endswith(suffix)
            )
        if any(ch in paths for ch in "*?["):
            return sorted(_glob.glob(paths))
        return [paths]
    return list(paths)


class ParquetShardReader(ShardReader):
    """Arrow/Parquet shards -> bounded column-batch chunks.

    Chunks come from ``ParquetFile.iter_batches(batch_size=chunk_rows)``:
    pyarrow reads one row-group window at a time, so a batch may carry
    fewer than ``chunk_rows`` rows at row-group boundaries, but never
    more — the bound is what the fixed footprint rides on. Column
    conversion happens PER BATCH (that is the whole point; see the
    ``full-materialize-in-stream-path`` graftcheck rule).
    """

    format = "parquet"

    def __init__(
        self,
        paths: Union[str, Sequence[str]],
        columns: Optional[Sequence[str]] = None,
        chunk_rows: int = DEFAULT_CHUNK_ROWS,
    ):
        super().__init__(chunk_rows)
        self.paths = _expand_paths(paths, ".parquet")
        if not self.paths:
            raise ValueError(f"no parquet shards at {paths!r}")
        self.columns = list(columns) if columns is not None else None
        self._num_rows: Optional[int] = None

    @staticmethod
    def _pq():
        try:
            import pyarrow.parquet as pq
        except ImportError as e:  # pragma: no cover - container has pyarrow
            raise ImportError(
                "ParquetShardReader needs pyarrow; install it or use "
                "NumpyShardReader (the dependency-free shard fallback)"
            ) from e
        return pq

    @property
    def num_rows(self) -> int:
        if self._num_rows is None:
            pq = self._pq()
            # footer metadata only — no row data is read
            self._num_rows = sum(
                pq.ParquetFile(p).metadata.num_rows for p in self.paths
            )
        return self._num_rows

    @property
    def column_names(self) -> List[str]:
        if self.columns is not None:
            return list(self.columns)
        pq = self._pq()
        return list(pq.ParquetFile(self.paths[0]).schema_arrow.names)

    @property
    def num_shards(self) -> int:
        return len(self.paths)

    def iter_chunks(self) -> Iterator[ColumnChunk]:
        pq = self._pq()
        m = _metrics()
        index = 0
        for si, path in enumerate(self.paths):
            shard_s = 0.0
            t0 = time.perf_counter()
            pf = pq.ParquetFile(path)
            m["shards"].labels(format=self.format).inc()
            for batch in pf.iter_batches(
                batch_size=self.chunk_rows, columns=self.columns
            ):
                cols = {
                    name: batch.column(i).to_numpy(zero_copy_only=False)
                    for i, name in enumerate(batch.schema.names)
                }
                now = time.perf_counter()
                shard_s += now - t0
                chunk = ColumnChunk(cols, path, index, batch.num_rows, si)
                _record_chunk(self.format, chunk)
                yield chunk
                index += 1
                t0 = time.perf_counter()  # exclude consumer time
            shard_s += time.perf_counter() - t0
            m["read_s"].labels(format=self.format).observe(shard_s)


class NumpyShardReader(ShardReader):
    """``.npy`` shards -> bounded chunks, no dependencies beyond numpy.

    ``shards`` is a list of ``{column: path.npy}`` dicts (one dict per
    shard; `write_numpy_shards` produces the layout) or a directory it
    wrote. Shard files open with ``mmap_mode="r"`` and only the chunk
    window is copied, so host footprint stays O(chunk) even for shards
    far larger than RAM.
    """

    format = "numpy"

    def __init__(
        self,
        shards: Union[str, Sequence[Dict[str, str]]],
        columns: Optional[Sequence[str]] = None,
        chunk_rows: int = DEFAULT_CHUNK_ROWS,
    ):
        super().__init__(chunk_rows)
        if isinstance(shards, str):
            shards = _scan_numpy_shard_dir(shards)
        self.shards = [dict(s) for s in shards]
        if not self.shards:
            raise ValueError("no numpy shards given")
        self.columns = (
            list(columns) if columns is not None
            else sorted(self.shards[0])
        )

    @property
    def num_rows(self) -> int:
        total = 0
        for shard in self.shards:
            first = shard[self.columns[0]]
            total += int(np.load(first, mmap_mode="r").shape[0])
        return total

    @property
    def column_names(self) -> List[str]:
        return list(self.columns)

    @property
    def num_shards(self) -> int:
        return len(self.shards)

    def iter_chunks(self) -> Iterator[ColumnChunk]:
        m = _metrics()
        index = 0
        for si, shard in enumerate(self.shards):
            shard_s = 0.0
            t0 = time.perf_counter()
            mms = {c: np.load(shard[c], mmap_mode="r") for c in self.columns}
            m["shards"].labels(format=self.format).inc()
            rows = int(next(iter(mms.values())).shape[0])
            name = shard[self.columns[0]]
            for lo in range(0, rows, self.chunk_rows):
                hi = min(lo + self.chunk_rows, rows)
                # np.array copies ONLY the chunk window out of the mmap
                cols = {c: np.array(mm[lo:hi]) for c, mm in mms.items()}
                now = time.perf_counter()
                shard_s += now - t0
                chunk = ColumnChunk(cols, name, index, hi - lo, si)
                _record_chunk(self.format, chunk)
                yield chunk
                index += 1
                t0 = time.perf_counter()
            shard_s += time.perf_counter() - t0
            m["read_s"].labels(format=self.format).observe(shard_s)


class ArrayReader(ShardReader):
    """In-memory columns -> bounded zero-copy row views (the
    ``stream_chunk_rows`` estimator path: the caller already holds the
    arrays, so chunks alias them instead of copying)."""

    format = "array"

    def __init__(
        self,
        columns: Dict[str, np.ndarray],
        chunk_rows: int = DEFAULT_CHUNK_ROWS,
    ):
        super().__init__(chunk_rows)
        if not columns:
            raise ValueError("no columns given")
        self._cols = {k: np.asarray(v) for k, v in columns.items()}
        rows = {v.shape[0] for v in self._cols.values()}
        if len(rows) != 1:
            raise ValueError(f"ragged column lengths: {sorted(rows)}")
        self._rows = rows.pop()

    @property
    def num_rows(self) -> int:
        return int(self._rows)

    @property
    def column_names(self) -> List[str]:
        return list(self._cols)

    def iter_chunks(self) -> Iterator[ColumnChunk]:
        index = 0
        for lo in range(0, self._rows, self.chunk_rows):
            hi = min(lo + self.chunk_rows, self._rows)
            cols = {c: a[lo:hi] for c, a in self._cols.items()}
            chunk = ColumnChunk(cols, "<memory>", index, hi - lo)
            _record_chunk(self.format, chunk)
            yield chunk
            index += 1


def _scan_numpy_shard_dir(path: str) -> List[Dict[str, str]]:
    """Reassemble write_numpy_shards' `<shard>.<column>.npy` layout."""
    shards: Dict[str, Dict[str, str]] = {}
    for f in sorted(os.listdir(path)):
        if not f.endswith(".npy"):
            continue
        stem = f[: -len(".npy")]
        shard_id, _, col = stem.partition(".")
        if not col:
            continue
        shards.setdefault(shard_id, {})[col] = os.path.join(path, f)
    return [shards[k] for k in sorted(shards)]


def write_numpy_shards(
    out_dir: str,
    columns: Dict[str, np.ndarray],
    rows_per_shard: int,
) -> NumpyShardReader:
    """Split 1-D columns into `<shard>.<column>.npy` files under `out_dir`
    and return a reader over them (test/bench harness; 2-D inputs must be
    split into per-slot columns first — that IS the columnar layout)."""
    os.makedirs(out_dir, exist_ok=True)
    rows = {np.asarray(v).shape[0] for v in columns.values()}
    if len(rows) != 1:
        raise ValueError(f"ragged column lengths: {sorted(rows)}")
    n = rows.pop()
    shards: List[Dict[str, str]] = []
    for s, lo in enumerate(range(0, n, int(rows_per_shard))):
        hi = min(lo + int(rows_per_shard), n)
        shard: Dict[str, str] = {}
        for c, a in columns.items():
            a = np.asarray(a)
            if a.ndim != 1:
                raise ValueError(
                    f"column {c!r} is {a.ndim}-D; write per-slot 1-D columns"
                )
            p = os.path.join(out_dir, f"shard_{s:05d}.{c}.npy")
            np.save(p, a[lo:hi])
            shard[c] = p
        shards.append(shard)
    return NumpyShardReader(shards)


def write_parquet_shards(
    out_dir: str,
    columns: Dict[str, np.ndarray],
    rows_per_shard: int,
) -> ParquetShardReader:
    """Split 1-D columns into `shard_NNNNN.parquet` files under `out_dir`
    and return a reader over them (pyarrow required)."""
    import pyarrow as pa

    pq = ParquetShardReader._pq()
    os.makedirs(out_dir, exist_ok=True)
    rows = {np.asarray(v).shape[0] for v in columns.values()}
    if len(rows) != 1:
        raise ValueError(f"ragged column lengths: {sorted(rows)}")
    n = rows.pop()
    paths: List[str] = []
    for s, lo in enumerate(range(0, n, int(rows_per_shard))):
        hi = min(lo + int(rows_per_shard), n)
        arrays, names = [], []
        for c, a in columns.items():
            a = np.asarray(a)
            if a.ndim != 1:
                raise ValueError(
                    f"column {c!r} is {a.ndim}-D; write per-slot 1-D columns"
                )
            arrays.append(pa.array(a[lo:hi]))
            names.append(c)
        p = os.path.join(out_dir, f"shard_{s:05d}.parquet")
        pq.write_table(pa.table(arrays, names=names), p)
        paths.append(p)
    return ParquetShardReader(paths)


def round_robin_owners(num_units: int, devices: Sequence[Any]) -> List[Any]:
    """FIXED round-robin unit->device ownership for sharded ingestion:
    unit i (a reader shard, or a streamed GBDT spill chunk) belongs to
    ``devices[i % len(devices)]`` for the whole fit — deterministic, so
    every pass over the stream places the same rows on the same chip, and
    on a pod each host's reader feeds its own devices. Used with
    ``DeviceChunkPrefetcher(placement=...)``: the staged chunk's rows are
    uploaded straight onto their owner (docs/dataplane.md "Sharded
    ingestion")."""
    if not devices:
        raise ValueError("round_robin_owners needs at least one device")
    return [devices[i % len(devices)] for i in range(int(num_units))]


def open_shards(
    paths: Union[str, Sequence[str]],
    columns: Optional[Sequence[str]] = None,
    chunk_rows: int = DEFAULT_CHUNK_ROWS,
) -> ShardReader:
    """Reader by extension: ``.parquet`` shards -> ParquetShardReader,
    ``.npy`` shard layouts -> NumpyShardReader. Mesh consumers map the
    reader's shards onto devices with ``round_robin_owners`` (the sharded
    streaming ingestion tier)."""
    probe = _expand_paths(paths, ".parquet")
    if probe and all(p.endswith(".parquet") for p in probe):
        return ParquetShardReader(probe, columns, chunk_rows)
    if isinstance(paths, str) and os.path.isdir(paths):
        return NumpyShardReader(paths, columns, chunk_rows)
    raise ValueError(
        f"cannot infer shard format from {paths!r}: expected .parquet "
        "shards or a write_numpy_shards directory"
    )


class ColumnarSource(Transformer, Wrappable):
    """Materialize columnar shards into a DataFrame (the small-data face of
    the streaming tier: when the table fits, read it whole; when it does
    not, use ``reader().iter_chunks()`` — the bounded streaming API this
    stage is a thin Params wrapper over)."""

    paths = Param(
        "paths",
        "Shard files, a shard directory, or a glob (stream order is the "
        "sorted file order)",
        TypeConverters.to_list_string,
    )
    format = Param(
        "format",
        "Shard format: auto (by extension) | parquet | numpy",
        TypeConverters.to_string,
    )
    columns = Param(
        "columns",
        "Columns to read (empty: every column in the shards)",
        TypeConverters.to_list_string,
    )
    chunk_rows = Param(
        "chunk_rows",
        "Max rows per streamed chunk — the bounded host/HBM footprint knob "
        "(docs/dataplane.md Streaming ingestion)",
        TypeConverters.to_int,
    )

    def __init__(self, **kwargs: Any):
        super().__init__()
        self._set_defaults(
            paths=[], format="auto", columns=[],
            chunk_rows=DEFAULT_CHUNK_ROWS,
        )
        self.set_params(**kwargs)

    def reader(self) -> ShardReader:
        """The streaming reader these Params describe."""
        paths = self.get(self.paths)
        if not paths:
            raise ValueError("ColumnarSource needs paths")
        src: Union[str, Sequence[str]] = (
            paths[0] if len(paths) == 1 else paths
        )
        cols = self.get(self.columns) or None
        rows = self.get(self.chunk_rows)
        fmt = self.get(self.format)
        if fmt == "parquet":
            return ParquetShardReader(src, cols, rows)
        if fmt == "numpy":
            return NumpyShardReader(src, cols, rows)
        return open_shards(src, cols, rows)

    def transform(self, df):
        """Read every chunk and concatenate per column (whole-table by
        DESIGN at this stage level; chunked temps stay bounded). The input
        frame's columns ride along unless a shard column shadows them."""
        from mmlspark_tpu.core.dataframe import DataFrame

        parts: Dict[str, List[np.ndarray]] = {}
        for chunk in self.reader().iter_chunks():
            for c, a in chunk.columns.items():
                parts.setdefault(c, []).append(a)
        out = df
        for c, arrs in parts.items():
            out = out.with_column(c, np.concatenate(arrs))
        return out

    def transform_schema(self, schema):
        from mmlspark_tpu.core.dataframe import DataType, Field

        cols = self.get(self.columns)
        if not cols:
            # no explicit projection: the produced columns come from the
            # shard schema — footer/header metadata only, no row reads
            try:
                cols = self.reader().column_names
            except (ValueError, OSError, ImportError):
                cols = []  # paths unset/unreadable at planning time
        have = {f.name for f in schema}
        return list(schema) + [
            Field(c, DataType.DOUBLE) for c in cols if c not in have
        ]
