"""Crash-consistent artifact store: atomic writes, verified loads, retention.

TPU pods get preempted. The reference framework survives a lost worker
because Spark re-runs its tasks; our trainers hold all progress in process
memory, so a `kill -9` at epoch 9 of 10 used to lose everything. This module
is the durability layer under `TPULearner.fit(checkpoint_dir=...)` and the
GBDT trainer's per-K-rounds checkpoints (docs/persistence.md), and the home
of the atomic-write helpers every persisting class routes through
(Network/NetworkBundle/Booster/save_stage — the `non-atomic-artifact-write`
graftcheck rule keeps it that way).

Commit protocol (`CheckpointStore.save`), in order — each step's failure
mode leaves the store loadable:

1. create a unique tmp dir *inside the store root* (same filesystem, so the
   final rename is atomic; readers never look inside ``.tmp-*``);
2. write every payload file into it, ``fsync`` each one (data durable
   before the commit record exists);
3. write ``MANIFEST.json`` LAST — per-file SHA-256 + byte sizes + the
   generation number. The manifest IS the commit record: a generation
   directory without a valid manifest is garbage by definition;
4. ``fsync`` the tmp dir (entries durable), then ``os.replace`` it to
   ``gen_<NNNNNNNN>`` — the atomic publish — and ``fsync`` the store root
   (the rename itself durable across power loss).

A crash before step 4 leaves only an invisible tmp dir (GC'd by the next
writer); a crash during the rename leaves either the tmp name or the final
name, never a half state (POSIX rename atomicity). Torn files can therefore
only be observed in a generation whose manifest *also* landed — impossible
under the ordering above on a correctly-fsyncing filesystem, and still
*detected* (bad hash / short file) and quarantined on a lying one.

Verified load (`load_latest`) walks generations newest-first, re-hashes
every file against the manifest and returns the first intact one; corrupt
generations (bad hash, missing/truncated manifest, torn or missing file)
are moved to ``quarantine/`` — never deleted, they are forensic evidence —
and the walk falls back to the previous generation, incrementing
``checkpoint_resume_total{outcome="fallback"}``.

Fault injection: every filesystem touch routes through the module-level
`_fs` ops, which consult the store's `fault_injector` (or the globally
installed one — `io/storage_faults.py`); the injector raises the same
OSError types a real disk produces, or `InjectedCrash` to simulate a kill
at an exact byte/step. bench.run_recovery_smoke and
tests/test_checkpoint.py sweep every such fault point.
"""

from __future__ import annotations

import contextlib
import hashlib
import io as _io
import json
import os
import shutil
import tempfile
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from mmlspark_tpu.obs.logging import get_logger

log = get_logger("mmlspark_tpu.io.checkpoint")

MANIFEST = "MANIFEST.json"
_GEN_PREFIX = "gen_"
_TMP_PREFIX = ".tmp-"
_QUARANTINE = "quarantine"

#: process-global fault injector (storage_faults.installed() context manager);
#: a store-level `fault_injector=` takes precedence.
_GLOBAL_INJECTOR: Optional[Any] = None


def set_global_fault_injector(inj: Optional[Any]) -> None:
    global _GLOBAL_INJECTOR
    _GLOBAL_INJECTOR = inj


class CorruptArtifactError(RuntimeError):
    """A persisted artifact failed verification (bad hash, missing or
    truncated commit record, torn file). Carries the path and what to do
    about it, so the operator never has to reverse-engineer the layout."""

    def __init__(self, path: str, reason: str, recovery: str):
        self.path = path
        self.reason = reason
        self.recovery = recovery
        super().__init__(
            f"corrupt or incomplete artifact at {path!r}: {reason}. {recovery}"
        )


# -- fault-injectable filesystem primitives -----------------------------------
#
# Every write/fsync/rename in this module (and in the persistence call sites
# that route through the atomic helpers below) goes through these, so
# StorageFaultInjector can tear, crash or ENOSPC any exact step. `tmp_path`
# parameter names are a contract: these primitives are only ever handed
# not-yet-published paths — publishing is `replace_path`'s job.


def _injector(explicit: Optional[Any]) -> Optional[Any]:
    return explicit if explicit is not None else _GLOBAL_INJECTOR


def write_bytes(tmp_path: str, data: bytes, fault_injector: Optional[Any] = None) -> None:
    """Write + flush + fsync `data` at `tmp_path` (a not-yet-published path)."""
    inj = _injector(fault_injector)
    if inj is not None:
        inj.on_write(tmp_path, data)  # may tear/ENOSPC/crash
    with open(tmp_path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    if inj is not None:
        inj.on_fsync(tmp_path)


def fsync_file(path: str, fault_injector: Optional[Any] = None) -> None:
    inj = _injector(fault_injector)
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    if inj is not None:
        inj.on_fsync(path)


def fsync_dir(path: str, fault_injector: Optional[Any] = None) -> None:
    """fsync a directory: makes its entries (created/renamed children)
    durable. A no-op errno on platforms that refuse O_RDONLY dir fsync is
    tolerated — the replace stays atomic, only power-loss durability of the
    entry is platform-dependent there."""
    inj = _injector(fault_injector)
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # EINVAL/EBADF on exotic filesystems
        pass
    finally:
        os.close(fd)
    if inj is not None:
        inj.on_fsync(path)


def fsync_tree(root: str, fault_injector: Optional[Any] = None) -> None:
    """fsync every file and directory under `root` (bottom-up), then `root`
    itself — the durability pass save_stage runs on its staged tmp dir
    before publishing."""
    for dirpath, _dirnames, filenames in os.walk(root, topdown=False):
        for name in filenames:
            fsync_file(os.path.join(dirpath, name), fault_injector)
        fsync_dir(dirpath, fault_injector)


def replace_path(src: str, dst: str, fault_injector: Optional[Any] = None) -> None:
    """The atomic publish: `os.replace` + fsync of the parent directory."""
    inj = _injector(fault_injector)
    if inj is not None:
        inj.on_replace(src, dst, os.replace)
    else:
        os.replace(src, dst)
    fsync_dir(os.path.dirname(os.path.abspath(dst)) or ".", fault_injector)


def atomic_write_bytes(path: str, data: bytes, fault_injector: Optional[Any] = None) -> None:
    """Crash-consistent single-file write: unique tmp sibling, fsync,
    rename over `path`, fsync parent. A crash at any step leaves either the
    old file or the new one, never a torn hybrid."""
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + _TMP_PREFIX, dir=parent
    )
    os.close(fd)
    try:
        write_bytes(tmp, data, fault_injector)
        replace_path(tmp, path, fault_injector)
    except Exception:
        # a live failure (ENOSPC, permission) cleans its scratch; an
        # InjectedCrash (BaseException) deliberately leaves it, like a kill
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str, fault_injector: Optional[Any] = None) -> None:
    atomic_write_bytes(path, text.encode("utf-8"), fault_injector)


def publish_dir(tmp_dir: str, dst: str, fault_injector: Optional[Any] = None) -> None:
    """Publish a fully-written staging directory at `dst`: fsync the tree,
    then atomically swap it in. When `dst` already exists it is parked at a
    unique trash name first (os.replace cannot replace a non-empty dir);
    a live failure swaps the old version back. The park-then-swap window is
    the one residual non-atomicity for *replacing* directory artifacts — the
    checkpoint store never hits it (generation dirs are never overwritten).
    """
    import glob as _glob

    fsync_tree(tmp_dir, fault_injector)
    parent = os.path.dirname(os.path.abspath(dst)) or "."
    trash = None
    if os.path.exists(dst):
        # at most ONE parked incumbent per dst: trash left by an earlier
        # kill holds a version dst has since superseded — reclaim it now so
        # crash-window recovery is never ambiguous about which park is
        # current (dst escaped: its own characters must not glob)
        for stale in _glob.glob(_glob.escape(dst) + ".trash-*"):
            shutil.rmtree(stale, ignore_errors=True)
        trash = tempfile.mkdtemp(
            prefix=os.path.basename(dst) + ".trash-", dir=parent
        )
        os.rmdir(trash)  # need the unique NAME; replace recreates it
        os.replace(dst, trash)
    try:
        replace_path(tmp_dir, dst, fault_injector)
    except Exception:
        # live failure: swap the parked incumbent back. A simulated kill
        # (InjectedCrash, a BaseException) skips this on purpose — a dead
        # process restores nothing; the incumbent survives at the trash
        # name, recoverable by hand, never silently deleted.
        if trash is not None and not os.path.exists(dst):
            try:
                os.replace(trash, dst)
                trash = None
            except OSError:
                pass
        raise
    if trash is not None:
        shutil.rmtree(trash, ignore_errors=True)


@contextlib.contextmanager
def staged_dir(dst: str, fault_injector: Optional[Any] = None) -> Iterator[str]:
    """The directory-artifact staging protocol as one reusable block: yields
    a fresh tmp sibling of `dst` to build into; a clean exit fsyncs the tree
    and publishes it atomically at `dst` (publish_dir); a live failure
    reclaims the staging dir and re-raises. A simulated kill (InjectedCrash,
    a BaseException) leaves the staging dir behind — like a real one.
    Used by save_stage/save_dataframe/Network.save_to_dir so the protocol
    lives in exactly one place."""
    parent = os.path.dirname(os.path.abspath(dst)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp_dir = tempfile.mkdtemp(
        prefix=os.path.basename(dst) + _TMP_PREFIX, dir=parent
    )
    try:
        yield tmp_dir
        publish_dir(tmp_dir, dst, fault_injector)
    except Exception:
        shutil.rmtree(tmp_dir, ignore_errors=True)
        raise


# -- resume identity -----------------------------------------------------------


def fingerprint(ident: Dict[str, Any], *arrays: Any,
                sample_rows: int = 64) -> str:
    """Identity hash a checkpoint may resume against: sha256 of the
    sorted-keys JSON `ident` dict, then up to `sample_rows` evenly spaced
    rows of each array. Each entry is None (skipped — callers encode
    *presence* in `ident` so None vs empty stays distinguishable), an
    ndarray (hashed in its native dtype), or an ``(ndarray, dtype)`` pair
    — the dtype normalization is applied to the sampled rows only, so
    fingerprinting stays O(sample_rows) bytes however large the dataset
    (no full-array copy on the resume path of a memory-tight preemptible
    worker). Sampling keeps it cheap at 100M rows while still
    collision-proof against "resumed on the wrong shard" mistakes. Both
    trainers (TPULearner and the GBDT segment driver) derive their
    fingerprints here so the resume-identity protocol cannot drift
    between them."""
    h = hashlib.sha256(json.dumps(ident, sort_keys=True).encode())
    entries = [e if isinstance(e, tuple) else (e, None)
               for e in arrays if e is not None]
    if entries:
        n = np.asarray(entries[0][0]).shape[0]
        idx = np.linspace(0, n - 1, min(sample_rows, n)).astype(int)
        for a, dt in entries:
            a = np.asarray(a)
            # one shared idx samples every array: a shorter companion
            # would otherwise surface as a raw IndexError mid-hash
            if a.shape[0] != n:
                raise ValueError(
                    f"fingerprint: sampled array has {a.shape[0]} rows, "
                    f"expected {n} (all arrays must share the leading "
                    "dimension)"
                )
            rows = a[idx]
            if dt is not None:
                rows = rows.astype(dt)
            h.update(np.ascontiguousarray(rows).tobytes())
    return h.hexdigest()


# -- array <-> bytes helpers ---------------------------------------------------


def pack_arrays(arrays: Dict[str, np.ndarray]) -> bytes:
    """Serialize a flat {name: ndarray} dict to npz bytes (allow_pickle off:
    checkpoints must never gain pickle semantics)."""
    packed = {}
    for k, v in arrays.items():
        a = np.asarray(v)
        # np.savez's write side pickles object arrays by default; the store
        # would commit such a generation with matching hashes and then every
        # unpack_arrays (allow_pickle=False) on it would fail — an
        # integrity-verified checkpoint that can never be resumed. Refuse at
        # pack time, where the caller can still fix the leaf.
        if a.dtype.hasobject:
            raise TypeError(
                f"pack_arrays: array {k!r} has dtype {a.dtype} — object "
                "arrays would be pickled into the checkpoint and can never "
                "be unpacked (loads run with allow_pickle=False); convert "
                "the value to a numeric, bool, or bytes dtype first"
            )
        packed[k] = a
    buf = _io.BytesIO()
    np.savez(buf, **packed)
    return buf.getvalue()


def unpack_arrays(data: bytes) -> Dict[str, np.ndarray]:
    with np.load(_io.BytesIO(data), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


# -- the store -----------------------------------------------------------------


class Checkpoint:
    """One verified generation: its number, raw file bytes, and user meta."""

    __slots__ = ("generation", "files", "meta", "path")

    def __init__(self, generation: int, files: Dict[str, bytes],
                 meta: Dict[str, Any], path: str):
        self.generation = generation
        self.files = files
        self.meta = meta
        self.path = path

    def arrays(self, name: str) -> Dict[str, np.ndarray]:
        return unpack_arrays(self.files[name])

    def json(self, name: str) -> Any:
        return json.loads(self.files[name].decode("utf-8"))

    def text(self, name: str) -> str:
        return self.files[name].decode("utf-8")


def _obs():
    """(write histogram, bytes counter, resume counter, generation gauge) —
    resolved per call so registry resets in tests pick up fresh families."""
    from mmlspark_tpu.obs.metrics import registry

    reg = registry()
    return (
        reg.histogram("checkpoint_write_seconds",
                      "Wall seconds per checkpoint commit"),
        reg.counter("checkpoint_bytes_total",
                    "Payload bytes committed to checkpoint stores"),
        reg.counter("checkpoint_resume_total",
                    "Checkpoint load outcomes", ("outcome",)),
        reg.gauge("checkpoint_generation",
                  "Latest committed checkpoint generation"),
    )


class CheckpointStore:
    """Crash-consistent, integrity-verified generation store at `root`.

    Not a concurrent-writer store: one training process owns a store at a
    time (generation numbers are scanned, not locked). Readers are always
    safe — they only ever see committed generations.
    """

    def __init__(self, root: str, keep_last: int = 3,
                 fault_injector: Optional[Any] = None):
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1")
        self.root = os.path.abspath(root)
        self.keep_last = int(keep_last)
        self.fault_injector = fault_injector
        os.makedirs(self.root, exist_ok=True)

    # -- enumeration -----------------------------------------------------------

    def generations(self) -> List[int]:
        out = []
        for name in os.listdir(self.root):
            if name.startswith(_GEN_PREFIX):
                try:
                    out.append(int(name[len(_GEN_PREFIX):]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_generation(self) -> Optional[int]:
        gens = self.generations()
        return gens[-1] if gens else None

    def _gen_dir(self, generation: int) -> str:
        return os.path.join(self.root, f"{_GEN_PREFIX}{generation:08d}")

    # -- commit ----------------------------------------------------------------

    def save(self, files: Dict[str, bytes],
             meta: Optional[Dict[str, Any]] = None) -> int:
        """Commit `files` as the next generation; returns its number.

        File names are flat (no path separators — the manifest maps names,
        not trees). Raises OSError (e.g. ENOSPC) on live write failures,
        leaving previous generations untouched.
        """
        from mmlspark_tpu.obs import tracer

        for name in files:
            if os.sep in name or name in (MANIFEST, ""):
                raise ValueError(f"invalid checkpoint file name {name!r}")
        write_hist, bytes_total, _resume, gen_gauge = _obs()
        t0 = time.perf_counter()
        gen = (self.latest_generation() or 0) + 1
        self._gc_tmp()
        tmp = tempfile.mkdtemp(prefix=_TMP_PREFIX, dir=self.root)
        total = 0
        with tracer().span("checkpoint:commit", generation=gen,
                           n_files=len(files)):
            try:
                manifest: Dict[str, Any] = {
                    "generation": gen,
                    "files": {},
                    "meta": meta or {},
                    "created_unix": time.time(),
                }
                for name, data in sorted(files.items()):
                    write_bytes(os.path.join(tmp, name), data,
                                self.fault_injector)
                    manifest["files"][name] = {
                        "sha256": hashlib.sha256(data).hexdigest(),
                        "bytes": len(data),
                    }
                    total += len(data)
                # the commit record goes LAST: its presence asserts every
                # payload byte above is already durable
                write_bytes(
                    os.path.join(tmp, MANIFEST),
                    json.dumps(manifest, indent=1, sort_keys=True).encode(),
                    self.fault_injector,
                )
                fsync_dir(tmp, self.fault_injector)
                replace_path(tmp, self._gen_dir(gen), self.fault_injector)
            except Exception:
                # live failure (not a simulated kill): reclaim the scratch
                shutil.rmtree(tmp, ignore_errors=True)
                raise
        write_hist.observe(time.perf_counter() - t0)
        bytes_total.inc(total)
        gen_gauge.set(gen)
        self._retain()
        log.debug("checkpoint_committed", generation=gen,
                  files=len(files), bytes=total, root=self.root)
        return gen

    def _gc_tmp(self) -> None:
        """Reclaim tmp dirs left by crashed writers (invisible to readers)."""
        for name in os.listdir(self.root):
            if name.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    def _retain(self) -> None:
        gens = self.generations()
        for gen in gens[: max(0, len(gens) - self.keep_last)]:
            shutil.rmtree(self._gen_dir(gen), ignore_errors=True)

    # -- verified load ---------------------------------------------------------

    def _verify_gen(self, generation: int) -> Checkpoint:
        """Read + verify one generation; raises CorruptArtifactError with
        the precise reason on any integrity failure."""
        path = self._gen_dir(generation)
        recovery = (
            "The store will fall back to the previous intact generation; "
            "the corrupt one is moved to quarantine/ for inspection."
        )
        mpath = os.path.join(path, MANIFEST)
        try:
            with open(mpath, "rb") as f:
                manifest = json.loads(f.read().decode("utf-8"))
        except FileNotFoundError:
            raise CorruptArtifactError(path, "missing MANIFEST.json commit "
                                       "record", recovery)
        except (json.JSONDecodeError, UnicodeDecodeError):
            raise CorruptArtifactError(path, "truncated or garbled "
                                       "MANIFEST.json", recovery)
        if not isinstance(manifest, dict) or "files" not in manifest:
            raise CorruptArtifactError(path, "MANIFEST.json lacks a files "
                                       "map", recovery)
        files: Dict[str, bytes] = {}
        for name, rec in manifest["files"].items():
            fpath = os.path.join(path, name)
            try:
                with open(fpath, "rb") as f:
                    data = f.read()
            except FileNotFoundError:
                raise CorruptArtifactError(
                    path, f"payload file {name!r} missing", recovery)
            if len(data) != rec.get("bytes"):
                raise CorruptArtifactError(
                    path,
                    f"payload file {name!r} is {len(data)} bytes, manifest "
                    f"says {rec.get('bytes')} (torn write)", recovery)
            digest = hashlib.sha256(data).hexdigest()
            if digest != rec.get("sha256"):
                raise CorruptArtifactError(
                    path, f"payload file {name!r} hash mismatch (bit rot or "
                    "tampering)", recovery)
            files[name] = data
        return Checkpoint(generation, files, manifest.get("meta", {}), path)

    def _quarantine(self, generation: int, reason: str) -> None:
        qdir = os.path.join(self.root, _QUARANTINE)
        os.makedirs(qdir, exist_ok=True)
        src = self._gen_dir(generation)
        slug = "".join(c if c.isalnum() else "-" for c in reason)[:48]
        dst = os.path.join(qdir, f"{_GEN_PREFIX}{generation:08d}.{slug}")
        try:
            if os.path.exists(dst):
                shutil.rmtree(dst, ignore_errors=True)
            os.replace(src, dst)
        except OSError:  # quarantine is best-effort; the skip is what matters
            log.warning("quarantine_failed", path=src)

    def load_latest(self) -> Optional[Checkpoint]:
        """Newest intact generation, or None when the store holds none.

        Never returns a corrupt artifact: generations failing verification
        are quarantined and the walk falls back
        (`checkpoint_resume_total{outcome="fallback"}`).
        """
        from mmlspark_tpu.obs import tracer

        _w, _b, resume_total, gen_gauge = _obs()
        fell_back = False
        with tracer().span("checkpoint:load", root=self.root) as span:
            for gen in reversed(self.generations()):
                try:
                    ck = self._verify_gen(gen)
                except CorruptArtifactError as e:
                    log.warning("checkpoint_verification_failed",
                                generation=gen, reason=e.reason)
                    self._quarantine(gen, e.reason.split("(")[0].strip())
                    fell_back = True
                    continue
                outcome = "fallback" if fell_back else "resumed"
                resume_total.labels(outcome=outcome).inc()
                gen_gauge.set(gen)
                span.set_attribute("generation", gen)
                span.set_attribute("outcome", outcome)
                return ck
            resume_total.labels(
                outcome="fallback" if fell_back else "fresh"
            ).inc()
            span.set_attribute("outcome", "fresh")
        return None
