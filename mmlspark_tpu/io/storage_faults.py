"""Storage fault injection for the checkpoint/persistence layer.

The disk-side sibling of `serving/faults.py`: deterministic faults injected
at the exact filesystem steps `io/checkpoint.py` routes every persistence
write through (write / fsync / rename), raising the same exception types a
real disk produces — `OSError(ENOSPC)` for a full disk, torn files for a
power cut mid-write — plus `InjectedCrash` to simulate the process dying at
a precise point (`kill -9` semantics: nothing after the fault runs, no
cleanup handlers fire).

`InjectedCrash` deliberately subclasses BaseException: product code that
catches `Exception` for cleanup must NOT intercept a simulated kill, or the
harness would test a politely-failing process instead of a dead one.

Faults are armed per-operation with an optional path substring match and a
1-based `nth` occurrence, so a crash-point sweep can kill a training fit at
*every* checkpoint boundary in turn (tests/test_checkpoint.py,
bench.run_recovery_smoke). `record_ops=True` first runs a fit while logging
every (op, path) touch; the sweep then replays with `crash_at_op(i)` for
each i — interrupting at every injected fault point without knowing the
store's internals.

Install either per-store (`CheckpointStore(fault_injector=...)`) or
process-wide for code paths that build their own stores
(`with installed(inj): learner.fit(df)`).
"""

from __future__ import annotations

import contextlib
import errno
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from mmlspark_tpu.obs.logging import get_logger

log = get_logger("mmlspark_tpu.io.checkpoint")


class InjectedCrash(BaseException):
    """Simulated process death at an injected fault point. BaseException on
    purpose — see module docstring. Only the test/bench harness catches it."""


class StorageFaultInjector:
    """Deterministic storage fault state consulted by `io/checkpoint._fs`
    primitives. Thread-safe; each armed fault fires once (at its `nth`
    matching operation) unless documented persistent."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._faults: List[Dict[str, Any]] = []
        self._op_counter = 0
        self.record_ops = False
        self.ops: List[Tuple[str, str]] = []

    # -- arming ----------------------------------------------------------------

    def _arm(self, **fault: Any) -> None:
        fault["seen"] = 0  # per-fault occurrence count: armed faults never
        # share counters, so rearming or stacking faults on one op is exact
        with self._lock:
            self._faults.append(fault)

    def torn_write(self, match: str = "", at_byte: int = 0, nth: int = 1) -> None:
        """The nth matching write lands only its first `at_byte` bytes on
        disk, then the process dies (power cut mid-write)."""
        self._arm(kind="torn", op="write", match=match, nth=nth,
                  at_byte=int(at_byte))

    def crash_on_write(self, match: str = "", nth: int = 1) -> None:
        """Die just before the nth matching write (file never created)."""
        self._arm(kind="crash", op="write", match=match, nth=nth)

    def crash_on_fsync(self, match: str = "", nth: int = 1) -> None:
        """Die at the nth matching fsync — the written bytes may or may not
        be durable; the commit record that would follow never lands."""
        self._arm(kind="crash", op="fsync", match=match, nth=nth)

    def crash_before_rename(self, match: str = "", nth: int = 1) -> None:
        """Die just before the nth matching atomic publish: the staged tmp
        dir/file exists, the final name was never created/updated."""
        self._arm(kind="crash_before", op="replace", match=match, nth=nth)

    def crash_after_rename(self, match: str = "", nth: int = 1) -> None:
        """Die just after the nth matching atomic publish: the new
        generation is fully committed, nothing after it ran (retention,
        in-memory bookkeeping, the rest of training)."""
        self._arm(kind="crash_after", op="replace", match=match, nth=nth)

    def enospc(self, match: str = "", nth: int = 1) -> None:
        """The nth matching write raises OSError(ENOSPC) after landing a
        prefix of the data (how a full disk actually fails)."""
        self._arm(kind="enospc", op="write", match=match, nth=nth)

    def slow_fsync(self, delay_s: float) -> None:
        """Every fsync takes `delay_s` (a saturated device). Persistent."""
        self._arm(kind="slow", op="fsync", match="", nth=0,
                  delay_s=float(delay_s))

    def crash_at_op(self, op_index: int) -> None:
        """Die at the op_index-th (0-based) filesystem operation of any
        kind — paired with `record_ops` this sweeps every fault point."""
        self._arm(kind="crash_at_op", op="*", nth=0, match="",
                  op_index=int(op_index))

    def clear(self) -> None:
        with self._lock:
            self._faults.clear()
            self._op_counter = 0

    # -- direct corruption (post-commit, no hook needed) -----------------------

    @staticmethod
    def bit_flip(path: str, byte_index: Optional[int] = None,
                 bit: int = 0) -> None:
        """Flip one bit of a committed file in place — silent media
        corruption that only integrity verification can catch."""
        with open(path, "r+b") as f:  # in-place corruption, not an artifact write  # graftcheck: ignore[non-atomic-artifact-write]
            f.seek(0, os.SEEK_END)
            size = f.tell()
            if size == 0:
                return
            idx = size // 2 if byte_index is None else min(byte_index, size - 1)
            f.seek(idx)
            b = f.read(1)
            f.seek(idx)
            f.write(bytes([b[0] ^ (1 << (bit % 8))]))

    @staticmethod
    def truncate(path: str, keep_bytes: int) -> None:
        """Truncate a committed file — a torn write observed post-hoc."""
        with open(path, "r+b") as f:  # in-place corruption, not an artifact write  # graftcheck: ignore[non-atomic-artifact-write]
            f.truncate(keep_bytes)

    # -- hooks (called by io/checkpoint primitives) ----------------------------

    def _next(self, op: str, path: str) -> Optional[Dict[str, Any]]:
        """Find-and-consume the fault due at this (op, path), if any."""
        with self._lock:
            self._op_counter += 1
            if self.record_ops:
                self.ops.append((op, path))
            for fault in list(self._faults):
                if fault["kind"] == "crash_at_op":
                    if self._op_counter - 1 == fault["op_index"]:
                        self._faults.remove(fault)
                        return fault
                    continue
                if fault["op"] != op or fault["match"] not in path:
                    continue
                if fault["kind"] == "slow":
                    return fault  # persistent, never consumed
                fault["seen"] += 1
                if fault["seen"] == fault["nth"]:
                    self._faults.remove(fault)
                    return fault
            return None

    def on_write(self, path: str, data: bytes) -> None:
        fault = self._next("write", path)
        if fault is None:
            return
        kind = fault["kind"]
        if kind == "crash_at_op" or kind == "crash":
            log.info("storage_fault", fault="crash_before_write", path=path)
            raise InjectedCrash(f"crash before write {path}")
        if kind == "torn":
            with open(path, "wb") as f:  # deliberately torn: the fault under test  # graftcheck: ignore[non-atomic-artifact-write]
                f.write(data[: fault["at_byte"]])
                f.flush()
                os.fsync(f.fileno())
            log.info("storage_fault", fault="torn_write", path=path,
                     at_byte=fault["at_byte"])
            raise InjectedCrash(f"torn write {path}@{fault['at_byte']}")
        if kind == "enospc":
            with open(path, "wb") as f:  # deliberately partial: ENOSPC under test  # graftcheck: ignore[non-atomic-artifact-write]
                f.write(data[: max(0, len(data) // 2)])
            raise OSError(errno.ENOSPC, os.strerror(errno.ENOSPC), path)

    def on_fsync(self, path: str) -> None:
        fault = self._next("fsync", path)
        if fault is None:
            return
        if fault["kind"] == "slow":
            time.sleep(fault["delay_s"])
            return
        log.info("storage_fault", fault="crash_at_fsync", path=path)
        raise InjectedCrash(f"crash at fsync {path}")

    def on_replace(self, src: str, dst: str,
                   do_replace: Callable[[str, str], None]) -> None:
        fault = self._next("replace", dst)
        if fault is None:
            do_replace(src, dst)
            return
        kind = fault["kind"]
        if kind in ("crash_before", "crash_at_op", "crash"):
            log.info("storage_fault", fault="crash_before_rename",
                     src=src, dst=dst)
            raise InjectedCrash(f"crash before rename {dst}")
        do_replace(src, dst)
        log.info("storage_fault", fault="crash_after_rename",
                 src=src, dst=dst)
        raise InjectedCrash(f"crash after rename {dst}")


@contextlib.contextmanager
def installed(inj: StorageFaultInjector) -> Iterator[StorageFaultInjector]:
    """Install `inj` process-wide for code that builds its own stores
    (`TPULearner.fit`, the GBDT trainer); always uninstalled on exit."""
    from mmlspark_tpu.io import checkpoint as _ckpt

    _ckpt.set_global_fault_injector(inj)
    try:
        yield inj
    finally:
        _ckpt.set_global_fault_injector(None)
