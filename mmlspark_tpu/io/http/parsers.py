"""Input/output parsers flanking HTTPTransformer in SimpleHTTPTransformer.

Reference: src/io/http/src/main/scala/Parsers.scala — JSONInputParser
(:31-83, row -> POST HTTPRequestData with JSON entity), CustomInputParser
(:87-135, arbitrary row->request function), JSONOutputParser (:139-191,
response entity -> parsed JSON), StringOutputParser (:195-210),
CustomOutputParser (:214-270).

JSON typing note: the reference parses into a user-supplied Spark DataType;
this build parses into native Python objects (dict -> STRUCT column,
list -> ARRAY) — schema-on-read, checked downstream, which is the idiomatic
shape for a Python-native data plane.
"""

from __future__ import annotations

import json
from typing import Any, Callable, List, Optional

from mmlspark_tpu.core.dataframe import DataFrame, DataType, Field
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasInputCol,
    HasOutputCol,
    Param,
    TypeConverters,
    Wrappable,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.io.http.schema import (
    HTTPRequestData,
    HTTPResponseData,
    entity_to_string,
)


class HTTPInputParser(Transformer, HasInputCol, HasOutputCol):
    """Base: emits an HTTPRequestData column (Parsers.scala:21-26)."""

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.STRUCT)]


class JSONInputParser(HTTPInputParser, Wrappable):
    """Row value -> JSON POST request (Parsers.scala:31-83). Scalars wrap as
    {input_col: value}; dicts/lists serialize as-is."""

    url = Param("url", "Url of the service", TypeConverters.to_string)
    method = Param("method", "HTTP method (PUT, POST, PATCH)", TypeConverters.to_string)
    headers = Param("headers", "Extra request headers", TypeConverters.to_dict)

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None,
                 url: Optional[str] = None, **kwargs: Any):
        super().__init__()
        self._set_defaults(headers={}, method="POST")
        if input_col:
            self.set_input_col(input_col)
        if output_col:
            self.set_output_col(output_col)
        if url:
            self.set(self.url, url)
        self.set_params(**kwargs)

    def set_url(self, v: str) -> "JSONInputParser":
        return self.set(self.url, v)

    def transform(self, df: DataFrame) -> DataFrame:
        url = self.get(self.url)
        method = self.get(self.method)
        headers = self.get(self.headers)
        in_name = self.get(self.input_col)
        values = df.column(in_name).values
        requests = []
        for v in values:
            if isinstance(v, (dict, list)):
                body = json.dumps(v)
            else:
                body = json.dumps({in_name: _jsonable(v)})
            requests.append(HTTPRequestData.post_json(url, body, headers, method))
        import numpy as np

        arr = np.empty(len(requests), object)
        arr[:] = requests
        return df.with_column(self.get(self.output_col), arr, DataType.STRUCT)


def _jsonable(v: Any) -> Any:
    import numpy as np

    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


class CustomInputParser(HTTPInputParser, Wrappable):
    """Arbitrary row -> HTTPRequestData function (Parsers.scala:87-135)."""

    udf = ComplexParam("udf", "Function mapping an input value to HTTPRequestData")

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None,
                 udf: Optional[Callable[[Any], HTTPRequestData]] = None):
        super().__init__()
        if input_col:
            self.set_input_col(input_col)
        if output_col:
            self.set_output_col(output_col)
        if udf is not None:
            self.set(self.udf, udf)

    def set_udf(self, f: Callable[[Any], HTTPRequestData]) -> "CustomInputParser":
        return self.set(self.udf, f)

    def transform(self, df: DataFrame) -> DataFrame:
        import numpy as np

        f = self.get(self.udf)
        values = df.column(self.get(self.input_col)).values
        out = np.empty(len(values), object)
        out[:] = [f(v) for v in values]
        return df.with_column(self.get(self.output_col), out, DataType.STRUCT)


class HTTPOutputParser(Transformer, HasInputCol, HasOutputCol):
    """Base: consumes an HTTPResponseData column (Parsers.scala:137-139)."""


class JSONOutputParser(HTTPOutputParser, Wrappable):
    """Response entity -> parsed JSON object per row (Parsers.scala:139-191).
    Null/absent responses parse to None."""

    post_processor = ComplexParam(
        "post_processor", "Optional UDFTransformer applied to the parsed column"
    )

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None):
        super().__init__()
        if input_col:
            self.set_input_col(input_col)
        if output_col:
            self.set_output_col(output_col)

    def set_post_process_func(self, f: Callable[[Any], Any]) -> "JSONOutputParser":
        from mmlspark_tpu.stages.basic import UDFTransformer

        return self.set(self.post_processor, UDFTransformer(udf=f))

    def transform(self, df: DataFrame) -> DataFrame:
        import numpy as np

        values = df.column(self.get(self.input_col)).values
        parsed = []
        for r in values:
            s = entity_to_string(r)
            parsed.append(json.loads(s) if s else None)
        out = np.empty(len(parsed), object)
        out[:] = parsed
        res = df.with_column(self.get(self.output_col), out, DataType.STRUCT)
        pp = self.get_or_default(self.post_processor)
        if pp is not None:
            pp.set_input_col(self.get(self.output_col))
            pp.set_output_col(self.get(self.output_col))
            res = pp.transform(res)
        return res

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.STRUCT)]


class StringOutputParser(HTTPOutputParser, Wrappable):
    """Response entity -> utf-8 string per row (Parsers.scala:195-210)."""

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None):
        super().__init__()
        if input_col:
            self.set_input_col(input_col)
        if output_col:
            self.set_output_col(output_col)

    def transform(self, df: DataFrame) -> DataFrame:
        import numpy as np

        values = df.column(self.get(self.input_col)).values
        out = np.empty(len(values), object)
        out[:] = [entity_to_string(r) for r in values]
        return df.with_column(self.get(self.output_col), out, DataType.STRING)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.STRING)]


class CustomOutputParser(HTTPOutputParser, Wrappable):
    """Arbitrary HTTPResponseData -> value function (Parsers.scala:214-270)."""

    udf = ComplexParam("udf", "Function mapping HTTPResponseData to an output value")

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None,
                 udf: Optional[Callable[[Optional[HTTPResponseData]], Any]] = None):
        super().__init__()
        if input_col:
            self.set_input_col(input_col)
        if output_col:
            self.set_output_col(output_col)
        if udf is not None:
            self.set(self.udf, udf)

    def set_udf(self, f: Callable[[Optional[HTTPResponseData]], Any]) -> "CustomOutputParser":
        return self.set(self.udf, f)

    def transform(self, df: DataFrame) -> DataFrame:
        import numpy as np

        f = self.get(self.udf)
        values = df.column(self.get(self.input_col)).values
        out = np.empty(len(values), object)
        out[:] = [f(r) for r in values]
        return df.with_column(self.get(self.output_col), out, DataType.STRUCT)
