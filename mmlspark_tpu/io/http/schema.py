"""HTTP request/response data model — the wire schema of "HTTP on Spark".

TPU-native redesign of the reference's case-class HTTP schemas
(src/io/http/src/main/scala/HTTPSchema.scala:25-204: HeaderData, EntityData,
StatusLineData, ProtocolVersionData, RequestLineData, HTTPRequestData,
HTTPResponseData — all SparkBindings codecs). Here they are plain frozen-ish
dataclasses carried as object rows in STRUCT columns; `to_dict`/`from_dict`
give the Row-shaped view the reference encodes, so JSON round-trips and the
serving wire format match.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class HeaderData:
    name: str
    value: str

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "value": self.value}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "HeaderData":
        return HeaderData(d["name"], d["value"])


@dataclasses.dataclass
class EntityData:
    """Message body. `content` is raw bytes (DataType.BINARY semantics)."""

    content: bytes = b""
    content_encoding: Optional[HeaderData] = None
    content_length: Optional[int] = None
    content_type: Optional[HeaderData] = None
    is_chunked: bool = False
    is_repeatable: bool = True
    is_streaming: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "content": self.content,
            "contentEncoding": self.content_encoding.to_dict() if self.content_encoding else None,
            "contentLength": self.content_length,
            "contentType": self.content_type.to_dict() if self.content_type else None,
            "isChunked": self.is_chunked,
            "isRepeatable": self.is_repeatable,
            "isStreaming": self.is_streaming,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "EntityData":
        return EntityData(
            content=d.get("content", b""),
            content_encoding=HeaderData.from_dict(d["contentEncoding"]) if d.get("contentEncoding") else None,
            content_length=d.get("contentLength"),
            content_type=HeaderData.from_dict(d["contentType"]) if d.get("contentType") else None,
            is_chunked=bool(d.get("isChunked", False)),
            is_repeatable=bool(d.get("isRepeatable", True)),
            is_streaming=bool(d.get("isStreaming", False)),
        )

    @property
    def string_content(self) -> str:
        return self.content.decode("utf-8") if self.content else ""


@dataclasses.dataclass
class ProtocolVersionData:
    protocol: str = "HTTP"
    major: int = 1
    minor: int = 1

    def to_dict(self) -> Dict[str, Any]:
        return {"protocol": self.protocol, "major": self.major, "minor": self.minor}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ProtocolVersionData":
        return ProtocolVersionData(d.get("protocol", "HTTP"), d.get("major", 1), d.get("minor", 1))


@dataclasses.dataclass
class StatusLineData:
    protocol_version: ProtocolVersionData
    status_code: int
    reason_phrase: str

    def to_dict(self) -> Dict[str, Any]:
        return {
            "protocolVersion": self.protocol_version.to_dict(),
            "statusCode": self.status_code,
            "reasonPhrase": self.reason_phrase,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "StatusLineData":
        return StatusLineData(
            ProtocolVersionData.from_dict(d.get("protocolVersion", {})),
            d["statusCode"],
            d.get("reasonPhrase", ""),
        )


@dataclasses.dataclass
class RequestLineData:
    method: str
    uri: str
    protocol_version: ProtocolVersionData = dataclasses.field(default_factory=ProtocolVersionData)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "method": self.method,
            "uri": self.uri,
            "protocolVersion": self.protocol_version.to_dict(),
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "RequestLineData":
        return RequestLineData(
            d["method"], d["uri"],
            ProtocolVersionData.from_dict(d.get("protocolVersion", {})),
        )


@dataclasses.dataclass
class HTTPRequestData:
    request_line: RequestLineData
    headers: List[HeaderData] = dataclasses.field(default_factory=list)
    entity: Optional[EntityData] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "requestLine": self.request_line.to_dict(),
            "headers": [h.to_dict() for h in self.headers],
            "entity": self.entity.to_dict() if self.entity else None,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "HTTPRequestData":
        return HTTPRequestData(
            RequestLineData.from_dict(d["requestLine"]),
            [HeaderData.from_dict(h) for h in d.get("headers", [])],
            EntityData.from_dict(d["entity"]) if d.get("entity") else None,
        )

    @staticmethod
    def get(url: str, headers: Optional[Dict[str, str]] = None) -> "HTTPRequestData":
        """Body-less GET (index/existence probes, e.g. azure_search)."""
        hs = [HeaderData(k, v) for k, v in (headers or {}).items()]
        return HTTPRequestData(RequestLineData("GET", url), hs, None)

    @staticmethod
    def post_json(url: str, body: str, headers: Optional[Dict[str, str]] = None,
                  method: str = "POST") -> "HTTPRequestData":
        """The JSONInputParser product: method+url+JSON entity
        (reference: Parsers.scala JSONInputParser.transform)."""
        hs = [HeaderData(k, v) for k, v in (headers or {}).items()]
        if not any(h.name.lower() == "content-type" for h in hs):
            hs.append(HeaderData("Content-type", "application/json"))
        data = body.encode("utf-8")
        return HTTPRequestData(
            RequestLineData(method, url),
            hs,
            EntityData(
                content=data,
                content_length=len(data),
                content_type=HeaderData("Content-type", "application/json"),
            ),
        )


@dataclasses.dataclass
class HTTPResponseData:
    headers: List[HeaderData]
    entity: Optional[EntityData]
    status_line: StatusLineData
    locale: str = "en"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "headers": [h.to_dict() for h in self.headers],
            "entity": self.entity.to_dict() if self.entity else None,
            "statusLine": self.status_line.to_dict(),
            "locale": self.locale,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "HTTPResponseData":
        return HTTPResponseData(
            [HeaderData.from_dict(h) for h in d.get("headers", [])],
            EntityData.from_dict(d["entity"]) if d.get("entity") else None,
            StatusLineData.from_dict(d["statusLine"]),
            d.get("locale", "en"),
        )

    @staticmethod
    def ok(content: bytes, content_type: str = "application/json") -> "HTTPResponseData":
        return HTTPResponseData(
            headers=[],
            entity=EntityData(
                content=content,
                content_length=len(content),
                content_type=HeaderData("Content-type", content_type),
            ),
            status_line=StatusLineData(ProtocolVersionData(), 200, "OK"),
        )


def entity_to_string(response: Optional[HTTPResponseData]) -> Optional[str]:
    """HTTPSchema.entity_to_string equivalent (HTTPSchema.scala)."""
    if response is None or response.entity is None:
        return None
    return response.entity.string_content
