"""HTTPTransformer and SimpleHTTPTransformer — "HTTP on Spark" client stages.

Reference: src/io/http/src/main/scala/HTTPTransformer.scala:78-128 (request
column -> pooled/async calls -> response column) and
SimpleHTTPTransformer.scala (mini-batch -> input parser -> HTTPTransformer ->
error split -> output parser -> drop -> flatten, assembled as an internal
PipelineModel).

TPU-framework notes: a partition maps to a worker's row range; the client
pool is a per-stage singleton (the reference's SharedVariable per-JVM
clientHolder), so concurrent transforms reuse keep-alive connections.
"""

from __future__ import annotations

import json
from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, DataType, Field
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasInputCol,
    HasOutputCol,
    Param,
    Params,
    TypeConverters,
    Wrappable,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import find_unused_column_name
from mmlspark_tpu.io.http.clients import (
    AsyncHTTPClient,
    SingleThreadedHTTPClient,
    advanced_handler,
)
from mmlspark_tpu.io.http.parsers import (
    HTTPInputParser,
    HTTPOutputParser,
    JSONInputParser,
    JSONOutputParser,
)
from mmlspark_tpu.io.http.schema import HTTPResponseData, entity_to_string


class HTTPParams(Params):
    """Shared client knobs (HTTPTransformer.scala HTTPParams trait)."""

    concurrency = Param(
        "concurrency", "Max number of concurrent calls", TypeConverters.to_int
    )
    timeout = Param(
        "timeout", "Seconds to wait before closing the connection", TypeConverters.to_float
    )
    concurrent_timeout = Param(
        "concurrent_timeout",
        "Max seconds to wait on a future if concurrency > 1",
        TypeConverters.to_float,
    )
    retry_times = Param(
        "retry_times",
        "Backoff schedule in ms between retries (sendWithRetries)",
        TypeConverters.to_list_int,
    )
    handler = ComplexParam(
        "handler", "Override handler fn(client_pool, request) -> response"
    )

    def _http_defaults(self, retry_times: List[int]) -> None:
        self._set_defaults(
            concurrency=1, timeout=60.0, concurrent_timeout=100.0,
            retry_times=retry_times,
        )

    def _make_handler(self):
        if self.is_set(self.handler):
            return self.get(self.handler)
        return advanced_handler(*self.get(self.retry_times))

    def _make_client(self):
        if self.get(self.concurrency) <= 1:
            return SingleThreadedHTTPClient(self._make_handler(), self.get(self.timeout))
        return AsyncHTTPClient(
            self._make_handler(),
            self.get(self.concurrency),
            self.get(self.concurrent_timeout),
            self.get(self.timeout),
        )


class HasErrorCol(Params):
    error_col = Param("error_col", "Column to hold http errors", TypeConverters.to_string)

    def set_error_col(self, v: str):
        return self.set(self.error_col, v)


class HTTPTransformer(Transformer, HTTPParams, HasInputCol, HasOutputCol, Wrappable):
    """HTTPRequestData column -> HTTPResponseData column
    (HTTPTransformer.scala:78-128). None requests map to None responses."""

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None,
                 **kwargs: Any):
        super().__init__()
        self._http_defaults([100, 500, 1000])
        if input_col:
            self.set_input_col(input_col)
        if output_col:
            self.set_output_col(output_col)
        self.set_params(**kwargs)
        self._client = None  # SharedVariable clientHolder role

    def _get_client(self):
        if self._client is None:
            self._client = self._make_client()
        return self._client

    def transform(self, df: DataFrame) -> DataFrame:
        requests = df.column(self.get(self.input_col)).values
        client = self._get_client()
        responses = list(client.send(iter(requests)))
        out = np.empty(len(responses), object)
        out[:] = responses
        return df.with_column(self.get(self.output_col), out, DataType.STRUCT)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.STRUCT)]


def _add_error(resp: Optional[HTTPResponseData]) -> Optional[dict]:
    """ErrorUtils.addError (SimpleHTTPTransformer.scala:32-42): non-200
    responses become {response, status} error rows; 200/None pass clean."""
    if resp is None:
        return None
    if resp.status_line.status_code == 200:
        return None
    return {
        "response": entity_to_string(resp),
        "status": resp.status_line.to_dict(),
    }


class SimpleHTTPTransformer(Transformer, HTTPParams, HasInputCol, HasOutputCol,
                            HasErrorCol, Wrappable):
    """JSON-in -> call -> JSON-out sugar (SimpleHTTPTransformer.scala):
    composes [mini_batcher?] -> input_parser -> HTTPTransformer -> error
    split -> output_parser -> drop temp cols -> [flatten?]."""

    input_parser = ComplexParam("input_parser", "HTTPInputParser for the input column")
    output_parser = ComplexParam("output_parser", "HTTPOutputParser for the output column")
    mini_batcher = ComplexParam("mini_batcher", "Optional MiniBatchTransformer")
    flatten_output_batches = Param(
        "flatten_output_batches", "Whether to flatten output batches",
        TypeConverters.to_boolean,
    )

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None,
                 url: Optional[str] = None, **kwargs: Any):
        super().__init__()
        # error_col stays unset by default (_error_col falls back to
        # "errors"): a None default would not survive its to_string converter
        self._http_defaults([0, 50, 100, 500])
        if input_col:
            self.set_input_col(input_col)
        if output_col:
            self.set_output_col(output_col)
        if url:
            self.set_url(url)
        self.set_params(**kwargs)

    def set_url(self, url: str) -> "SimpleHTTPTransformer":
        parser = self.get_or_default(self.input_parser)
        if parser is None:
            parser = JSONInputParser()
        if not isinstance(parser, JSONInputParser):
            raise ValueError("set_url is only available with a JSONInputParser")
        return self.set(self.input_parser, parser.set_url(url))

    def _error_col(self) -> str:
        return self.get_or_default(self.error_col) or "errors"

    def _pipeline_stages(self, df_columns: List[str]):
        avoid = set(df_columns) | {self.get(self.output_col)}
        parsed_col = find_unused_column_name("parsedInput", avoid)
        unparsed_col = find_unused_column_name("unparsedOutput", avoid)

        input_parser = self.get_or_default(self.input_parser) or JSONInputParser()
        if not isinstance(input_parser, HTTPInputParser):
            raise TypeError("input_parser must be an HTTPInputParser")
        input_parser.set_input_col(self.get(self.input_col))
        input_parser.set_output_col(parsed_col)

        client = HTTPTransformer(input_col=parsed_col, output_col=unparsed_col)
        client.set(client.retry_times, self.get(self.retry_times))
        client.set(client.concurrency, self.get(self.concurrency))
        client.set(client.concurrent_timeout, self.get(self.concurrent_timeout))
        client.set(client.timeout, self.get(self.timeout))
        if self.is_set(self.handler):
            client.set(client.handler, self.get(self.handler))

        output_parser = self.get_or_default(self.output_parser) or JSONOutputParser()
        if not isinstance(output_parser, HTTPOutputParser):
            raise TypeError("output_parser must be an HTTPOutputParser")
        output_parser.set_input_col(unparsed_col)
        output_parser.set_output_col(self.get(self.output_col))

        return parsed_col, unparsed_col, input_parser, client, output_parser

    def transform(self, df: DataFrame) -> DataFrame:
        mb = self.get_or_default(self.mini_batcher)
        if mb is not None:
            df = mb.transform(df)
        (parsed_col, unparsed_col, input_parser, client,
         output_parser) = self._pipeline_stages(df.columns)

        cur = input_parser.transform(df)
        cur = client.transform(cur)
        # error split (ErrorUtils): non-200 -> error col, response nullified
        responses = cur.column(unparsed_col).values
        errors = np.empty(len(responses), object)
        errors[:] = [_add_error(r) for r in responses]
        cleaned = np.empty(len(responses), object)
        cleaned[:] = [
            r if (e is None and r is not None) else None
            for r, e in zip(responses, errors)
        ]
        cur = cur.with_column(self._error_col(), errors, DataType.STRUCT)
        cur = cur.with_column(unparsed_col, cleaned, DataType.STRUCT)
        cur = output_parser.transform(cur)
        cur = cur.drop(parsed_col, unparsed_col)
        if mb is not None and self.get_or_default(self.flatten_output_batches, True) is not False:
            from mmlspark_tpu.stages.batching import FlattenBatch

            cur = FlattenBatch().transform(cur)
        return cur

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [
            Field(self._error_col(), DataType.STRUCT),
            Field(self.get(self.output_col), DataType.STRUCT),
        ]
