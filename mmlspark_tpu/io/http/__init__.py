"""HTTP on Spark — client stages for calling web services from a DataFrame.

Reference module: src/io/http (client half). Server half (Spark Serving)
lives in mmlspark_tpu.serving.
"""

from mmlspark_tpu.io.http.clients import (
    AsyncHTTPClient,
    HTTPClientPool,
    SingleThreadedHTTPClient,
    advanced_handler,
    basic_handler,
    send_with_retries,
)
from mmlspark_tpu.io.http.parsers import (
    CustomInputParser,
    CustomOutputParser,
    HTTPInputParser,
    HTTPOutputParser,
    JSONInputParser,
    JSONOutputParser,
    StringOutputParser,
)
from mmlspark_tpu.io.http.schema import (
    EntityData,
    HeaderData,
    HTTPRequestData,
    HTTPResponseData,
    ProtocolVersionData,
    RequestLineData,
    StatusLineData,
    entity_to_string,
)
from mmlspark_tpu.io.http.transformer import (
    HasErrorCol,
    HTTPParams,
    HTTPTransformer,
    SimpleHTTPTransformer,
)

__all__ = [
    "AsyncHTTPClient",
    "CustomInputParser",
    "CustomOutputParser",
    "EntityData",
    "HasErrorCol",
    "HeaderData",
    "HTTPClientPool",
    "HTTPInputParser",
    "HTTPOutputParser",
    "HTTPParams",
    "HTTPRequestData",
    "HTTPResponseData",
    "HTTPTransformer",
    "JSONInputParser",
    "JSONOutputParser",
    "ProtocolVersionData",
    "RequestLineData",
    "SimpleHTTPTransformer",
    "SingleThreadedHTTPClient",
    "StatusLineData",
    "StringOutputParser",
    "advanced_handler",
    "basic_handler",
    "entity_to_string",
    "send_with_retries",
]
