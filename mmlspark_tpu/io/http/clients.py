"""HTTP clients: retry/backoff handlers + single-threaded and async pools.

Reference behavior being matched (not the JVM machinery):
- `HandlingUtils.sendWithRetries` (HTTPClients.scala:55-134): 200/201/202/400
  succeed immediately; 429 honors Retry-After then retries; other codes retry
  after the next backoff delay; the LAST response is returned when retries
  are exhausted (never an exception for an HTTP-level status).
- `advanced(retryTimes*)` handler = sendWithRetries with a backoff-ms list;
  `basic` = one shot, no retries (HTTPClients.scala:119-134).
- `AsyncHTTPClient` (Clients.scala:102-116): up to `concurrency` requests in
  flight per worker, responses yielded IN ORDER, each future bounded by
  `concurrentTimeout`.

Transport is http.client with per-(scheme,netloc) keep-alive connections in
thread-local pools — the role of the Apache CloseableHttpClient pool, without
the JVM. Connection-level failures retry on the same backoff schedule and
raise after exhaustion (the reference's client.execute throw).
"""

from __future__ import annotations

import http.client
import threading
import time
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, List, Optional, Tuple

from mmlspark_tpu.obs.logging import get_logger
from mmlspark_tpu.io.http.schema import (
    EntityData,
    HeaderData,
    HTTPRequestData,
    HTTPResponseData,
    ProtocolVersionData,
    StatusLineData,
)

log = get_logger("mmlspark_tpu.io.http")

# A handler turns (client, request) into a response — the HandlerFunc contract
HandlerFunc = Callable[["HTTPClientPool", HTTPRequestData], HTTPResponseData]

_SUCCESS_CODES = frozenset({200, 201, 202, 400})


class HTTPClientPool:
    """Thread-local keep-alive connections keyed by (scheme, netloc)."""

    def __init__(self, request_timeout: float = 60.0):
        self.request_timeout = request_timeout
        self._local = threading.local()
        # every connection ever vended, so close() can reach the ones that
        # live in OTHER threads' locals (async workers)
        self._all_conns: List[http.client.HTTPConnection] = []
        self._all_lock = threading.Lock()

    def _connections(self) -> dict:
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = {}
            self._local.conns = conns
        return conns

    def _connect(self, scheme: str, netloc: str) -> http.client.HTTPConnection:
        conns = self._connections()
        conn = conns.get((scheme, netloc))
        if conn is None:
            cls = http.client.HTTPSConnection if scheme == "https" else http.client.HTTPConnection
            conn = cls(netloc, timeout=self.request_timeout)
            conns[(scheme, netloc)] = conn
            with self._all_lock:
                self._all_conns.append(conn)
        return conn

    def execute(self, request: HTTPRequestData) -> HTTPResponseData:
        """One request over a pooled connection -> response data (any status)."""
        url = urllib.parse.urlsplit(request.request_line.uri)
        path = url.path or "/"
        if url.query:
            path += "?" + url.query
        headers = {h.name: h.value for h in request.headers}
        body = request.entity.content if request.entity else None
        conn = self._connect(url.scheme or "http", url.netloc)
        try:
            conn.request(request.request_line.method, path, body=body, headers=headers)
            resp = conn.getresponse()
        except (http.client.HTTPException, ConnectionError, OSError):
            # stale keep-alive or dropped socket: rebuild the connection once
            conn.close()
            self._connections().pop((url.scheme or "http", url.netloc), None)
            conn = self._connect(url.scheme or "http", url.netloc)
            conn.request(request.request_line.method, path, body=body, headers=headers)
            resp = conn.getresponse()
        content = resp.read()
        entity = None
        if content or resp.getheader("Content-Type"):
            ct = resp.getheader("Content-Type")
            entity = EntityData(
                content=content,
                content_length=len(content),
                content_type=HeaderData("Content-Type", ct) if ct else None,
            )
        return HTTPResponseData(
            headers=[HeaderData(k, v) for k, v in resp.getheaders()],
            entity=entity,
            status_line=StatusLineData(
                ProtocolVersionData("HTTP", resp.version // 10, resp.version % 10),
                resp.status,
                resp.reason,
            ),
        )

    def close(self) -> None:
        with self._all_lock:
            conns, self._all_conns = self._all_conns, []
        for conn in conns:
            conn.close()
        self._local.conns = {}


def send_with_retries(
    client: HTTPClientPool,
    request: HTTPRequestData,
    retries_ms: Tuple[int, ...],
) -> HTTPResponseData:
    """sendWithRetries semantics (HTTPClients.scala:55-108)."""
    last_exc: Optional[Exception] = None
    response: Optional[HTTPResponseData] = None
    for attempt in range(len(retries_ms) + 1):
        try:
            response = client.execute(request)
            last_exc = None
        except (http.client.HTTPException, ConnectionError, OSError) as e:
            last_exc = e
            response = None
        if response is not None:
            code = response.status_line.status_code
            if code in _SUCCESS_CODES:
                return response
            if code == 429:
                retry_after = next(
                    (h.value for h in response.headers if h.name.lower() == "retry-after"),
                    None,
                )
                delay = _parse_retry_after(retry_after)
                if delay is not None:
                    log.info("http_rate_limited", wait_s=round(delay, 1),
                             uri=request.request_line.uri)
                    time.sleep(delay)
                # 429 retries without consuming extra backoff beyond the schedule
            else:
                log.warning(
                    "http_error_response", code=code,
                    reason=response.status_line.reason_phrase,
                    uri=request.request_line.uri,
                )
        if attempt < len(retries_ms):
            time.sleep(retries_ms[attempt] / 1000.0)
    if response is None:
        assert last_exc is not None
        raise last_exc
    return response


def _parse_retry_after(value: Optional[str]) -> Optional[float]:
    """Retry-After is delta-seconds OR an HTTP-date (RFC 7231 §7.1.3)."""
    if value is None:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        import email.utils

        dt = email.utils.parsedate_to_datetime(value)
        import datetime

        return max(0.0, (dt - datetime.datetime.now(datetime.timezone.utc)).total_seconds())
    except (TypeError, ValueError):
        return None


def advanced_handler(*retries_ms: int) -> HandlerFunc:
    """HandlingUtils.advanced(retryTimes*) — retrying handler factory."""

    def handle(client: HTTPClientPool, request: HTTPRequestData) -> HTTPResponseData:
        return send_with_retries(client, request, tuple(retries_ms))

    handle.retries_ms = tuple(retries_ms)  # introspectable for persistence
    return handle


def basic_handler(client: HTTPClientPool, request: HTTPRequestData) -> HTTPResponseData:
    """HandlingUtils.basic — one shot, no retries."""
    return client.execute(request)


class SingleThreadedHTTPClient:
    """In-order, one-at-a-time sender (SingleThreadedClient mixin role)."""

    def __init__(self, handler: HandlerFunc, request_timeout: float):
        self.handler = handler
        self.pool = HTTPClientPool(request_timeout)

    def send(
        self, requests: Iterable[Optional[HTTPRequestData]]
    ) -> Iterator[Optional[HTTPResponseData]]:
        for req in requests:
            yield self.handler(self.pool, req) if req is not None else None

    def close(self) -> None:
        self.pool.close()


class AsyncHTTPClient:
    """Bounded-window concurrent sender preserving input order
    (AsyncClient.sendRequestsWithContext, Clients.scala:102-116)."""

    def __init__(
        self,
        handler: HandlerFunc,
        concurrency: int,
        concurrent_timeout: float,
        request_timeout: float,
    ):
        self.handler = handler
        self.concurrency = concurrency
        self.concurrent_timeout = concurrent_timeout
        self.pool = HTTPClientPool(request_timeout)
        self._executor = ThreadPoolExecutor(max_workers=concurrency)

    def send(
        self, requests: Iterable[Optional[HTTPRequestData]]
    ) -> Iterator[Optional[HTTPResponseData]]:
        window: List = []
        for req in requests:
            if req is None:
                window.append(None)
            else:
                window.append(self._executor.submit(self.handler, self.pool, req))
            if len(window) >= self.concurrency:
                head = window.pop(0)
                yield head.result(self.concurrent_timeout) if head is not None else None
        for head in window:
            yield head.result(self.concurrent_timeout) if head is not None else None

    def close(self) -> None:
        self._executor.shutdown(wait=False)
        self.pool.close()
