"""io — file readers producing BINARY / IMAGE DataFrames.

Equivalent of the reference's io/binary + io/image modules (SURVEY.md §2.4):
BinaryFileFormat.scala:34-114 (whole-file rows, zip walking, subsampling),
PatchedImageFileFormat.scala:23 (image reads). The Spark DataSource
registration (`spark.read.binary`) becomes plain functions returning
DataFrames.
"""

from mmlspark_tpu.io.binary import read_binary
from mmlspark_tpu.io.image import read_images

__all__ = ["read_binary", "read_images"]
