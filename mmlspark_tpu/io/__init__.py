"""io — file readers producing BINARY / IMAGE DataFrames.

Equivalent of the reference's io/binary + io/image modules (SURVEY.md §2.4):
BinaryFileFormat.scala:34-114 (whole-file rows, zip walking, subsampling),
PatchedImageFileFormat.scala:23 (image reads). The Spark DataSource
registration (`spark.read.binary`) becomes plain functions returning
DataFrames.
"""

from mmlspark_tpu.io.binary import read_binary
from mmlspark_tpu.io.columnar import (
    ArrayReader,
    ColumnarSource,
    ColumnChunk,
    NumpyShardReader,
    ParquetShardReader,
    ShardReader,
    open_shards,
    write_numpy_shards,
    write_parquet_shards,
)
from mmlspark_tpu.io.checkpoint import (
    Checkpoint,
    CheckpointStore,
    CorruptArtifactError,
    atomic_write_bytes,
    atomic_write_text,
    fsync_tree,
    publish_dir,
)
from mmlspark_tpu.io.image import read_images
from mmlspark_tpu.io.storage_faults import InjectedCrash, StorageFaultInjector

__all__ = [
    "read_binary",
    "read_images",
    "ArrayReader",
    "ColumnChunk",
    "ColumnarSource",
    "NumpyShardReader",
    "ParquetShardReader",
    "ShardReader",
    "open_shards",
    "write_numpy_shards",
    "write_parquet_shards",
    "Checkpoint",
    "CheckpointStore",
    "CorruptArtifactError",
    "InjectedCrash",
    "StorageFaultInjector",
    "atomic_write_bytes",
    "atomic_write_text",
    "fsync_tree",
    "publish_dir",
]
