"""Typed cognitive-style HTTP clients over the io.http tier.

Reference: io/http/src/main/scala/services/CognitiveServiceBase.scala:247-318
(CognitiveServicesBase: url + subscription-key params, an internal
SimpleHTTPTransformer pipeline with typed input/output parsers) and
TextAnalytics.scala (TextSentiment et al. — documents JSON contract).

These clients target any endpoint speaking the service contract (tests run a
local mock; this build has no network egress). The subscription key rides the
Ocp-Apim-Subscription-Key header exactly like the reference.
"""

from __future__ import annotations

import json
from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, Field
from mmlspark_tpu.core.params import Param, TypeConverters, Wrappable
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.io.http.parsers import CustomInputParser, JSONOutputParser
from mmlspark_tpu.io.http.schema import HTTPRequestData
from mmlspark_tpu.io.http.transformer import SimpleHTTPTransformer

_KEY_HEADER = "Ocp-Apim-Subscription-Key"


class CognitiveServiceBase(Transformer, Wrappable):
    """Shared plumbing: url + subscription_key + concurrency; subclasses
    define the request body per row and the response field to surface."""

    url = Param("url", "Url of the cognitive service", TypeConverters.to_string)
    subscription_key = Param(
        "subscription_key", "The API key (Ocp-Apim-Subscription-Key header)",
        TypeConverters.to_string,
    )
    input_col = Param("input_col", "The name of the input column", TypeConverters.to_string)
    output_col = Param("output_col", "The name of the output column", TypeConverters.to_string)
    error_col = Param("error_col", "Column for non-200 responses", TypeConverters.to_string)
    concurrency = Param(
        "concurrency", "Max concurrent in-flight requests", TypeConverters.to_int
    )

    def __init__(self, url: Optional[str] = None,
                 subscription_key: Optional[str] = None,
                 input_col: str = "text", output_col: Optional[str] = None,
                 concurrency: int = 1, **kwargs: Any):
        super().__init__()
        self._set_defaults(
            input_col="text",
            output_col=type(self).__name__ + "_output",
            error_col=type(self).__name__ + "_error",
            concurrency=1,
        )
        if url:
            self.set(self.url, url)
        if subscription_key:
            self.set(self.subscription_key, subscription_key)
        self.set(self.input_col, input_col)
        if output_col:
            self.set(self.output_col, output_col)
        self.set(self.concurrency, concurrency)
        # subclass-declared params (language, granularity, error_col, ...)
        self.set_params(**kwargs)

    def set_url(self, v: str):
        return self.set(self.url, v)

    def set_subscription_key(self, v: str):
        return self.set(self.subscription_key, v)

    # -- subclass contract -----------------------------------------------------

    def make_body(self, value: Any) -> str:
        raise NotImplementedError

    def query_params(self) -> dict:
        """URL query parameters — the reference's isURLParam ServiceParams
        (CognitiveServiceBase.scala prepareUrl). Empty by default."""
        return {}

    def _headers(self) -> dict:
        h = {"Content-Type": "application/json"}
        if self.is_set(self.subscription_key):
            h[_KEY_HEADER] = self.get(self.subscription_key)
        return h

    def _full_url(self, extra: Optional[dict] = None) -> str:
        import urllib.parse

        url = self.get(self.url)
        qp = {k: v for k, v in self.query_params().items() if v is not None}
        qp.update(extra or {})
        if not qp:
            return url
        sep = "&" if "?" in url else "?"
        return url + sep + urllib.parse.urlencode(qp)

    def _make_request(self, value: Any) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        return HTTPRequestData.post_json(
            self._full_url(), self.make_body(value), self._headers()
        )

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [
            Field(self.get(self.output_col), DataType.STRUCT),
            Field(self.get(self.error_col), DataType.STRUCT),
        ]

    def _inner_key(self) -> tuple:
        return (
            self.get(self.input_col), self.get(self.output_col),
            self.get(self.error_col), self.get(self.concurrency),
        )

    def transform(self, df: DataFrame) -> DataFrame:
        # Cache the inner stage across calls: SimpleHTTPTransformer owns the
        # keep-alive client pool (and executor at concurrency>1), so
        # rebuilding it per micro-batch would re-handshake every connection
        key = self._inner_key()
        cached = getattr(self, "_inner_cache", None)
        if cached is None or cached[0] != key:
            inner = SimpleHTTPTransformer(
                input_col=self.get(self.input_col),
                output_col=self.get(self.output_col),
            )
            inner.set(inner.input_parser, CustomInputParser(udf=self._make_request))
            inner.set(inner.output_parser, JSONOutputParser())
            inner.set(inner.error_col, self.get(self.error_col))
            inner.set(inner.concurrency, self.get(self.concurrency))
            self._inner_cache = (key, inner)
        return self._inner_cache[1].transform(df)


class TextAnalyticsBase(CognitiveServiceBase):
    """Documents-contract base for the Text Analytics family
    (TextAnalytics.scala:31 TextAnalyticsBase): body {documents: [{id,
    language?, text}]}, response {documents: [...], errors: [...]}."""

    language = Param("language", "Language of the input text", TypeConverters.to_string)

    #: subclasses without a language field in the contract set this False
    _body_has_language = True

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self._set_defaults(language="en")

    def set_language(self, v: str):
        return self.set(self.language, v)

    def make_body(self, value: Any) -> str:
        doc = {"id": "1", "text": str(value)}
        if self._body_has_language:
            doc["language"] = self.get_or_default(self.language)
        return json.dumps({"documents": [doc]})


class TextSentiment(TextAnalyticsBase):
    """Text -> sentiment score (TextAnalytics.scala:184 TextSentiment):
    response {documents: [{id, score}]}."""


class LanguageDetector(TextAnalyticsBase):
    """Text -> detected languages (TextAnalytics.scala:198 LanguageDetector):
    the request documents carry no language field; response
    {documents: [{id, detectedLanguages: [...]}]}."""

    _body_has_language = False


class EntityDetector(TextAnalyticsBase):
    """Text -> linked entities (TextAnalytics.scala:212 EntityDetector):
    response {documents: [{id, entities: [...]}]}."""


class KeyPhraseExtractor(TextAnalyticsBase):
    """Text -> key phrases (TextAnalytics.scala:248 KeyPhraseExtractor):
    response {documents: [{id, keyPhrases: [...]}]}."""


class NER(TextAnalyticsBase):
    """Text -> named entities (TextAnalytics.scala:226 NER): response
    {documents: [{id, entities: [...]}]}."""


# -- Computer Vision family ----------------------------------------------------


class _ImageServiceBase(CognitiveServiceBase):
    """Vision services take an image by URL: body {"url": <value>}
    (ComputerVision.scala HasImageUrl/HasImageBytes — the URL branch; this
    build's data plane carries paths/URLs, bytes ride the same POST)."""

    def make_body(self, value: Any) -> str:
        if isinstance(value, dict):
            return json.dumps(value)
        return json.dumps({"url": str(value)})


class OCR(_ImageServiceBase):
    """Image -> printed-text regions (ComputerVision.scala:178 OCR):
    query params language + detectOrientation, response {regions: [...]}."""

    language = Param("language", "Language of the text in the image",
                     TypeConverters.to_string)
    detect_orientation = Param(
        "detect_orientation", "Detect image orientation before OCR",
        TypeConverters.to_boolean,
    )

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self._set_defaults(language="unk", detect_orientation=True)

    def query_params(self) -> dict:
        return {
            "language": self.get_or_default(self.language),
            "detectOrientation": str(
                self.get_or_default(self.detect_orientation)
            ).lower(),
        }


class AnalyzeImage(_ImageServiceBase):
    """Image -> visual-feature analysis (ComputerVision.scala:302
    AnalyzeImage): query params visualFeatures/details/language, response
    {categories, tags, description, ...}."""

    visual_features = Param(
        "visual_features", "Visual feature types to return (comma-joined)",
        TypeConverters.to_list_string,
    )
    details = Param("details", "Domain-specific details to return",
                    TypeConverters.to_list_string)
    language = Param("language", "Language of the response",
                     TypeConverters.to_string)

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self._set_defaults(
            visual_features=["Categories"], details=[], language="en"
        )

    def query_params(self) -> dict:
        feats = self.get_or_default(self.visual_features)
        details = self.get_or_default(self.details)
        return {
            "visualFeatures": ",".join(feats) if feats else None,
            "details": ",".join(details) if details else None,
            "language": self.get_or_default(self.language),
        }


class GenerateThumbnails(_ImageServiceBase):
    """Image -> thumbnail bytes (ComputerVision.scala:282
    GenerateThumbnails): query params width/height/smartCropping."""

    width = Param("width", "Thumbnail width in pixels", TypeConverters.to_int)
    height = Param("height", "Thumbnail height in pixels", TypeConverters.to_int)
    smart_cropping = Param("smart_cropping", "Intelligently crop the image",
                           TypeConverters.to_boolean)

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self._set_defaults(width=64, height=64, smart_cropping=True)

    def query_params(self) -> dict:
        return {
            "width": self.get_or_default(self.width),
            "height": self.get_or_default(self.height),
            "smartCropping": str(
                self.get_or_default(self.smart_cropping)
            ).lower(),
        }


# -- Face family ---------------------------------------------------------------


class DetectFace(_ImageServiceBase):
    """Image -> detected faces (Face.scala:19 DetectFace): query params
    returnFaceId / returnFaceLandmarks / returnFaceAttributes, response a
    list of {faceId, faceRectangle, faceAttributes?}."""

    return_face_id = Param("return_face_id", "Return faceIds of detected faces",
                           TypeConverters.to_boolean)
    return_face_landmarks = Param(
        "return_face_landmarks", "Return face landmarks", TypeConverters.to_boolean
    )
    return_face_attributes = Param(
        "return_face_attributes",
        "Face attributes to return (age, gender, ... comma-joined)",
        TypeConverters.to_list_string,
    )

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self._set_defaults(
            return_face_id=True, return_face_landmarks=False,
            return_face_attributes=[],
        )

    def query_params(self) -> dict:
        attrs = self.get_or_default(self.return_face_attributes)
        return {
            "returnFaceId": str(self.get_or_default(self.return_face_id)).lower(),
            "returnFaceLandmarks": str(
                self.get_or_default(self.return_face_landmarks)
            ).lower(),
            "returnFaceAttributes": ",".join(attrs) if attrs else None,
        }


class BingImageSearch(CognitiveServiceBase):
    """Search query -> image results (ImageSearch.scala:63 BingImageSearch):
    GET with q/count/offset/mkt/imageType query params, response
    {value: [{contentUrl, ...}]}. The input column holds the query string."""

    count = Param("count", "Number of images to return", TypeConverters.to_int)
    offset = Param("offset", "Zero-based result offset", TypeConverters.to_int)
    market = Param("market", "Result market, e.g. en-US", TypeConverters.to_string)
    image_type = Param("image_type", "Filter by image type (Photo, ...)",
                       TypeConverters.to_string)

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        # image_type stays unset by default (get_or_default -> None): a None
        # default would not survive its to_string converter
        self._set_defaults(count=10, offset=0, market="en-US")

    def query_params(self) -> dict:
        return {
            "count": self.get_or_default(self.count),
            "offset": self.get_or_default(self.offset),
            "mkt": self.get_or_default(self.market),
            "imageType": self.get_or_default(self.image_type),
        }

    def make_body(self, value: Any) -> str:  # unused for GET
        return ""

    def _make_request(self, value: Any) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        return HTTPRequestData.get(
            self._full_url(extra={"q": str(value)}), self._headers()
        )

    @staticmethod
    def content_urls(response: Any) -> List[str]:
        """Extract contentUrl list from a search response (the reference's
        downloadFromUrls companion pipeline starts here)."""
        if not isinstance(response, dict):
            return []
        return [
            v["contentUrl"] for v in response.get("value", [])
            if isinstance(v, dict) and "contentUrl" in v
        ]


class VerifyFaces(CognitiveServiceBase):
    """Two face ids -> same-person verdict (Face.scala VerifyFaces): the
    input column holds a (faceId1, faceId2) pair (list/tuple/dict); body
    {faceId1, faceId2}, response {isIdentical, confidence}."""

    def make_body(self, value: Any) -> str:
        if isinstance(value, dict):
            return json.dumps(
                {"faceId1": value["faceId1"], "faceId2": value["faceId2"]}
            )
        pair = list(value)
        if len(pair) != 2:
            raise ValueError(
                f"VerifyFaces input must be a (faceId1, faceId2) pair, got "
                f"{value!r}"
            )
        return json.dumps({"faceId1": str(pair[0]), "faceId2": str(pair[1])})


class AnomalyDetector(CognitiveServiceBase):
    """Series -> anomaly verdicts (AnomalyDetection.scala contract): body
    {series: [{timestamp, value}...], granularity}, one request per row."""

    granularity = Param(
        "granularity", "Series granularity (hourly, daily, ...)",
        TypeConverters.to_string,
    )

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self._set_defaults(granularity="daily")

    def make_body(self, value: Any) -> str:
        series = value
        if isinstance(series, np.ndarray):
            series = series.tolist()
        return json.dumps(
            {"series": series, "granularity": self.get_or_default(self.granularity)}
        )
