"""Typed cognitive-style HTTP clients over the io.http tier.

Reference: io/http/src/main/scala/services/CognitiveServiceBase.scala:247-318
(CognitiveServicesBase: url + subscription-key params, an internal
SimpleHTTPTransformer pipeline with typed input/output parsers) and
TextAnalytics.scala (TextSentiment et al. — documents JSON contract).

These clients target any endpoint speaking the service contract (tests run a
local mock; this build has no network egress). The subscription key rides the
Ocp-Apim-Subscription-Key header exactly like the reference.
"""

from __future__ import annotations

import json
from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, Field
from mmlspark_tpu.core.params import Param, TypeConverters, Wrappable
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.io.http.parsers import CustomInputParser, JSONOutputParser
from mmlspark_tpu.io.http.schema import HTTPRequestData
from mmlspark_tpu.io.http.transformer import SimpleHTTPTransformer

_KEY_HEADER = "Ocp-Apim-Subscription-Key"


class CognitiveServiceBase(Transformer, Wrappable):
    """Shared plumbing: url + subscription_key + concurrency; subclasses
    define the request body per row and the response field to surface."""

    url = Param("url", "Url of the cognitive service", TypeConverters.to_string)
    subscription_key = Param(
        "subscription_key", "The API key (Ocp-Apim-Subscription-Key header)",
        TypeConverters.to_string,
    )
    input_col = Param("input_col", "The name of the input column", TypeConverters.to_string)
    output_col = Param("output_col", "The name of the output column", TypeConverters.to_string)
    error_col = Param("error_col", "Column for non-200 responses", TypeConverters.to_string)
    concurrency = Param(
        "concurrency", "Max concurrent in-flight requests", TypeConverters.to_int
    )

    def __init__(self, url: Optional[str] = None,
                 subscription_key: Optional[str] = None,
                 input_col: str = "text", output_col: Optional[str] = None,
                 concurrency: int = 1, **kwargs: Any):
        super().__init__()
        self._set_defaults(
            input_col="text",
            output_col=type(self).__name__ + "_output",
            error_col=type(self).__name__ + "_error",
            concurrency=1,
        )
        if url:
            self.set(self.url, url)
        if subscription_key:
            self.set(self.subscription_key, subscription_key)
        self.set(self.input_col, input_col)
        if output_col:
            self.set(self.output_col, output_col)
        self.set(self.concurrency, concurrency)
        # subclass-declared params (language, granularity, error_col, ...)
        self.set_params(**kwargs)

    def set_url(self, v: str):
        return self.set(self.url, v)

    def set_subscription_key(self, v: str):
        return self.set(self.subscription_key, v)

    # -- subclass contract -----------------------------------------------------

    def make_body(self, value: Any) -> str:
        raise NotImplementedError

    def _headers(self) -> dict:
        h = {"Content-Type": "application/json"}
        if self.is_set(self.subscription_key):
            h[_KEY_HEADER] = self.get(self.subscription_key)
        return h

    def _make_request(self, value: Any) -> Optional[HTTPRequestData]:
        if value is None:
            return None
        return HTTPRequestData.post_json(
            self.get(self.url), self.make_body(value), self._headers()
        )

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [
            Field(self.get(self.output_col), DataType.STRUCT),
            Field(self.get(self.error_col), DataType.STRUCT),
        ]

    def _inner_key(self) -> tuple:
        return (
            self.get(self.input_col), self.get(self.output_col),
            self.get(self.error_col), self.get(self.concurrency),
        )

    def transform(self, df: DataFrame) -> DataFrame:
        # Cache the inner stage across calls: SimpleHTTPTransformer owns the
        # keep-alive client pool (and executor at concurrency>1), so
        # rebuilding it per micro-batch would re-handshake every connection
        key = self._inner_key()
        cached = getattr(self, "_inner_cache", None)
        if cached is None or cached[0] != key:
            inner = SimpleHTTPTransformer(
                input_col=self.get(self.input_col),
                output_col=self.get(self.output_col),
            )
            inner.set(inner.input_parser, CustomInputParser(udf=self._make_request))
            inner.set(inner.output_parser, JSONOutputParser())
            inner.set(inner.error_col, self.get(self.error_col))
            inner.set(inner.concurrency, self.get(self.concurrency))
            self._inner_cache = (key, inner)
        return self._inner_cache[1].transform(df)


class TextSentiment(CognitiveServiceBase):
    """Text -> sentiment score, Text Analytics v2 documents contract
    (TextAnalytics.scala TextSentiment): body {documents: [{id, language,
    text}]}, response {documents: [{id, score}]}."""

    language = Param("language", "Language of the input text", TypeConverters.to_string)

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self._set_defaults(language="en")

    def set_language(self, v: str):
        return self.set(self.language, v)

    def make_body(self, value: Any) -> str:
        return json.dumps(
            {
                "documents": [
                    {
                        "id": "1",
                        "language": self.get_or_default(self.language),
                        "text": str(value),
                    }
                ]
            }
        )


class AnomalyDetector(CognitiveServiceBase):
    """Series -> anomaly verdicts (AnomalyDetection.scala contract): body
    {series: [{timestamp, value}...], granularity}, one request per row."""

    granularity = Param(
        "granularity", "Series granularity (hourly, daily, ...)",
        TypeConverters.to_string,
    )

    def __init__(self, **kwargs: Any):
        super().__init__(**kwargs)
        self._set_defaults(granularity="daily")

    def make_body(self, value: Any) -> str:
        series = value
        if isinstance(series, np.ndarray):
            series = series.tolist()
        return json.dumps(
            {"series": series, "granularity": self.get_or_default(self.granularity)}
        )
