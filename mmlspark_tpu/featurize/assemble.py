"""Featurize / FastVectorAssembler implementations."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, Field
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasInputCols,
    HasOutputCol,
    Param,
    TypeConverters,
    Wrappable,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.core.schema import CategoricalMap, get_categorical_map, is_image


class FastVectorAssembler(Transformer, HasInputCols, HasOutputCol, Wrappable):
    """Concatenate numeric/vector columns into one VECTOR, writing slot
    names into ml_attr metadata (reference: core/spark FastVectorAssembler —
    which keeps only categorical metadata for speed; slot names here are
    cheap so we keep them all)."""

    def __init__(self, input_cols: Optional[List[str]] = None,
                 output_col: str = "features"):
        super().__init__()
        if input_cols:
            self.set(self.input_cols, input_cols)
        self.set(self.output_col, output_col)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.VECTOR)]

    def transform(self, df: DataFrame) -> DataFrame:
        parts: List[np.ndarray] = []
        names: List[str] = []
        for col_name in self.get(self.input_cols):
            col = df.column(col_name)
            v = col.values
            if v.ndim == 1:
                if v.dtype == object:
                    v = np.array([float(x) for x in v], np.float64)
                parts.append(v.astype(np.float64)[:, None])
                names.append(col_name)
            else:
                parts.append(v.astype(np.float64))
                slot_names = col.metadata.get("ml_attr", {}).get("names")
                if slot_names and len(slot_names) == v.shape[1]:
                    names.extend(slot_names)
                else:
                    names.extend(f"{col_name}_{i}" for i in range(v.shape[1]))
        out = (
            np.concatenate(parts, axis=1)
            if parts
            else np.zeros((len(df), 0), np.float64)
        )
        return df.with_column(
            self.get(self.output_col), out, DataType.VECTOR,
            metadata={"ml_attr": {"names": names}},
        )


class Featurize(Estimator, HasOutputCol, Wrappable):
    """Auto-featurization estimator (Featurize.scala:83-100)."""

    feature_columns = Param(
        "feature_columns", "Input columns to featurize", TypeConverters.to_list_string
    )
    number_of_features = Param(
        "number_of_features", "Hash width for string columns", TypeConverters.to_int
    )
    one_hot_encode_categoricals = Param(
        "one_hot_encode_categoricals", "One-hot categorical columns", TypeConverters.to_boolean
    )
    allow_images = Param("allow_images", "Unroll image columns", TypeConverters.to_boolean)

    def __init__(self, feature_columns: Optional[List[str]] = None,
                 output_col: str = "features", number_of_features: int = 4096,
                 one_hot_encode_categoricals: bool = True, allow_images: bool = False):
        super().__init__()
        if feature_columns:
            self.set(self.feature_columns, feature_columns)
        self.set(self.output_col, output_col)
        self.set(self.number_of_features, number_of_features)
        self.set(self.one_hot_encode_categoricals, one_hot_encode_categoricals)
        self.set(self.allow_images, allow_images)

    def set_feature_columns(self, v: List[str]):
        return self.set(self.feature_columns, v)

    def fit(self, df: DataFrame) -> "FeaturizeModel":
        one_hot = self.get(self.one_hot_encode_categoricals)
        plans: List[Dict[str, Any]] = []
        for name in self.get(self.feature_columns):
            col = df.column(name)
            cmap = get_categorical_map(df, name)
            if cmap is not None:
                plans.append({
                    "col": name,
                    "kind": "onehot" if one_hot else "cat_index",
                    "levels": list(cmap.levels),
                })
            elif col.dtype == DataType.VECTOR:
                plans.append({"col": name, "kind": "vector"})
            elif col.dtype == DataType.BOOLEAN:
                plans.append({"col": name, "kind": "bool"})
            elif col.dtype.is_numeric:
                v = col.values.astype(np.float64)
                finite = v[~np.isnan(v)]
                plans.append({
                    "col": name, "kind": "numeric",
                    "mean": float(finite.mean()) if len(finite) else 0.0,
                })
            elif col.dtype == DataType.TIMESTAMP:
                plans.append({"col": name, "kind": "datetime"})
            elif col.dtype == DataType.STRING:
                values = [v for v in col.values if v is not None]
                uniq = sorted(set(values))
                if one_hot and len(uniq) <= 64:  # low-cardinality: one-hot
                    plans.append({"col": name, "kind": "onehot", "levels": uniq})
                else:
                    plans.append({
                        "col": name, "kind": "hash_string",
                        "width": self.get(self.number_of_features),
                    })
            elif col.dtype == DataType.ARRAY:
                plans.append({
                    "col": name, "kind": "hash_tokens",
                    "width": self.get(self.number_of_features),
                })
            elif is_image(df, name):
                if not self.get(self.allow_images):
                    raise ValueError(
                        f"image column {name!r} requires allow_images=True"
                    )
                plans.append({"col": name, "kind": "image"})
            else:
                raise TypeError(
                    f"cannot featurize column {name!r} of type {col.dtype.value}"
                )
        model = FeaturizeModel(plans)
        model.set(model.output_col, self.get(self.output_col))
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.VECTOR)]


class FeaturizeModel(Model, HasOutputCol, Wrappable):
    """Fitted Featurize: applies per-column plans (cast/hash/one-hot/dates) and assembles the feature vector."""

    plans = ComplexParam("plans", "Per-column featurization plans")

    def __init__(self, plans: Optional[List[Dict[str, Any]]] = None):
        super().__init__()
        if plans is not None:
            self.set(self.plans, plans)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.VECTOR)]

    def transform(self, df: DataFrame) -> DataFrame:
        from mmlspark_tpu.text.features import _stable_hash

        parts: List[np.ndarray] = []
        names: List[str] = []
        n = len(df)
        for plan in self.get(self.plans):
            name = plan["col"]
            kind = plan["kind"]
            col = df.column(name)
            if kind == "numeric":
                v = col.values.astype(np.float64).copy()
                v[np.isnan(v)] = plan["mean"]
                parts.append(v[:, None])
                names.append(name)
            elif kind == "bool":
                parts.append(col.values.astype(np.float64)[:, None])
                names.append(name)
            elif kind == "vector":
                parts.append(col.values.astype(np.float64))
                names.extend(f"{name}_{i}" for i in range(col.values.shape[1]))
            elif kind in ("onehot", "cat_index"):
                levels = plan["levels"]
                index = {v: i for i, v in enumerate(levels)}
                vals = df._hashable_col(name)
                idx = np.array([index.get(v, -1) for v in vals], np.int64)
                if kind == "cat_index":
                    parts.append(idx.astype(np.float64)[:, None])
                    names.append(name)
                else:
                    oh = np.zeros((n, len(levels)), np.float64)
                    ok = idx >= 0
                    oh[np.nonzero(ok)[0], idx[ok]] = 1.0
                    parts.append(oh)
                    names.extend(f"{name}={lv}" for lv in levels)
            elif kind == "datetime":
                ts = col.values.astype("datetime64[us]")
                import datetime

                feats = np.zeros((n, 6), np.float64)
                for i, t in enumerate(ts):
                    dt = t.astype(datetime.datetime)
                    feats[i] = [dt.year, dt.month, dt.day, dt.weekday(), dt.hour, dt.minute]
                parts.append(feats)
                names.extend(f"{name}_{p}" for p in ("year", "month", "day", "weekday", "hour", "minute"))
            elif kind == "hash_string":
                width = plan["width"]
                out = np.zeros((n, width), np.float64)
                for i, v in enumerate(col.values):
                    for tok in str(v).lower().split():
                        out[i, _stable_hash(tok, width)] += 1.0
                parts.append(out)
                names.extend(f"{name}_hash{i}" for i in range(width))
            elif kind == "hash_tokens":
                width = plan["width"]
                out = np.zeros((n, width), np.float64)
                for i, tokens in enumerate(col.values):
                    for tok in tokens:
                        out[i, _stable_hash(str(tok), width)] += 1.0
                parts.append(out)
                names.extend(f"{name}_hash{i}" for i in range(width))
            elif kind == "image":
                rows = []
                for r in col.values:
                    data = np.asarray(r["data"])
                    if data.ndim == 2:  # grayscale: promote to HWC like UnrollImage
                        data = data[:, :, None]
                    rows.append(np.transpose(data, (2, 0, 1)).reshape(-1))
                arr = np.stack(rows).astype(np.float64)
                parts.append(arr)
                names.extend(f"{name}_px{i}" for i in range(arr.shape[1]))
            else:
                raise ValueError(f"unknown plan kind {kind!r}")
        out = (
            np.concatenate(parts, axis=1) if parts else np.zeros((n, 0), np.float64)
        )
        return df.with_column(
            self.get(self.output_col), out, DataType.VECTOR,
            metadata={"ml_attr": {"names": names}},
        )
