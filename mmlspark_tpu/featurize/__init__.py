"""featurize — automatic feature assembly.

Equivalent of the reference's featurize module (SURVEY.md §2.3):
Featurize.fit (Featurize.scala:83-100) + AssembleFeatures.scala +
core/spark FastVectorAssembler. Per-type handling mirrors the reference:
numerics cast to double (mean-imputed), booleans 0/1, categorical metadata
one-hot, plain strings tokenized+hashed, timestamps decomposed, token
arrays hashed, vectors passed through, images unrolled — then assembled
into one dense VECTOR column with slot-name metadata.

Dense width default is the reference's tree/NN setting
(numFeaturesTreeOrNNBased = 4096, Featurize.scala:13-19) — the 2^18 sparse
default has no dense-tensor analog worth materializing.
"""

from mmlspark_tpu.featurize.assemble import (
    Featurize,
    FeaturizeModel,
    FastVectorAssembler,
)

__all__ = ["FastVectorAssembler", "Featurize", "FeaturizeModel"]
