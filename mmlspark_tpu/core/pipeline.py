"""Estimator / Transformer / Pipeline — the product API surface.

Keeps the SparkML pipeline contract the reference extends (SURVEY.md §1 L4:
"learners expose standard SparkML Estimator[M]/Model/Transformer classes") so
users of the reference can switch frameworks without relearning:

    model = Pipeline(stages=[featurize, classifier]).fit(df)
    scored = model.transform(df)

Persistence follows the reference's constructor-based scheme
(src/core/serialize/src/main/scala/ConstructorWriter.scala): simple params as
JSON, complex params via type-dispatched writers (core/serialize.py).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence

from mmlspark_tpu.core.dataframe import DataFrame, Field
from mmlspark_tpu.core.params import ComplexParam, Params, Wrappable


_OBS_HISTS: dict = {}


def _obs_hist(key: str):
    """Process-level pipeline histograms, created once — transform runs
    inside the serving model lock, which must not pay registry lookups
    per batch."""
    if not _OBS_HISTS:
        from mmlspark_tpu.obs.metrics import registry

        reg = registry()
        # single update: a concurrent reader must never observe the dict
        # non-empty but missing a key
        _OBS_HISTS.update({
            "stage": reg.histogram(
                "pipeline_stage_seconds",
                "Wall seconds per pipeline stage transform", ("stage",),
            ),
            "fit": reg.histogram(
                "pipeline_fit_stage_seconds",
                "Wall seconds fitting each pipeline stage", ("stage",),
            ),
        })
    return _OBS_HISTS[key]


class PipelineStage(Params):
    """Base of all pipeline stages."""

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        """Schema-only dry run; default passthrough. Stages override to
        declare output columns so pipelines can be schema-checked pre-fit."""
        return schema

    # -- persistence ----------------------------------------------------------

    def save(self, path: str, overwrite: bool = False) -> None:
        from mmlspark_tpu.core import serialize

        serialize.save_stage(self, path, overwrite=overwrite)

    @classmethod
    def load(cls, path: str) -> "PipelineStage":
        from mmlspark_tpu.core import serialize

        stage = serialize.load_stage(path)
        if cls is not PipelineStage and not isinstance(stage, cls):
            raise TypeError(f"Loaded {type(stage).__name__}, expected {cls.__name__}")
        return stage

    write = save
    read = load


class Transformer(PipelineStage):
    def transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


class Estimator(PipelineStage):
    def fit(self, df: DataFrame) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer produced by an Estimator."""


class Evaluator(Params):
    """Computes a scalar metric over a scored DataFrame."""

    def evaluate(self, df: DataFrame) -> float:
        raise NotImplementedError

    def is_larger_better(self) -> bool:
        return True


class Pipeline(Estimator, Wrappable):
    """Chain of stages; fit() fits estimators in sequence, transforming the
    running DataFrame through each fitted model (SparkML semantics)."""

    stages_param = ComplexParam("stages", "The stages of the pipeline")

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None):
        super().__init__()
        if stages is not None:
            self.set_stages(list(stages))

    def set_stages(self, stages: List[PipelineStage]) -> "Pipeline":
        return self.set(self.stages_param, list(stages))

    def get_stages(self) -> List[PipelineStage]:
        return self.get(self.stages_param)

    def fit(self, df: DataFrame) -> "PipelineModel":
        from mmlspark_tpu.obs import tracer

        fit_hist = _obs_hist("fit")
        fitted: List[Transformer] = []
        current = df
        stages = self.get_stages()
        with tracer().span("pipeline:fit", stages=len(stages)):
            for i, stage in enumerate(stages):
                name = type(stage).__name__
                t0 = time.perf_counter()
                with tracer().span(f"fit:{name}", index=i):
                    if isinstance(stage, Estimator):
                        model = stage.fit(current)
                        fitted.append(model)
                        if i < len(stages) - 1:
                            current = model.transform(current)
                    elif isinstance(stage, Transformer):
                        fitted.append(stage)
                        if i < len(stages) - 1:
                            current = stage.transform(current)
                    else:
                        raise TypeError(
                            f"Pipeline stage {stage!r} is neither Estimator "
                            "nor Transformer"
                        )
                fit_hist.labels(stage=name).observe(time.perf_counter() - t0)
        return PipelineModel(fitted)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        for stage in self.get_stages():
            schema = stage.transform_schema(schema)
        return schema


class PipelineModel(Model, Wrappable):
    stages_param = ComplexParam("stages", "The fitted stages of the pipeline")

    def __init__(self, stages: Optional[Sequence[Transformer]] = None):
        super().__init__()
        #: per-stage dataplane counter deltas from the most recent
        #: transform(): [(stage class name, {h2d/d2h/compile deltas}), ...].
        #: Device-resident chains show zeros at interior stage boundaries —
        #: the measured form of "no host round-trips between device stages"
        #: (docs/dataplane.md; surfaced by bench.py --smoke).
        self.last_stage_dataplane: List[tuple] = []
        if stages is not None:
            self.set(self.stages_param, list(stages))

    def get_stages(self) -> List[Transformer]:
        return self.get(self.stages_param)

    def transform(self, df: DataFrame) -> DataFrame:
        from mmlspark_tpu.obs import tracer
        from mmlspark_tpu.utils.profiling import dataplane_counters

        counters = dataplane_counters()
        stage_hist = _obs_hist("stage")
        stats: List[tuple] = []
        for stage in self.get_stages():
            name = type(stage).__name__
            before = counters.snapshot()
            t0 = time.perf_counter()
            # nests under the active request span in serving (the score
            # stage activates it), so a traced request's tree includes the
            # per-stage breakdown
            with tracer().span(f"stage:{name}") as span:
                df = stage.transform(df)
                delta = counters.delta(before)
                for k, v in delta.items():
                    if v:
                        span.set_attribute(k, v)
            stage_hist.labels(stage=name).observe(time.perf_counter() - t0)
            stats.append((name, delta))
        self.last_stage_dataplane = stats
        return df

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        for stage in self.get_stages():
            schema = stage.transform_schema(schema)
        return schema
