"""Double-buffered host->HBM prefetch for the device dataplane.

Host staging work (JPEG decode, Parquet chunk reads, bin transforms —
anything inherently host-side) runs in a worker pool feeding staged host
payloads; a single pipeline thread uploads each staged payload to device
HBM — uploads are ISSUED one at a time in order (BASELINE.md round 3:
unbounded concurrent device_puts collapse tunnel throughput ~50x) with at
most `depth` transfers unconfirmed in flight, so the producer never waits
on the consumer's dispatched-compute backlog — and parks up to `depth`
device-resident payloads in a bounded queue. The consumer drains the queue
while the next payload stages and uploads behind it, so chunk N+1's h2d
overlaps chunk N's device compute.

Two public faces share ONE pipeline core:

- ``DeviceChunkPrefetcher`` — the generic tier (ISSUE 9): any lazy iterable
  of work units, an optional ``stage_fn``, payloads that may be a single
  ndarray or a tuple/dict of ndarrays (numeric column chunks, binned GBDT
  chunks). No image imports anywhere on this path.
- ``DeviceBatchPrefetcher`` — the image tier (ISSUE 7): a full item list
  chunked by ``batch_size`` with a decode pool, unchanged API.

Overlap is MEASURED, not assumed: every payload records stage/upload/
request timestamps, `summary()` reports the overlap ratio (1 - consumer
wait / producer prep, clamped to [0, 1]) and the count of payloads whose
upload finished before the consumer asked — the gateable evidence for
"prefetch fully overlaps compute" (ROADMAP streaming-ingestion item; the
bench gates in tests/test_bench_smoke.py). Uploads land in the same
profiling.dataplane_counters() every other transfer point reports to, and
the loader exports `dataplane_prefetch_*` registry metrics including the
`dataplane_prefetch_overlap_ratio` gauge and the
`dataplane_prefetch_resident_bytes_peak` device-buffer high-water gauge
(the HBM-footprint-bound evidence: at most ``depth`` chunks ever resident).

Lifecycle: the pipeline thread holds NO strong reference to the public
prefetcher — only to its internal state — and a ``weakref.finalize`` stops
the pipeline when the public object is collected. So a consumer that breaks
out of a bare ``for`` loop and drops the iterator cannot strand a producer
spinning on a full queue pinning device batches; explicit ``close()`` (or
the context manager) remains the deterministic way to release resources
immediately.
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

_SENTINEL = object()


_METRICS: Dict[str, Any] = {}

# all live pipeline states (weak: an abandoned prefetcher must stay
# collectable) plus the peak of the most recently finished loop — the
# resident-peak gauge aggregates over BOTH at scrape time, so two
# concurrently live prefetchers (streamed GBDT + an image pipeline) can
# no longer clobber each other's high-water mark
_LIVE_STATES: "weakref.WeakSet" = weakref.WeakSet()
_STATES_LOCK = threading.Lock()
_LAST_FINISHED_PEAK = 0.0


def _resident_peak_now() -> float:
    with _STATES_LOCK:
        peaks = [s.resident_peak for s in _LIVE_STATES]
    return float(max([_LAST_FINISHED_PEAK] + peaks))


def _metrics() -> Dict[str, Any]:
    """Process-wide prefetch instruments, created on first use (keeps this
    module import-light and obs-optional)."""
    if not _METRICS:
        from mmlspark_tpu.obs.metrics import registry

        reg = registry()
        _METRICS["batches"] = reg.counter(
            "dataplane_prefetch_batches_total",
            "Batches staged through the host->HBM prefetcher")
        _METRICS["overlapped"] = reg.counter(
            "dataplane_prefetch_overlapped_batches_total",
            "Prefetched batches whose upload finished before the consumer "
            "asked for them")
        _METRICS["ratio"] = reg.gauge(
            "dataplane_prefetch_overlap_ratio",
            "1 - consumer wait / producer prep for the most recently "
            "finished prefetch loop (1.0 = prep fully hidden)")
        peak = reg.gauge(
            "dataplane_prefetch_resident_bytes_peak",
            "High-water mark of device bytes parked in prefetch queues: the "
            "max over all LIVE prefetchers and the most recently finished "
            "loop (the depth-bounded HBM footprint of streaming ingestion)")
        peak.set_function(_resident_peak_now)
        _METRICS["resident_peak"] = peak
    return _METRICS


def upload_host_chunk(host: Any, sharding: Any = None) -> Any:
    """Counted host->HBM upload of one staged payload: a single ndarray or
    a tuple/list/dict of ndarrays (each leaf uploaded — and counted in
    dataplane_counters — separately; the device result mirrors the host
    structure). The ONE pipeline-entry transfer of a streamed chunk."""
    import jax

    from mmlspark_tpu.utils.profiling import dataplane_counters

    def put(a):
        a = np.asarray(a)
        dataplane_counters().record_h2d(a.nbytes)
        return (
            jax.device_put(a) if sharding is None
            else jax.device_put(a, sharding)
        )

    if isinstance(host, dict):
        return {k: put(v) for k, v in host.items()}
    if isinstance(host, (tuple, list)):
        return type(host)(put(v) for v in host)
    return put(host)


def payload_nbytes(host: Any) -> int:
    """Host bytes of one staged payload (sum over leaves)."""
    if isinstance(host, dict):
        return sum(np.asarray(v).nbytes for v in host.values())
    if isinstance(host, (tuple, list)):
        return sum(np.asarray(v).nbytes for v in host)
    return np.asarray(host).nbytes


class _PrefetchState:
    """Everything the pipeline thread touches — shared with (but not
    owning) the public prefetcher, so the thread cannot keep an abandoned
    prefetcher alive."""

    def __init__(self, depth: int, ledger_class: str = "prefetch_chunks"):
        self.q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.stop = threading.Event()
        self.error: Optional[BaseException] = None
        self.timeline: List[Dict[str, float]] = []
        self.tl_lock = threading.Lock()
        self.resident_bytes = 0
        self.resident_peak = 0
        # index -> (device label, nbytes) for chunks the device-memory
        # ledger currently counts as resident (uploaded, not yet consumed);
        # once `ledger_released` the pipeline stops adding and any
        # still-producing upload is freed immediately
        self.ledger_entries: Dict[int, Any] = {}
        self.ledger_released = False
        # the resident-byte class (obs/memory.CLASSES) parked chunks are
        # attributed to: "prefetch_chunks" for generic streaming, or
        # "train_batches" when the trainer owns the pipeline
        self.ledger_class = ledger_class
        self.owner = f"prefetch-{id(self)}"


def _ledger_add(state: _PrefetchState, idx: int, batch: Any,
                nbytes: int) -> None:
    """Attribute one uploaded chunk to its owning device in the
    device-memory ledger (prefetch_chunks class). In the PR 15 placement
    mode each chunk lands on its owner device, so the label comes from the
    uploaded leaves, not the pipeline default."""
    from mmlspark_tpu.obs.memory import device_label, memory_ledger

    led = memory_ledger()
    if not led.enabled:
        return
    leaf = batch
    if isinstance(leaf, dict):
        leaf = next(iter(leaf.values()), None)
    elif isinstance(leaf, (tuple, list)):
        leaf = leaf[0] if leaf else None
    dev = device_label(leaf)
    led.record_alloc(dev, state.ledger_class, nbytes, owner=state.owner)
    with state.tl_lock:
        if not state.ledger_released:
            state.ledger_entries[idx] = (dev, nbytes)
            return
    led.record_free(dev, state.ledger_class, nbytes, owner=state.owner)


def _ledger_pop(state: _PrefetchState, idx: int) -> None:
    """The consumer took chunk `idx` off the queue: its bytes are now the
    consumer's to account, not the prefetcher's."""
    with state.tl_lock:
        entry = state.ledger_entries.pop(idx, None)
    if entry is None:
        return
    from mmlspark_tpu.obs.memory import memory_ledger

    memory_ledger().record_free(
        entry[0], state.ledger_class, entry[1], owner=state.owner)


def _ledger_release(state: _PrefetchState) -> None:
    """Free every still-parked chunk (end of loop, close(), or the GC
    finalizer) and refuse future adds — idempotent."""
    with state.tl_lock:
        if state.ledger_released and not state.ledger_entries:
            return
        state.ledger_released = True
        entries = list(state.ledger_entries.values())
        state.ledger_entries.clear()
    if not entries:
        return
    from mmlspark_tpu.obs.memory import memory_ledger

    led = memory_ledger()
    for dev, nbytes in entries:
        led.record_free(dev, state.ledger_class, nbytes, owner=state.owner)


def _finalize_state(state: _PrefetchState) -> None:
    state.stop.set()
    _ledger_release(state)


def _produce(
    state: _PrefetchState,
    source: Iterable[Any],
    stage_fn: Callable[[Any], Any],
    workers: int,
    upload: bool,
    sharding: Any,
    placement: Optional[Callable[[Any], Any]] = None,
) -> None:
    def stage(item):
        t0 = time.perf_counter()
        host = stage_fn(item)
        # per-item device ownership (sharded ingestion): the placement
        # callback names the device/sharding this chunk's rows live on,
        # overriding the pipeline-wide sharding
        tgt = placement(item) if placement is not None else sharding
        return host, time.perf_counter() - t0, tgt

    try:
        source = iter(source)
        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="prefetch-stage"
        ) as pool:
            # sliding submit window over the LAZY source: keeps the pool busy
            # without letting staged host chunks pile up unboundedly ahead of
            # uploads — and never materializes the work list (a streamed
            # shard reader may be far larger than host RAM)
            window = workers + 1
            futures: "deque" = deque()
            # lagged completion barrier: at most `depth` uploads may be
            # unconfirmed before the producer stops to let the device
            # drain. Blocking on upload N itself (the old scheme) couples
            # the producer to the consumer's dispatched-compute backlog —
            # transfers queue behind executions on the device stream — and
            # serializes the pipeline into lockstep with the train loop.
            inflight: "deque" = deque()
            max_inflight = max(1, state.q.maxsize)
            for _ in range(window):
                try:
                    futures.append(pool.submit(stage, next(source)))
                except StopIteration:
                    break
            idx = 0
            while futures:
                if state.stop.is_set():
                    break
                host, decode_s, tgt = futures.popleft().result()
                try:
                    futures.append(pool.submit(stage, next(source)))
                except StopIteration:
                    pass
                t_up = time.perf_counter()
                nbytes = payload_nbytes(host)
                if upload:
                    import jax

                    batch = upload_host_chunk(host, tgt)
                    inflight.append(batch)
                    if len(inflight) > max_inflight:
                        jax.block_until_ready(inflight.popleft())
                else:
                    batch = host
                upload_done = time.perf_counter()
                entry = {
                    "index": float(idx),
                    "decode_s": decode_s,
                    "upload_s": upload_done - t_up,
                    "upload_done_t": upload_done,
                    "requested_t": -1.0,
                    "wait_s": -1.0,
                    "nbytes": float(nbytes),
                }
                with state.tl_lock:
                    state.timeline.append(entry)
                    state.resident_bytes += nbytes
                    state.resident_peak = max(
                        state.resident_peak, state.resident_bytes
                    )
                if upload:
                    _ledger_add(state, idx, batch, nbytes)
                while not state.stop.is_set():
                    try:
                        state.q.put((idx, batch, entry), timeout=0.05)
                        break
                    except queue.Full:
                        continue
                idx += 1
    except BaseException as e:  # surfaced to the consumer in __next__
        state.error = e
    finally:
        # the sentinel must ALWAYS land — including when stop was set while
        # the consumer is already blocked in q.get() on an empty queue
        # (close() from another thread, or the weakref finalizer). While
        # the consumer is live we wait for space so no staged batch is
        # lost; once stop is set nobody wants those batches, and the
        # producer is the only putter, so draining one slot guarantees the
        # put_nowait succeeds.
        while True:
            if state.stop.is_set():
                try:
                    state.q.put_nowait(_SENTINEL)
                    break
                except queue.Full:
                    try:
                        state.q.get_nowait()
                    except queue.Empty:
                        pass
            else:
                try:
                    state.q.put(_SENTINEL, timeout=0.05)
                    break
                except queue.Full:
                    continue


class _ChunkPipeline:
    """The shared pipeline core: lazy source -> staged host payloads ->
    ordered counted uploads (a depth-bounded in-flight window) ->
    depth-bounded device queue. Subclasses
    only shape the constructor surface."""

    def __init__(
        self,
        source: Iterable[Any],
        stage_fn: Callable[[Any], Any],
        depth: int = 2,
        workers: int = 1,
        upload: bool = True,
        sharding: Any = None,
        placement: Optional[Callable[[Any], Any]] = None,
        ledger_class: str = "prefetch_chunks",
    ):
        self._state = _PrefetchState(max(1, int(depth)), ledger_class)
        self._started = False
        with _STATES_LOCK:
            _LIVE_STATES.add(self._state)
        # the thread closes over state/source/stage_fn only — NOT self —
        # so an abandoned prefetcher is collectable, and this finalizer
        # then stops the producer and releases its ledger bytes (it also
        # runs at interpreter shutdown)
        self._finalizer = weakref.finalize(
            self, _finalize_state, self._state)
        self._thread = threading.Thread(
            target=_produce,
            args=(self._state, source, stage_fn,
                  max(1, int(workers)), upload, sharding, placement),
            name="prefetch-pipeline", daemon=True,
        )

    # -- consumer side -----------------------------------------------------

    def __iter__(self) -> "_ChunkPipeline":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def __next__(self) -> Any:
        if not self._started:
            self.__iter__()
        state = self._state
        t_req = time.perf_counter()
        while True:
            try:
                item = state.q.get(timeout=0.05)
                break
            except queue.Empty:
                # close()/finalize can race a consumer already parked in
                # get(): once stop is set and the queue is drained, nothing
                # more is coming — finish rather than block forever
                if state.stop.is_set():
                    item = _SENTINEL
                    break
        if item is _SENTINEL:
            self._finish()
            if state.error is not None:
                raise state.error
            raise StopIteration
        idx, batch, entry = item
        now = time.perf_counter()
        with state.tl_lock:
            entry["requested_t"] = t_req
            entry["wait_s"] = now - t_req
            state.resident_bytes -= int(entry["nbytes"])
        _ledger_pop(state, idx)
        m = _metrics()
        m["batches"].inc()
        if idx > 0 and entry["upload_done_t"] <= t_req:
            m["overlapped"].inc()
        return batch

    def __enter__(self) -> "_ChunkPipeline":
        return self.__iter__()

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Stop the pipeline (idempotent; safe after partial consumption)."""
        self._state.stop.set()
        if self._started:
            self._thread.join(timeout=5.0)
        _ledger_release(self._state)

    def _finish(self) -> None:
        global _LAST_FINISHED_PEAK
        s = self.summary()
        m = _metrics()
        m["ratio"].set(s["overlap_ratio"])
        with _STATES_LOCK:
            _LAST_FINISHED_PEAK = float(s["resident_bytes_peak"])
        _ledger_release(self._state)

    # -- evidence ----------------------------------------------------------

    def timeline(self) -> List[Dict[str, float]]:
        """Per-batch timestamps (perf_counter clock): decode_s, upload_s,
        upload_done_t, requested_t, wait_s, nbytes. The overlap proof
        compares upload_done_t of batch N+1 against the consumer's compute
        window for batch N."""
        state = self._state
        with state.tl_lock:
            return [dict(e) for e in state.timeline]

    def summary(self) -> Dict[str, float]:
        """Overlap evidence: batches, overlapped_batches (upload finished
        before the consumer asked), wait vs prep seconds, overlap_ratio =
        1 - wait/prep clamped to [0, 1], and resident_bytes_peak (the
        depth-bounded device-buffer high-water)."""
        state = self._state
        with state.tl_lock:
            consumed = [e for e in state.timeline if e["wait_s"] >= 0]
            # the first batch can never overlap anything: nothing was
            # computing while it staged, so it is excluded from the ratio
            tail = [e for e in consumed if e["index"] > 0]
            wait = sum(e["wait_s"] for e in tail)
            prep = sum(e["decode_s"] + e["upload_s"] for e in tail)
            overlapped = sum(
                1 for e in tail if e["upload_done_t"] <= e["requested_t"]
            )
            ratio = 1.0 - (wait / prep) if prep > 0 else 0.0
            return {
                "batches": len(consumed),
                "overlapped_batches": overlapped,
                "overlap_ratio": round(max(0.0, min(1.0, ratio)), 4),
                "wait_s": round(wait, 4),
                "prep_s": round(prep, 4),
                "resident_bytes_peak": int(state.resident_peak),
            }


class DeviceChunkPrefetcher(_ChunkPipeline):
    """Iterate device-resident chunks staged and uploaded ahead of the
    consumer — the GENERIC double-buffer tier (numeric column chunks,
    binned GBDT chunks, any host payload shaped as an ndarray or a
    tuple/dict of ndarrays).

    Parameters
    ----------
    chunks: a LAZY iterable of work units — consumed one sliding window at
        a time, never materialized (a shard reader's chunk iterator can be
        far larger than host RAM).
    stage_fn: work unit -> host payload, run in the worker pool (None:
        the work units already ARE host payloads). Per-chunk host work
        (file read, decode, bin transform) belongs here.
    depth: device chunks parked ahead of the consumer (the double buffer;
        2 keeps one uploading while one is consumed). This bounds the
        streaming HBM footprint at depth * chunk_bytes, measured by
        `summary()["resident_bytes_peak"]`.
    workers: staging pool size (stage parallelism; uploads stay ordered,
        with at most `depth` transfers unconfirmed in flight).
    upload: False yields host payloads instead (stage-only prefetch).
    placement: work unit -> jax Device (or Sharding) — the SHARDED upload
        mode (ISSUE 15): each staged chunk's rows are device_put leaf-wise
        directly onto their owning device (round-robin shard->device
        ownership in the sharded GBDT ingestion path), counted in the same
        dataplane metrics. Overrides `sharding` per item.
    ledger_class: the device-memory-ledger class (obs/memory.CLASSES)
        parked chunks are attributed to; the DNN trainer passes
        "train_batches" so in-flight batch shards are distinguishable
        from generic streamed chunks in /debug/memory.

    Use as an iterator (or context manager for early-exit cleanup):

        with DeviceChunkPrefetcher(reader.iter_chunks(), stage) as pf:
            for dev_chunk in pf:
                hist += kernel(dev_chunk)    # overlaps the next upload
        pf.summary()["overlap_ratio"]
    """

    def __init__(
        self,
        chunks: Iterable[Any],
        stage_fn: Optional[Callable[[Any], Any]] = None,
        depth: int = 2,
        workers: int = 1,
        upload: bool = True,
        sharding: Any = None,
        placement: Optional[Callable[[Any], Any]] = None,
        ledger_class: str = "prefetch_chunks",
    ):
        super().__init__(
            chunks, stage_fn if stage_fn is not None else (lambda c: c),
            depth=depth, workers=workers, upload=upload, sharding=sharding,
            placement=placement, ledger_class=ledger_class,
        )


class DeviceBatchPrefetcher(_ChunkPipeline):
    """Iterate device-resident batches decoded and uploaded ahead of the
    consumer — the image-tier face of the pipeline (ISSUE 7).

    Parameters
    ----------
    items: the full work list (bytes blobs, paths, rows — anything).
    decode_fn: list-of-items -> host numpy batch; runs in the worker pool.
        This is where per-item host work (image decode, parsing) belongs.
    batch_size: items per staged batch.
    depth: device batches parked ahead of the consumer (the double buffer;
        2 keeps one uploading while one is consumed).
    workers: decode pool size (decode parallelism; uploads stay ordered,
        with at most `depth` transfers unconfirmed in flight).
    upload: False yields host batches instead (decode-only prefetch).

    Use as an iterator (or context manager for early-exit cleanup):

        with DeviceBatchPrefetcher(blobs, decode, batch_size=64) as pf:
            for dev_batch in pf:
                y = model_fn(dev_batch)      # overlaps the next upload
        pf.summary()["overlap_ratio"]

    A bare iterator works too; on early exit, call close() to release the
    pipeline immediately (dropping the object also stops it, via GC).
    """

    def __init__(
        self,
        items: Sequence[Any],
        decode_fn: Callable[[List[Any]], np.ndarray],
        batch_size: int = 64,
        depth: int = 2,
        workers: int = 2,
        upload: bool = True,
        sharding: Any = None,
    ):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        items = list(items)
        bs = int(batch_size)
        chunks = [items[i: i + bs] for i in range(0, len(items), bs)]
        super().__init__(
            chunks, decode_fn,
            depth=depth, workers=workers, upload=upload, sharding=sharding,
        )
