"""Double-buffered host->HBM batch prefetch for the device dataplane.

Host decode (JPEG/PNG bytes -> numpy, inherently host work) runs in a
worker pool feeding staged host batches; a single pipeline thread uploads
each staged batch to device HBM — uploads stay SERIALIZED (BASELINE.md
round 3: concurrent in-flight device_puts collapse tunnel throughput ~50x)
— and parks up to `depth` device-resident batches in a bounded queue. The
consumer drains the queue while the next batch decodes and uploads behind
it, so batch N+1's h2d overlaps batch N's device compute.

Overlap is MEASURED, not assumed: every batch records decode/upload/
request timestamps, `summary()` reports the overlap ratio (1 - consumer
wait / producer prep, clamped to [0, 1]) and the count of batches whose
upload finished before the consumer asked — the gateable evidence for
"prefetch fully overlaps compute" (ROADMAP streaming-ingestion item; the
bench gate in tests/test_bench_smoke.py). Uploads land in the same
profiling.dataplane_counters() every other transfer point reports to, and
the loader exports `dataplane_prefetch_*` registry metrics including the
`dataplane_prefetch_overlap_ratio` gauge.

Lifecycle: the pipeline thread holds NO strong reference to the public
DeviceBatchPrefetcher — only to its internal state — and a
``weakref.finalize`` stops the pipeline when the public object is
collected. So a consumer that breaks out of a bare ``for`` loop and drops
the iterator cannot strand a producer spinning on a full queue pinning
device batches; explicit ``close()`` (or the context manager) remains the
deterministic way to release resources immediately.
"""

from __future__ import annotations

import queue
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

_SENTINEL = object()


_METRICS: Dict[str, Any] = {}


def _metrics() -> Dict[str, Any]:
    """Process-wide prefetch instruments, created on first use (keeps this
    module import-light and obs-optional)."""
    if not _METRICS:
        from mmlspark_tpu.obs.metrics import registry

        reg = registry()
        _METRICS["batches"] = reg.counter(
            "dataplane_prefetch_batches_total",
            "Batches staged through the host->HBM prefetcher")
        _METRICS["overlapped"] = reg.counter(
            "dataplane_prefetch_overlapped_batches_total",
            "Prefetched batches whose upload finished before the consumer "
            "asked for them")
        _METRICS["ratio"] = reg.gauge(
            "dataplane_prefetch_overlap_ratio",
            "1 - consumer wait / producer prep for the most recently "
            "finished prefetch loop (1.0 = prep fully hidden)")
    return _METRICS


class _PrefetchState:
    """Everything the pipeline thread touches — shared with (but not
    owning) the public DeviceBatchPrefetcher, so the thread cannot keep an
    abandoned prefetcher alive."""

    def __init__(self, depth: int):
        self.q: "queue.Queue" = queue.Queue(maxsize=depth)
        self.stop = threading.Event()
        self.error: Optional[BaseException] = None
        self.timeline: List[Dict[str, float]] = []
        self.tl_lock = threading.Lock()


def _produce(
    state: _PrefetchState,
    chunks: List[List[Any]],
    decode_fn: Callable[[List[Any]], np.ndarray],
    workers: int,
    upload: bool,
    sharding: Any,
) -> None:
    def stage(chunk):
        t0 = time.perf_counter()
        host = decode_fn(chunk)
        return host, time.perf_counter() - t0

    try:
        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="prefetch-decode"
        ) as pool:
            # sliding submit window: keeps the pool busy without letting
            # decoded host batches pile up unboundedly ahead of uploads
            window = workers + 1
            futures = [pool.submit(stage, c) for c in chunks[:window]]
            next_submit = len(futures)
            for idx in range(len(chunks)):
                if state.stop.is_set():
                    break
                host, decode_s = futures[idx].result()
                if next_submit < len(chunks):
                    futures.append(pool.submit(stage, chunks[next_submit]))
                    next_submit += 1
                t_up = time.perf_counter()
                if upload:
                    import jax

                    from mmlspark_tpu.images.device_ops import upload_batch

                    batch = upload_batch(host, sharding)
                    # block: "upload done" must mean bytes ON the device,
                    # and serialized uploads are the measured fast path
                    # for the tunnel-attached chip
                    jax.block_until_ready(batch)
                else:
                    batch = host
                upload_done = time.perf_counter()
                entry = {
                    "index": float(idx),
                    "decode_s": decode_s,
                    "upload_s": upload_done - t_up,
                    "upload_done_t": upload_done,
                    "requested_t": -1.0,
                    "wait_s": -1.0,
                }
                with state.tl_lock:
                    state.timeline.append(entry)
                while not state.stop.is_set():
                    try:
                        state.q.put((idx, batch, entry), timeout=0.05)
                        break
                    except queue.Full:
                        continue
    except BaseException as e:  # surfaced to the consumer in __next__
        state.error = e
    finally:
        # the sentinel must ALWAYS land — including when stop was set while
        # the consumer is already blocked in q.get() on an empty queue
        # (close() from another thread, or the weakref finalizer). While
        # the consumer is live we wait for space so no staged batch is
        # lost; once stop is set nobody wants those batches, and the
        # producer is the only putter, so draining one slot guarantees the
        # put_nowait succeeds.
        while True:
            if state.stop.is_set():
                try:
                    state.q.put_nowait(_SENTINEL)
                    break
                except queue.Full:
                    try:
                        state.q.get_nowait()
                    except queue.Empty:
                        pass
            else:
                try:
                    state.q.put(_SENTINEL, timeout=0.05)
                    break
                except queue.Full:
                    continue


class DeviceBatchPrefetcher:
    """Iterate device-resident batches decoded and uploaded ahead of the
    consumer.

    Parameters
    ----------
    items: the full work list (bytes blobs, paths, rows — anything).
    decode_fn: list-of-items -> host numpy batch; runs in the worker pool.
        This is where per-item host work (image decode, parsing) belongs.
    batch_size: items per staged batch.
    depth: device batches parked ahead of the consumer (the double buffer;
        2 keeps one uploading while one is consumed).
    workers: decode pool size (decode parallelism; uploads stay serial).
    upload: False yields host batches instead (decode-only prefetch).

    Use as an iterator (or context manager for early-exit cleanup):

        with DeviceBatchPrefetcher(blobs, decode, batch_size=64) as pf:
            for dev_batch in pf:
                y = model_fn(dev_batch)      # overlaps the next upload
        pf.summary()["overlap_ratio"]

    A bare iterator works too; on early exit, call close() to release the
    pipeline immediately (dropping the object also stops it, via GC).
    """

    def __init__(
        self,
        items: Sequence[Any],
        decode_fn: Callable[[List[Any]], np.ndarray],
        batch_size: int = 64,
        depth: int = 2,
        workers: int = 2,
        upload: bool = True,
        sharding: Any = None,
    ):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        items = list(items)
        bs = int(batch_size)
        chunks = [items[i: i + bs] for i in range(0, len(items), bs)]
        self._state = _PrefetchState(max(1, int(depth)))
        self._started = False
        # the thread closes over state/chunks/decode_fn only — NOT self —
        # so an abandoned prefetcher is collectable, and this finalizer
        # then stops the producer (it also runs at interpreter shutdown)
        self._finalizer = weakref.finalize(self, self._state.stop.set)
        self._thread = threading.Thread(
            target=_produce,
            args=(self._state, chunks, decode_fn,
                  max(1, int(workers)), upload, sharding),
            name="prefetch-pipeline", daemon=True,
        )

    # -- consumer side -----------------------------------------------------

    def __iter__(self) -> "DeviceBatchPrefetcher":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def __next__(self) -> Any:
        if not self._started:
            self.__iter__()
        state = self._state
        t_req = time.perf_counter()
        while True:
            try:
                item = state.q.get(timeout=0.05)
                break
            except queue.Empty:
                # close()/finalize can race a consumer already parked in
                # get(): once stop is set and the queue is drained, nothing
                # more is coming — finish rather than block forever
                if state.stop.is_set():
                    item = _SENTINEL
                    break
        if item is _SENTINEL:
            self._finish()
            if state.error is not None:
                raise state.error
            raise StopIteration
        idx, batch, entry = item
        now = time.perf_counter()
        entry["requested_t"] = t_req
        entry["wait_s"] = now - t_req
        m = _metrics()
        m["batches"].inc()
        if idx > 0 and entry["upload_done_t"] <= t_req:
            m["overlapped"].inc()
        return batch

    def __enter__(self) -> "DeviceBatchPrefetcher":
        return self.__iter__()

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Stop the pipeline (idempotent; safe after partial consumption)."""
        self._state.stop.set()
        if self._started:
            self._thread.join(timeout=5.0)

    def _finish(self) -> None:
        _metrics()["ratio"].set(self.summary()["overlap_ratio"])

    # -- evidence ----------------------------------------------------------

    def timeline(self) -> List[Dict[str, float]]:
        """Per-batch timestamps (perf_counter clock): decode_s, upload_s,
        upload_done_t, requested_t, wait_s. The overlap proof compares
        upload_done_t of batch N+1 against the consumer's compute window
        for batch N."""
        state = self._state
        with state.tl_lock:
            return [dict(e) for e in state.timeline]

    def summary(self) -> Dict[str, float]:
        """Overlap evidence: batches, overlapped_batches (upload finished
        before the consumer asked), wait vs prep seconds, and
        overlap_ratio = 1 - wait/prep clamped to [0, 1]."""
        state = self._state
        with state.tl_lock:
            consumed = [e for e in state.timeline if e["wait_s"] >= 0]
            # the first batch can never overlap anything: nothing was
            # computing while it staged, so it is excluded from the ratio
            tail = [e for e in consumed if e["index"] > 0]
            wait = sum(e["wait_s"] for e in tail)
            prep = sum(e["decode_s"] + e["upload_s"] for e in tail)
            overlapped = sum(
                1 for e in tail if e["upload_done_t"] <= e["requested_t"]
            )
            ratio = 1.0 - (wait / prep) if prep > 0 else 0.0
            return {
                "batches": len(consumed),
                "overlapped_batches": overlapped,
                "overlap_ratio": round(max(0.0, min(1.0, ratio)), 4),
                "wait_s": round(wait, 4),
                "prep_s": round(prep, 4),
            }
