"""Framework configuration namespace.

Reference: Typesafe-config `mmlspark.*` namespace
(src/core/env/src/main/scala/Configuration.scala:18-52). Here: a process-wide
dict seeded from MMLSPARK_TPU_* environment variables, with dotted-key
get/set. Also central logging setup (reference: Logging.scala).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict

_ENV_PREFIX = "MMLSPARK_TPU_"
_lock = threading.Lock()
_config: Dict[str, Any] = {}
_loaded = False

_DEFAULTS: Dict[str, Any] = {
    "sdk.logging.level": "INFO",
    "model.cache.dir": os.path.expanduser("~/.cache/mmlspark_tpu/models"),
    "serving.default.port": 8899,
    "gbdt.default.listen.timeout": 120.0,
}


def _load() -> None:
    global _loaded
    with _lock:
        if _loaded:
            return
        _config.update(_DEFAULTS)
        for key, value in os.environ.items():
            if key.startswith(_ENV_PREFIX):
                dotted = key[len(_ENV_PREFIX):].lower().replace("_", ".")
                _config[dotted] = value
        _loaded = True


def get(key: str, default: Any = None) -> Any:
    _load()
    return _config.get(key, default)


def set(key: str, value: Any) -> None:
    _load()
    _config[key] = value


def get_logger(name: str = "mmlspark_tpu") -> logging.Logger:
    """Deprecated: library code logs through
    mmlspark_tpu.obs.logging.get_logger (structured JSON lines with trace
    correlation) — graftcheck's `unstructured-log-in-library` rule flags
    new call sites of this shim. Kept for external callers that want the
    raw stdlib logger underneath (handler setup included)."""
    from mmlspark_tpu.obs.logging import stdlib_logger

    return stdlib_logger(name)
