"""Schema utilities: categorical metadata, image/binary schemas, helpers.

TPU-native equivalents of the reference's core/schema:
- CategoricalMap / CategoricalUtilities (Categoricals.scala:16-290)
- ImageSchemaUtils (ImageSchemaUtils.scala:9-33)
- BinaryFileSchema (BinaryFileSchema.scala)
- DatasetExtensions.findUnusedColumnName
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType

CATEGORICAL_KEY = "categorical"

# Image rows are dicts with these keys; `data` is an HxWxC uint8 ndarray
# (host representation; UnrollImage converts to CHW float vectors for TPU).
IMAGE_FIELDS = ("path", "height", "width", "nChannels", "mode", "data")
BINARY_FIELDS = ("path", "bytes")

# OpenCV-compatible mode codes used by the reference's image schema
IMAGE_MODE_CV8UC1 = 0
IMAGE_MODE_CV8UC3 = 16
IMAGE_MODE_CV8UC4 = 24


def make_image_row(data: np.ndarray, path: str = "") -> Dict[str, Any]:
    data = np.asarray(data)
    if data.ndim == 2:
        data = data[:, :, None]
    h, w, c = data.shape
    mode = {1: IMAGE_MODE_CV8UC1, 3: IMAGE_MODE_CV8UC3, 4: IMAGE_MODE_CV8UC4}.get(c)
    if mode is None:
        # ValueError (not a bare KeyError) so decode paths can classify it
        # as a decode failure (io/image.DECODE_ERRORS)
        raise ValueError(f"unsupported image channel count {c} (expect 1/3/4)")
    return {
        "path": path,
        "height": int(h),
        "width": int(w),
        "nChannels": int(c),
        "mode": mode,
        "data": data.astype(np.uint8),
    }


def is_image(df: DataFrame, col: str) -> bool:
    if col not in df or df.dtype(col) != DataType.STRUCT:
        return False
    values = df[col]
    for v in values:
        if v is None:
            continue
        return isinstance(v, dict) and {"height", "width", "nChannels", "data"} <= set(v)
    return False


def is_binary(df: DataFrame, col: str) -> bool:
    return col in df and df.dtype(col) == DataType.BINARY


class CategoricalMap:
    """Bidirectional value<->index mapping stored in column metadata.

    Reference: CategoricalMap (Categoricals.scala:16-290). Levels keep their
    original python type (str/int/float/bool); `ordinal` marks ordered levels.
    """

    def __init__(self, levels: Sequence[Any], ordinal: bool = False):
        self.levels = list(levels)
        self.ordinal = ordinal
        self._index = {v: i for i, v in enumerate(self.levels)}

    def __len__(self) -> int:
        return len(self.levels)

    def get_index(self, value: Any) -> int:
        try:
            return self._index[value]
        except KeyError:
            raise ValueError(f"Value {value!r} not in categorical levels") from None

    def get_index_option(self, value: Any, default: int = -1) -> int:
        return self._index.get(value, default)

    def get_level(self, index: int) -> Any:
        return self.levels[index]

    def to_metadata(self) -> dict:
        return {CATEGORICAL_KEY: {"levels": self.levels, "ordinal": self.ordinal}}

    @staticmethod
    def from_metadata(metadata: dict) -> Optional["CategoricalMap"]:
        info = metadata.get(CATEGORICAL_KEY)
        if not info:
            return None
        return CategoricalMap(info["levels"], info.get("ordinal", False))


def get_categorical_map(df: DataFrame, col: str) -> Optional[CategoricalMap]:
    return CategoricalMap.from_metadata(df.metadata(col))


def set_categorical_map(df: DataFrame, col: str, cmap: CategoricalMap) -> DataFrame:
    meta = dict(df.metadata(col))
    meta.update(cmap.to_metadata())
    return df.with_metadata(col, meta)


def find_unused_column_name(base: str, df_or_columns) -> str:
    """Reference: DatasetExtensions.findUnusedColumnName."""
    columns = df_or_columns.columns if isinstance(df_or_columns, DataFrame) else set(df_or_columns)
    name = base
    i = 1
    while name in columns:
        name = f"{base}_{i}"
        i += 1
    return name


def to_numeric(col: Column) -> np.ndarray:
    """Column -> float64 ndarray (1-D), for metric/stat computations."""
    v = col.values
    if v.dtype == object:
        return np.array([float(x) for x in v], dtype=np.float64)
    return v.astype(np.float64)
