"""Columnar, partition-aware DataFrame — the data plane of the framework.

The reference operates on Spark DataFrames; the TPU-native equivalent is a
lightweight columnar table whose columns are numpy arrays (host) that shard
cleanly onto a `jax.sharding.Mesh` (device). Design goals:

- **Columnar**: each column is one contiguous ndarray → zero-copy
  `jax.device_put` onto HBM, batched MXU-friendly compute, no per-row
  marshalling (the reference's per-row SWIG `setitem` copy at
  LightGBMUtils.scala:316-395 is the anti-pattern this design removes).
- **Partitioned**: `num_partitions` is logical; `partitions()` yields row
  slices so "one partition ≈ one worker/chip" semantics from the reference's
  test strategy (SURVEY.md §4) carry over directly.
- **Schema + metadata**: per-column `DataType` and a metadata dict carrying
  categorical levels / image schema, mirroring Spark column metadata
  (reference: core/schema Categoricals.scala:16-290).

Vector columns are 2-D float arrays (n_rows, dim) — the reference's
ml.linalg.Vector column becomes a dense matrix, which is what the TPU wants.
Ragged data (strings, bytes, variable-length lists, image structs) uses
object-dtype arrays and stays host-side.

**Device residency (ISSUE 3)**: numeric/VECTOR columns may be
device-backed — primary storage a `jax.Array` on HBM, host numpy
materialized lazily only when a host-only consumer asks. Device-consuming
stages (TPUModel, GBDT scoring, ImageFeaturizer) produce and accept
device-backed columns, so chained stages exchange HBM handles instead of
round-tripping through host numpy; `select`/`rename`/`with_metadata`/
`slice`/`limit` derive zero-copy views that preserve residency. See
docs/dataplane.md.
"""

from __future__ import annotations

import copy
import enum
import sys
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np


def is_device_array(values: Any) -> bool:
    """True for a jax.Array (device-resident storage). Checked via
    sys.modules so merely constructing host DataFrames never imports jax —
    if jax was never imported, no device array can exist."""
    if values is None or isinstance(values, np.ndarray):
        return False
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(values, jax.Array)


def _counters():
    """Lazy dataplane-counter accessor (keeps core.dataframe import-light)."""
    from mmlspark_tpu.utils.profiling import dataplane_counters

    return dataplane_counters()


def _trace_transfer(kind: str, nbytes: int) -> None:
    """Annotate the active trace span (if any) with a host<->device sync —
    slow-request logs then show WHICH stage paid a transfer, not just that
    one happened somewhere (obs/tracing.py span events)."""
    from mmlspark_tpu.obs.tracing import current_span

    span = current_span()
    if span is not None and span.recording:
        span.add_event(kind, nbytes=int(nbytes))


class DataType(enum.Enum):
    DOUBLE = "double"
    FLOAT = "float"
    INT = "int"
    LONG = "long"
    BOOLEAN = "boolean"
    STRING = "string"
    BINARY = "binary"       # python bytes per row
    VECTOR = "vector"       # fixed-dim dense vector -> 2D float array
    IMAGE = "image"         # dict row: {height,width,nChannels,mode,data}
    ARRAY = "array"         # variable-length python list per row
    STRUCT = "struct"       # dict per row
    TIMESTAMP = "timestamp" # numpy datetime64[us]

    @property
    def is_numeric(self) -> bool:
        return self in (DataType.DOUBLE, DataType.FLOAT, DataType.INT, DataType.LONG, DataType.BOOLEAN)


_NUMPY_KIND_TO_TYPE = {
    "f": {4: DataType.FLOAT, 8: DataType.DOUBLE, 2: DataType.FLOAT},
    "i": {1: DataType.INT, 2: DataType.INT, 4: DataType.INT, 8: DataType.LONG},
    "u": {1: DataType.INT, 2: DataType.INT, 4: DataType.LONG, 8: DataType.LONG},
    "b": {1: DataType.BOOLEAN},
}

_TYPE_TO_NUMPY = {
    DataType.DOUBLE: np.float64,
    DataType.FLOAT: np.float32,
    DataType.INT: np.int32,
    DataType.LONG: np.int64,
    DataType.BOOLEAN: np.bool_,
    DataType.TIMESTAMP: "datetime64[us]",
}


def _infer_device_type(values: Any) -> DataType:
    """DataType for a device-backed (jax.Array) column. bfloat16 (an
    accelerator compute dtype numpy has no kind for) maps to FLOAT."""
    dt = np.dtype(values.dtype)
    if values.ndim == 2:
        return DataType.VECTOR
    if dt.name == "bfloat16" or dt.kind == "V" and dt.itemsize == 2:
        return DataType.FLOAT
    kinds = _NUMPY_KIND_TO_TYPE.get(dt.kind)
    if kinds is None:
        raise TypeError(f"Cannot infer DataType for device dtype {dt}")
    return kinds[dt.itemsize]


def _infer_type(values: np.ndarray) -> DataType:
    if values.dtype == object:
        for v in values:
            if v is None:
                continue
            if isinstance(v, str):
                return DataType.STRING
            if isinstance(v, (bytes, bytearray)):
                return DataType.BINARY
            if isinstance(v, dict):
                return DataType.STRUCT
            if isinstance(v, (list, tuple, np.ndarray)):
                return DataType.ARRAY
            if isinstance(v, bool):
                return DataType.BOOLEAN
            if isinstance(v, (int, np.integer)):
                return DataType.LONG
            if isinstance(v, (float, np.floating)):
                return DataType.DOUBLE
        return DataType.STRING
    if values.ndim == 2:
        return DataType.VECTOR
    if values.dtype.kind == "U" or values.dtype.kind == "S":
        return DataType.STRING
    if values.dtype.kind == "M":
        return DataType.TIMESTAMP
    kinds = _NUMPY_KIND_TO_TYPE.get(values.dtype.kind)
    if kinds is None:
        raise TypeError(f"Cannot infer DataType for numpy dtype {values.dtype}")
    return kinds[values.dtype.itemsize]


class Field:
    """Schema entry: column name, type, and a metadata dict.

    metadata keys used across the framework:
      - "categorical": {"levels": [...], "ordinal": bool} — reference
        CategoricalMap (Categoricals.scala:16-290)
      - "ml_attr": one-hot slot names for assembled feature vectors
    """

    def __init__(self, name: str, dtype: DataType, metadata: Optional[dict] = None):
        self.name = name
        self.dtype = dtype
        self.metadata = metadata or {}

    def __repr__(self) -> str:
        meta = f", meta={list(self.metadata)}" if self.metadata else ""
        return f"Field({self.name!r}, {self.dtype.value}{meta})"

    def copy(self) -> "Field":
        return Field(self.name, self.dtype, dict(self.metadata))


class _ColumnStorage:
    """Mutable (host, device) backing cell SHARED by all views of a column,
    so a lazy sync or upload performed through any alias is visible to every
    other alias — a rename after a model stage must not double the exit
    fetch."""

    __slots__ = ("host", "device")

    def __init__(self, host: Optional[np.ndarray] = None, device: Any = None):
        self.host = host
        self.device = device


class Column:
    """A named array + type + metadata.

    Host storage is a numpy ndarray: 1-D for scalars/objects, 2-D (n, dim)
    for VECTOR. A column may instead be **device-backed**: primary storage
    is a `jax.Array` already resident on accelerator HBM (carrying whatever
    NamedSharding it was produced under), and the host ndarray materializes
    lazily — only when a host-only consumer asks via `.values` (object /
    string ops, serialization, collect). Device-consuming stages chain
    through `device_values()`, so featurize -> TPUModel -> postprocess
    pipelines move zero bytes across the host<->HBM link between stages;
    every sync either way is counted in profiling.dataplane_counters().
    """

    def __init__(self, values: Any, dtype: Optional[DataType] = None, metadata: Optional[dict] = None):
        device = None
        if is_device_array(values):
            device = values
            if dtype is None:
                dtype = _infer_device_type(values)
            values = None
        else:
            if not isinstance(values, np.ndarray):
                values = _to_array(values)
            if dtype is None:
                dtype = _infer_type(values)
            if dtype == DataType.VECTOR and values.ndim != 2:
                # rows of array-likes -> dense 2D; ragged rows (legal for Spark
                # vector columns — e.g. per-image LIME weights with differing
                # superpixel counts) stay as an object array of 1-D vectors.
                # Element conversion errors still raise — only raggedness is
                # tolerated.
                rows = [np.asarray(v, dtype=np.float64) for v in values]
                if len({r.shape for r in rows}) <= 1:
                    values = np.stack(rows) if rows else values
                else:
                    ragged = np.empty(len(rows), object)
                    ragged[:] = rows
                    values = ragged
        self._storage = _ColumnStorage(host=values, device=device)
        self.dtype = dtype
        self.metadata = metadata or {}

    # -- storage ----------------------------------------------------------

    @property
    def values(self) -> np.ndarray:
        """Host ndarray; device-backed columns sync device->host on first
        access (counted, shared with every view of this column), then serve
        the cached host copy. The sync honors the declared DataType: a
        device f32/i32 column declared DOUBLE/LONG (device compute dtypes
        are 32-bit) widens so host consumers see the schema's dtype."""
        storage = self._storage
        if storage.host is None:
            host = np.asarray(storage.device)
            _counters().record_d2h(host.nbytes)
            _trace_transfer("d2h_sync", host.nbytes)
            want = _TYPE_TO_NUMPY.get(self.dtype)
            if want is not None and host.dtype != np.dtype(want) and host.dtype.kind in "fiub":
                host = host.astype(want)
            storage.host = host
        return storage.host

    @property
    def is_device_backed(self) -> bool:
        return self._storage.device is not None

    def device_values(self, sharding: Any = None):
        """The column as a device-resident jax.Array, uploading (once,
        counted, shared with every view) if currently host-only. `sharding`
        applies only to that first upload; an already-device column returns
        as-is."""
        storage = self._storage
        if storage.device is None:
            host = storage.host
            if host.dtype == object:
                raise TypeError(
                    f"column of {self.dtype.value} is host-only (object "
                    "dtype cannot live on device)"
                )
            import jax

            storage.device = (
                jax.device_put(host) if sharding is None
                else jax.device_put(host, sharding)
            )
            _counters().record_h2d(host.nbytes)
            _trace_transfer("h2d_upload", host.nbytes)
        return storage.device

    @property
    def _backing(self) -> Any:
        s = self._storage
        return s.host if s.host is not None else s.device

    @property
    def shape(self) -> Tuple[int, ...]:
        """Shape without forcing a host sync."""
        return tuple(self._backing.shape)

    @property
    def ndim(self) -> int:
        return self._backing.ndim

    def __len__(self) -> int:
        shape = self._backing.shape
        return int(shape[0]) if shape else 0

    def __repr__(self) -> str:
        loc = ", device" if self.is_device_backed else ""
        return f"Column({self.dtype.value}, n={len(self)}{loc})"

    # -- derivation (zero-copy where storage allows) -----------------------

    def view(self, metadata: Optional[dict] = None) -> "Column":
        """Zero-copy view SHARING this column's storage cell (a sync or
        upload through either alias benefits both); metadata is a deep copy
        (of `metadata` if given, else this column's), so mutate-after-derive
        cannot corrupt sibling frames."""
        col = Column.__new__(Column)
        col._storage = self._storage
        col.dtype = self.dtype
        col.metadata = copy.deepcopy(
            self.metadata if metadata is None else metadata
        )
        return col

    def slice(self, start: int, stop: int) -> "Column":
        """Row slice. Host-synced columns slice as zero-copy host views;
        device-only columns slice on device (residency preserved — a
        host-synced column's slice re-uploads if a device stage needs it)."""
        storage = self._storage
        if storage.host is None:
            col = Column.__new__(Column)
            col._storage = _ColumnStorage(device=storage.device[start:stop])
            col.dtype = self.dtype
            col.metadata = copy.deepcopy(self.metadata)
            return col
        return Column(
            storage.host[start:stop], self.dtype, copy.deepcopy(self.metadata)
        )

    def take(self, indices: np.ndarray) -> "Column":
        return Column(self.values[indices], self.dtype, copy.deepcopy(self.metadata))

    def copy(self) -> "Column":
        return self.view()


def _to_array(values: Any) -> np.ndarray:
    """Convert a python sequence to the canonical ndarray representation."""
    if isinstance(values, np.ndarray):
        return values
    values = list(values)
    if values and isinstance(values[0], str):
        return np.array(values, dtype=object)
    if values and isinstance(values[0], (bytes, bytearray, dict)):
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        return arr
    if values and isinstance(values[0], (list, tuple, np.ndarray)):
        first_len = len(values[0])
        if all(
            isinstance(v, (list, tuple, np.ndarray))
            and len(v) == first_len
            and all(isinstance(x, (int, float, np.integer, np.floating)) for x in np.ravel(np.asarray(v, dtype=object))[:1])
            for v in values
        ):
            try:
                return np.array([np.asarray(v, dtype=np.float64) for v in values])
            except (ValueError, TypeError):
                pass
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        return arr
    try:
        arr = np.asarray(values)
        if arr.dtype.kind in "fiubM":
            return arr
    except (ValueError, TypeError):
        pass
    arr = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        arr[i] = v
    return arr


class DataFrame:
    """Immutable-by-convention columnar table.

    Construction:
      DataFrame.from_dict({"a": [1,2,3], "b": ["x","y","z"]})
      DataFrame.from_rows([{"a": 1}, {"a": 2}])
    """

    def __init__(self, columns: "Dict[str, Column]", num_partitions: int = 1):
        lengths = {name: len(col) for name, col in columns.items()}
        if len(set(lengths.values())) > 1:
            raise ValueError(f"Column length mismatch: {lengths}")
        self._columns: Dict[str, Column] = dict(columns)
        self.num_partitions = max(1, num_partitions)

    # -- construction ---------------------------------------------------------

    @staticmethod
    def from_dict(data: Dict[str, Any], num_partitions: int = 1,
                  types: Optional[Dict[str, DataType]] = None,
                  metadata: Optional[Dict[str, dict]] = None) -> "DataFrame":
        types = types or {}
        metadata = metadata or {}
        cols = {
            name: Column(values, types.get(name), metadata.get(name))
            for name, values in data.items()
        }
        return DataFrame(cols, num_partitions)

    @staticmethod
    def from_rows(rows: Sequence[Dict[str, Any]], num_partitions: int = 1) -> "DataFrame":
        if not rows:
            return DataFrame({}, num_partitions)
        names = list(rows[0].keys())
        return DataFrame.from_dict(
            {n: [r.get(n) for r in rows] for n in names}, num_partitions
        )

    # -- basic info -----------------------------------------------------------

    @property
    def columns(self) -> List[str]:
        return list(self._columns.keys())

    def __len__(self) -> int:
        if not self._columns:
            return 0
        return len(next(iter(self._columns.values())))

    count = __len__

    @property
    def schema(self) -> List[Field]:
        return [Field(n, c.dtype, dict(c.metadata)) for n, c in self._columns.items()]

    def field(self, name: str) -> Field:
        col = self.column(name)
        return Field(name, col.dtype, dict(col.metadata))

    def column(self, name: str) -> Column:
        if name not in self._columns:
            raise KeyError(f"No column {name!r}; have {self.columns}")
        return self._columns[name]

    def __getitem__(self, name: str) -> np.ndarray:
        return self.column(name).values

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def dtype(self, name: str) -> DataType:
        return self.column(name).dtype

    def metadata(self, name: str) -> dict:
        return self.column(name).metadata

    # -- fluent ML sugar (reference FluentAPI.scala:14-20) --------------------

    def ml_transform(self, *stages) -> "DataFrame":
        """df.ml_transform(t1, t2, ...) — apply transformers in order."""
        out = self
        for stage in stages:
            out = stage.transform(out)
        return out

    def ml_fit(self, estimator):
        """df.ml_fit(est) — fit an estimator on this frame, return the model."""
        return estimator.fit(self)

    # -- projection / mutation (returns new DataFrame) ------------------------

    def select(self, *names: str) -> "DataFrame":
        flat: List[str] = []
        for n in names:
            flat.extend(n if isinstance(n, (list, tuple)) else [n])
        return DataFrame({n: self.column(n) for n in flat}, self.num_partitions)

    def drop(self, *names: str) -> "DataFrame":
        flat = set()
        for n in names:
            flat.update(n if isinstance(n, (list, tuple)) else [n])
        return DataFrame(
            {n: c for n, c in self._columns.items() if n not in flat},
            self.num_partitions,
        )

    def with_column(self, name: str, values: Any, dtype: Optional[DataType] = None,
                    metadata: Optional[dict] = None) -> "DataFrame":
        if isinstance(values, Column):
            # view: shares storage, owns a deep-copied metadata dict so a
            # later metadata mutation can't corrupt the source frame
            col = values.view()
        else:
            col = Column(values, dtype, metadata)
            if metadata is not None:
                col.metadata = metadata
        new = dict(self._columns)
        new[name] = col
        return DataFrame(new, self.num_partitions)

    def with_metadata(self, name: str, metadata: dict) -> "DataFrame":
        new = dict(self._columns)
        new[name] = self.column(name).view(metadata)
        return DataFrame(new, self.num_partitions)

    def rename(self, existing: str, new_name: str) -> "DataFrame":
        cols = {}
        for n, c in self._columns.items():
            cols[new_name if n == existing else n] = c.view()
        return DataFrame(cols, self.num_partitions)

    def filter(self, mask: np.ndarray) -> "DataFrame":
        mask = np.asarray(mask)
        if mask.dtype == bool:
            idx = np.nonzero(mask)[0]
        else:
            idx = mask
        return DataFrame(
            {n: c.take(idx) for n, c in self._columns.items()}, self.num_partitions
        )

    def take(self, n: int) -> "DataFrame":
        return self.limit(n)

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(
            {name: c.slice(0, n) for name, c in self._columns.items()},
            self.num_partitions,
        )

    def sort(self, by: str, ascending: bool = True) -> "DataFrame":
        order = np.argsort(self[by], kind="stable")
        if not ascending:
            order = order[::-1]
        return self.filter(order)

    def sample(self, fraction: float, seed: int = 0, replace: bool = False) -> "DataFrame":
        rng = np.random.default_rng(seed)
        n = len(self)
        if replace:
            idx = rng.integers(0, n, size=int(round(n * fraction)))
        else:
            idx = np.nonzero(rng.random(n) < fraction)[0]
        return self.filter(idx)

    def random_split(self, weights: Sequence[float], seed: int = 0) -> List["DataFrame"]:
        rng = np.random.default_rng(seed)
        n = len(self)
        w = np.asarray(weights, dtype=np.float64)
        w = w / w.sum()
        assignment = rng.choice(len(w), size=n, p=w)
        return [self.filter(assignment == i) for i in range(len(w))]

    def union(self, other: "DataFrame") -> "DataFrame":
        if set(self.columns) != set(other.columns):
            raise ValueError(f"union column mismatch: {self.columns} vs {other.columns}")
        return concat([self, other])

    def distinct(self) -> "DataFrame":
        keys = list(zip(*(self._hashable_col(n) for n in self.columns))) if self.columns else []
        seen: Dict[Any, int] = {}
        idx = []
        for i, k in enumerate(keys):
            if k not in seen:
                seen[k] = i
                idx.append(i)
        return self.filter(np.asarray(idx, dtype=np.int64))

    def drop_na(self, subset: Optional[List[str]] = None) -> "DataFrame":
        names = subset or self.columns
        mask = np.ones(len(self), dtype=bool)
        for n in names:
            col = self.column(n)
            v = col.values
            if v.dtype != object and v.dtype.kind == "f":
                fv = v.astype(np.float64)
                mask &= ~(np.isnan(fv) if fv.ndim == 1 else np.isnan(fv).any(axis=1))
            elif v.dtype == object:
                # object-backed numeric columns can carry float('nan') values
                mask &= np.array(
                    [x is not None and not (isinstance(x, float) and np.isnan(x)) for x in v]
                )
        return self.filter(mask)

    def _hashable_col(self, name: str) -> list:
        v = self[name]
        if v.ndim == 2:
            return [tuple(row) for row in v]
        return [x.item() if isinstance(x, np.generic) else x for x in v]

    # -- group/join (host-side relational ops used by SAR, stats, LIME) --------

    def group_by(self, *keys: str) -> "GroupedData":
        return GroupedData(self, list(keys))

    def join(self, other: "DataFrame", on: Union[str, List[str]], how: str = "inner") -> "DataFrame":
        """Vectorized hash/sort join (np.unique + searchsorted) — no per-row
        Python on the hot path, so reference-scale frames (millions of rows
        feeding SAR/stats) join at array speed. Emits inner pairs in left-row
        order (right matches in right order within a key), unmatched-left
        rows inline, unmatched-right appended — the same layout the previous
        dict-index implementation produced."""
        on_cols = [on] if isinstance(on, str) else list(on)
        nl, nr = len(self), len(other)
        lk, rk = _join_codes(self, other, on_cols)

        order = np.argsort(rk, kind="stable")
        rks = rk[order]
        lo = np.searchsorted(rks, lk, "left")
        hi = np.searchsorted(rks, lk, "right")
        cnt = hi - lo
        matched = cnt > 0
        left_keep = how in ("left", "left_outer", "outer", "full")
        cnt2 = np.where(matched, cnt, 1 if left_keep else 0)
        total = int(cnt2.sum())
        li_arr = np.repeat(np.arange(nl, dtype=np.int64), cnt2)
        # per-slot offsets within each left row's match group
        grp_pos = np.cumsum(cnt2) - cnt2
        off = np.arange(total, dtype=np.int64) - np.repeat(grp_pos, cnt2)
        ri_arr = np.full(total, -1, dtype=np.int64)
        fill = np.repeat(matched, cnt2)
        ri_arr[fill] = order[(np.repeat(lo, cnt2) + off)[fill]]
        if how in ("right", "right_outer", "outer", "full") and nr:
            mr = np.zeros(nr, bool)
            mr[ri_arr[ri_arr >= 0]] = True
            extra = np.nonzero(~mr)[0]
            li_arr = np.concatenate([li_arr, np.full(len(extra), -1, np.int64)])
            ri_arr = np.concatenate([ri_arr, extra.astype(np.int64)])
        cols: Dict[str, Column] = {}
        for n, c in self._columns.items():
            cols[n] = _gather_with_null(c, li_arr)
        for n, c in other._columns.items():
            if n in on_cols:
                # fill join keys from whichever side matched
                merged = _gather_with_null(c, ri_arr)
                base = cols[n]
                vals = base.values.copy()
                fill = li_arr < 0
                if fill.any():
                    vals[fill] = merged.values[fill]
                cols[n] = Column(vals, base.dtype, dict(base.metadata))
                continue
            name = n if n not in cols else f"{n}_right"
            cols[name] = _gather_with_null(c, ri_arr)
        return DataFrame(cols, self.num_partitions)

    # -- partitioning (logical workers; SURVEY.md §2.7 item 1) -----------------

    def repartition(self, n: int) -> "DataFrame":
        return DataFrame(dict(self._columns), num_partitions=n)

    def coalesce(self, n: int) -> "DataFrame":
        return self.repartition(min(n, self.num_partitions))

    def partition_bounds(self) -> List[Tuple[int, int]]:
        n = len(self)
        k = min(self.num_partitions, max(1, n)) if n else 1
        sizes = [n // k + (1 if i < n % k else 0) for i in range(k)]
        bounds, start = [], 0
        for s in sizes:
            bounds.append((start, start + s))
            start += s
        return bounds

    def partitions(self) -> Iterator["DataFrame"]:
        for start, stop in self.partition_bounds():
            yield DataFrame(
                {n: c.slice(start, stop) for n, c in self._columns.items()},
                num_partitions=1,
            )

    def map_partitions(self, fn: Callable[["DataFrame"], "DataFrame"]) -> "DataFrame":
        parts = [fn(p) for p in self.partitions()]
        return concat(parts).repartition(self.num_partitions)

    # -- device residency ------------------------------------------------------

    def to_device(self, *names: str, sharding: Any = None) -> "DataFrame":
        """Stage the named numeric/VECTOR columns (default: all of them)
        onto device HBM; returns a frame whose columns are device-backed so
        downstream device-consuming stages start with zero upload cost.
        Object-dtype columns are left host-side untouched."""
        targets = list(names) or [
            n for n, c in self._columns.items()
            if (c.dtype == DataType.VECTOR or c.dtype.is_numeric)
            and (c.is_device_backed or c.values.dtype != object)
        ]
        cols = dict(self._columns)
        for n in targets:
            col = self.column(n).view()
            col.device_values(sharding)
            cols[n] = col
        return DataFrame(cols, self.num_partitions)

    # -- materialization -------------------------------------------------------

    def to_dict(self) -> Dict[str, np.ndarray]:
        return {n: c.values for n, c in self._columns.items()}

    def collect(self) -> List[Dict[str, Any]]:
        names = self.columns
        out = []
        for i in range(len(self)):
            row = {}
            for n in names:
                v = self._columns[n].values[i]
                row[n] = v.item() if isinstance(v, np.generic) else v
            out.append(row)
        return out

    def head(self, n: int = 5) -> List[Dict[str, Any]]:
        return self.limit(n).collect()

    def cache(self) -> "DataFrame":
        return self  # eager already; hook kept for API parity (Cacher stage)

    def __repr__(self) -> str:
        fields = ", ".join(f"{f.name}: {f.dtype.value}" for f in self.schema)
        return f"DataFrame[{fields}] (n={len(self)}, partitions={self.num_partitions})"

    def show(self, n: int = 10) -> None:
        # show() IS stdout display (the Spark df.show() contract) — the one
        # deliberate print surface in the library, so the suppressions are
        # the documentation, not an escape hatch
        print(self.__repr__())  # graftcheck: ignore[unstructured-log-in-library]
        for row in self.head(n):
            print(row)  # graftcheck: ignore[unstructured-log-in-library]


def concat(frames: Sequence["DataFrame"]) -> "DataFrame":
    """Row-concatenate DataFrames with identical columns; each column is
    concatenated once (O(total) copying, unlike pairwise union)."""
    frames = [f for f in frames if len(f.columns)]
    if not frames:
        return DataFrame({})
    names = frames[0].columns
    for f in frames[1:]:
        if set(f.columns) != set(names):
            raise ValueError(f"concat column mismatch: {names} vs {f.columns}")
    cols = {}
    for n in names:
        first = frames[0].column(n)
        cols[n] = Column(
            np.concatenate([f.column(n).values for f in frames]),
            first.dtype,
            dict(first.metadata),
        )
    return DataFrame(cols, frames[0].num_partitions)


def _gather_with_null(col: Column, idx: np.ndarray) -> Column:
    """Gather rows by index; index -1 produces a null (NaN / None / 0)."""
    has_null = (idx < 0).any()
    safe = np.where(idx < 0, 0, idx)
    vals = col.values[safe]
    if has_null:
        nulls = idx < 0
        if vals.dtype == object:
            vals = vals.copy()
            vals[nulls] = None
        elif vals.dtype.kind == "f" or col.dtype == DataType.VECTOR:
            vals = vals.astype(np.float64, copy=True)
            vals[nulls] = np.nan
        elif vals.dtype.kind in "USM":
            # Fixed-width strings and timestamps can't hold NaN; widen to
            # object with None so no silent corruption (timestamps would
            # otherwise become raw-tick doubles).
            out = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                out[i] = None if nulls[i] else (v.item() if isinstance(v, np.generic) else v)
            return Column(out, col.dtype, dict(col.metadata))
        else:
            vals = vals.astype(np.float64)
            vals[nulls] = np.nan
            return Column(vals, DataType.DOUBLE, dict(col.metadata))
    return Column(vals, col.dtype, dict(col.metadata))


def _factorize(vals: np.ndarray) -> np.ndarray:
    """(n,) or (n, d) values -> (n,) int64 codes; equal values (rows for
    2-D / VECTOR columns) share a code."""
    arr = np.asarray(vals)
    if arr.dtype != object and arr.dtype.kind in "biufSUM":
        if arr.ndim == 2:  # VECTOR column: factorize whole rows
            _, inv = np.unique(arr, axis=0, return_inverse=True)
        else:
            _, inv = np.unique(arr, return_inverse=True)
        return inv.astype(np.int64).reshape(-1)
    codes = np.empty(len(arr), np.int64)
    lookup: Dict[Any, int] = {}
    for i, v in enumerate(arr):
        if isinstance(v, np.ndarray):  # unhashable cell
            v = tuple(v.tolist())
        codes[i] = lookup.setdefault(v, len(lookup))
    return codes


def _multi_codes(cols: List[np.ndarray]) -> np.ndarray:
    """Combine per-column codes into one int64 code (mixed radix). Codes
    re-compress (np.unique) whenever the running radix product would
    overflow int64 — silent wraparound would alias distinct keys."""
    combined = cols[0].astype(np.int64)
    cmax = int(combined.max()) + 1 if len(combined) else 1
    for c in cols[1:]:
        radix = int(c.max()) + 1 if len(c) else 1
        if cmax > (2 ** 62) // max(radix, 1):
            _, inv = np.unique(combined, return_inverse=True)
            combined = inv.astype(np.int64)
            cmax = int(combined.max()) + 1 if len(combined) else 1
        combined = combined * radix + c
        cmax = cmax * radix
    return combined


def _kind_class(arr: np.ndarray) -> str:
    if arr.dtype == object:
        return "object"
    return {"b": "num", "i": "num", "u": "num", "f": "num",
            "S": "str", "U": "str", "M": "time"}.get(arr.dtype.kind, "object")


def _join_codes(
    left: "DataFrame", right: "DataFrame", on_cols: List[str]
) -> Tuple[np.ndarray, np.ndarray]:
    """Shared-code-space factorization of the join keys of both frames.
    Mismatched key families (numeric vs string) go through the object path
    so numpy's implicit int->str stringification can't fabricate matches;
    there, int 1 and str '1' stay distinct dict keys (zero matches, the
    pre-vectorization behavior). Numeric int/float promotion is kept —
    hash(1) == hash(1.0) matched in the old dict index too."""
    nl = len(left)
    per_col = []
    for k in on_cols:
        lv, rv = left[k], right[k]
        same_family = _kind_class(lv) == _kind_class(rv) != "object"
        if same_family and lv.ndim == rv.ndim:
            both = np.concatenate([lv, rv])
        else:
            both = np.concatenate(
                [np.asarray(lv, dtype=object), np.asarray(rv, dtype=object)]
            )
        per_col.append(_factorize(both))
    codes = _multi_codes(per_col)
    return codes[:nl], codes[nl:]


class GroupedData:
    """Minimal groupBy support: agg with named aggregations, and apply().

    Group discovery is vectorized (factorize -> stable argsort -> split), so
    reference-scale frames group at array speed; only `apply` and
    `collect_list` materialize per-group Python objects."""

    _AGGS = {
        "sum": np.sum,
        "mean": np.mean,
        "avg": np.mean,
        "min": np.min,
        "max": np.max,
        "count": len,
        "first": lambda v: v[0],
        "collect_list": list,
    }

    def __init__(self, df: DataFrame, keys: List[str]):
        self.df = df
        self.keys = keys
        n = len(df)
        self._groups: Dict[Any, np.ndarray] = {}
        if n == 0:
            return
        codes = _multi_codes([_factorize(df[k]) for k in keys])
        order = np.argsort(codes, kind="stable")
        sorted_codes = codes[order]
        # boundaries of equal-code runs -> per-group row-index arrays
        starts = np.nonzero(np.r_[True, sorted_codes[1:] != sorted_codes[:-1]])[0]
        groups = np.split(order, starts[1:])
        # first-appearance order (the old dict preserved insertion order)
        groups.sort(key=lambda g: int(g[0]))
        key_arrays = [df[k] for k in keys]

        def cell(a, i):
            v = a[i]
            if isinstance(v, np.generic):
                return v.item()
            if isinstance(v, np.ndarray):  # VECTOR key row
                return tuple(v.tolist())
            return v

        for g in groups:
            i0 = int(g[0])
            self._groups[tuple(cell(a, i0) for a in key_arrays)] = g

    def agg(self, **named_aggs: Tuple[str, str]) -> DataFrame:
        """agg(total=("amount","sum"), n=("amount","count"))"""
        out: Dict[str, list] = {k: [] for k in self.keys}
        for name in named_aggs:
            out[name] = []
        for key, idx in self._groups.items():
            for kname, kval in zip(self.keys, key):
                out[kname].append(kval)
            for name, (src, how) in named_aggs.items():
                vals = self.df[src][np.asarray(idx)]
                out[name].append(self._AGGS[how](vals))
        return DataFrame.from_dict(out, self.df.num_partitions)

    def apply(self, fn: Callable[[Tuple, DataFrame], Dict[str, Any]]) -> DataFrame:
        """mapGroups: fn(key_tuple, group_df) -> one output row (dict)."""
        rows = []
        for key, idx in self._groups.items():
            group = self.df.filter(np.asarray(idx))
            rows.append(fn(key, group))
        return DataFrame.from_rows(rows, self.df.num_partitions)

    def count(self) -> DataFrame:
        out: Dict[str, list] = {k: [] for k in self.keys}
        out["count"] = []
        for key, idx in self._groups.items():
            for kname, kval in zip(self.keys, key):
                out[kname].append(kval)
            out["count"].append(len(idx))
        return DataFrame.from_dict(out, self.df.num_partitions)
