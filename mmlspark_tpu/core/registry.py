"""Stage registry: enumerate every pipeline stage in the library.

Reference: core/utils JarLoadingUtils classpath scan that seeds FuzzingTest
(core/test/fuzzing/src/test/scala/FuzzingTest.scala:15-56) and codegen
(codegen/src/main/scala/CodeGen.scala:44-98). The Python analog is an
import-walk over the package: every concrete public subclass of
PipelineStage is registered, and the fuzzing sweep (tests/test_fuzzing.py)
asserts each one is either exercised or explicitly exempted — nothing ships
untested by omission.
"""

from __future__ import annotations

import importlib
import inspect
import pkgutil
from typing import Dict, List, Type

# Abstract surface (never registered): the pipeline contract classes and
# param-holder bases.
_BASE_NAMES = {
    "PipelineStage", "Transformer", "Estimator", "Model",
    "Pipeline", "PipelineModel",
}


def all_stage_classes(refresh: bool = False) -> Dict[str, Type]:
    """{fully.qualified.Name: class} for every concrete public stage.

    Walks (and imports) every module under mmlspark_tpu, so the result is
    complete regardless of what the caller already imported.
    """
    global _CACHE
    if _CACHE is not None and not refresh:
        return dict(_CACHE)
    import mmlspark_tpu
    from mmlspark_tpu.core.pipeline import PipelineStage

    out: Dict[str, Type] = {}
    for modinfo in pkgutil.walk_packages(
        mmlspark_tpu.__path__, prefix="mmlspark_tpu."
    ):
        try:
            mod = importlib.import_module(modinfo.name)
        except Exception as e:  # pragma: no cover - import failure is a bug
            raise ImportError(f"registry cannot import {modinfo.name}: {e!r}")
        for name, obj in vars(mod).items():
            if (
                inspect.isclass(obj)
                and issubclass(obj, PipelineStage)
                and obj.__module__ == modinfo.name  # defining module only
                and not name.startswith("_")
                and name not in _BASE_NAMES
                and not inspect.isabstract(obj)
            ):
                out[f"{obj.__module__}.{name}"] = obj
    _CACHE = dict(out)
    return out


_CACHE = None


def stage_names() -> List[str]:
    return sorted(all_stage_classes())
