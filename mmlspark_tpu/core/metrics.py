"""Metric name constants shared by evaluation modules.

Reference: core/metrics MetricConstants.scala / MetricUtils.scala.
"""

# classification
ACCURACY = "accuracy"
PRECISION = "precision"
RECALL = "recall"
AUC = "AUC"
F1 = "f1"
# regression
MSE = "mse"
RMSE = "rmse"
R2 = "r2"
MAE = "mae"
# meta
ALL = "all"

CLASSIFICATION_METRICS = [ACCURACY, PRECISION, RECALL, AUC, F1]
REGRESSION_METRICS = [MSE, RMSE, R2, MAE]

# column names produced by scoring models (kept stable for API parity)
SCORES_COL = "scores"
SCORED_LABELS_COL = "scored_labels"
SCORED_PROBABILITIES_COL = "scored_probabilities"
PREDICTION_COL = "prediction"

LARGER_IS_BETTER = {ACCURACY: True, PRECISION: True, RECALL: True, AUC: True, F1: True,
                    MSE: False, RMSE: False, R2: True, MAE: False}


def is_classification_metric(name: str) -> bool:
    return name in CLASSIFICATION_METRICS


def is_regression_metric(name: str) -> bool:
    return name in REGRESSION_METRICS
