"""Typed parameter system for pipeline stages.

TPU-native re-design of the reference's param machinery:
- SparkML `Params` traits + MMLSpark's `Wrappable`/`Has*Col` mixins
  (reference: src/core/contracts/src/main/scala/Params.scala:10-141)
- the ComplexParam zoo for values JSON can't carry
  (reference: src/core/serialize/src/main/scala/params/*.scala)

Params metadata is the single source of truth for the public API: persistence
(core/serialize.py), doc/wrapper generation (codegen/) and the fuzzing test
harness all reflect over it, exactly as the reference's codegen reflects over
Spark Params (src/codegen/src/main/scala/CodeGen.scala:44-98).
"""

from __future__ import annotations

import copy
import json
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


class TypeConverters:
    """Value coercion/validation helpers attached to `Param.type_converter`.

    Mirrors the role of pyspark.ml.param.TypeConverters so generated wrappers
    behave identically for users coming from the reference API.
    """

    @staticmethod
    def to_int(value: Any) -> int:
        if isinstance(value, bool):
            raise TypeError(f"Could not convert {value!r} to int")
        if isinstance(value, int):
            return value
        if isinstance(value, float) and value.is_integer():
            return int(value)
        raise TypeError(f"Could not convert {value!r} to int")

    @staticmethod
    def to_float(value: Any) -> float:
        if isinstance(value, bool):
            raise TypeError(f"Could not convert {value!r} to float")
        if isinstance(value, (int, float)):
            return float(value)
        raise TypeError(f"Could not convert {value!r} to float")

    @staticmethod
    def to_string(value: Any) -> str:
        if isinstance(value, str):
            return value
        raise TypeError(f"Could not convert {value!r} to str")

    @staticmethod
    def to_boolean(value: Any) -> bool:
        if isinstance(value, bool):
            return value
        raise TypeError(f"Could not convert {value!r} to bool")

    @staticmethod
    def to_list(value: Any) -> list:
        if isinstance(value, (list, tuple)):
            return list(value)
        raise TypeError(f"Could not convert {value!r} to list")

    @staticmethod
    def to_list_int(value: Any) -> List[int]:
        return [TypeConverters.to_int(v) for v in TypeConverters.to_list(value)]

    @staticmethod
    def to_list_float(value: Any) -> List[float]:
        return [TypeConverters.to_float(v) for v in TypeConverters.to_list(value)]

    @staticmethod
    def to_list_string(value: Any) -> List[str]:
        return [TypeConverters.to_string(v) for v in TypeConverters.to_list(value)]

    @staticmethod
    def to_dict(value: Any) -> dict:
        if isinstance(value, dict):
            return dict(value)
        raise TypeError(f"Could not convert {value!r} to dict")

    @staticmethod
    def identity(value: Any) -> Any:
        return value


def _json_ok(value: Any) -> bool:
    try:
        json.dumps(value)
        return True
    except (TypeError, ValueError):  # ValueError: circular containers
        return False


def check_json_simple(owner: str, name: str, value: Any) -> None:
    """Shared validation for simple (non-complex) param values: must be
    JSON-serializable or declared as ComplexParam. Used by persistence for
    both the set and default param maps so the rule can't drift."""
    if not _json_ok(value):
        raise TypeError(
            f"Non-JSON-serializable simple param {name!r} on {owner}; "
            "declare it as ComplexParam"
        )


class Param:
    """A named, documented, typed parameter declared on a `Params` class.

    Declared at class level; instances of the owning class carry values in
    their own `_param_map`, so Param objects are shared and immutable.
    """

    def __init__(
        self,
        name: str,
        doc: str,
        type_converter: Optional[Callable[[Any], Any]] = None,
        is_complex: bool = False,
    ):
        self.name = name
        self.doc = doc
        self.type_converter = type_converter or TypeConverters.identity
        # Complex params hold values JSON can't represent (models, arrays,
        # callables); persistence routes them through ComplexParamIO.
        self.is_complex = is_complex

    def __repr__(self) -> str:
        return f"Param({self.name!r})"

    def __hash__(self) -> int:
        return hash(self.name)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Param) and other.name == self.name


class ComplexParam(Param):
    """Param whose value is an arbitrary object (stage, model, array, fn).

    Reference: the 16 ComplexParam subtypes under
    src/core/serialize/src/main/scala/params/ (EstimatorParam,
    TransformerParam, UDFParam, DataFrameParam, ArrayParam, ...). Here a
    single class suffices — Python values self-describe and serialize.py
    dispatches on runtime type.
    """

    def __init__(self, name: str, doc: str):
        super().__init__(name, doc, TypeConverters.identity, is_complex=True)


class Params:
    """Base class carrying a param map; every pipeline stage derives from it.

    API kept close to pyspark.ml.param.Params (get/set/hasDefault/
    explainParams/copy) so reference users can switch without relearning.
    """

    def __init__(self) -> None:
        self._param_map: Dict[Param, Any] = {}
        self._default_param_map: Dict[Param, Any] = {}
        self.uid = f"{type(self).__name__}_{id(self):x}"

    # -- declaration/introspection ------------------------------------------------

    @classmethod
    def params(cls) -> List[Param]:
        """All Param objects declared on the class (and bases), sorted by name."""
        seen: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for value in vars(klass).values():
                if isinstance(value, Param):
                    seen[value.name] = value
        return sorted(seen.values(), key=lambda p: p.name)

    def get_param(self, name: str) -> Param:
        for p in self.params():
            if p.name == name:
                return p
        raise AttributeError(f"{type(self).__name__} has no param {name!r}")

    def has_param(self, name: str) -> bool:
        return any(p.name == name for p in self.params())

    # -- get/set -------------------------------------------------------------------

    def _resolve(self, param) -> Param:
        if isinstance(param, str):
            return self.get_param(param)
        if not self.has_param(param.name):
            raise AttributeError(
                f"{type(self).__name__} has no param {param.name!r}"
            )
        return param

    def set(self, param, value: Any) -> "Params":
        param = self._resolve(param)
        self._param_map[param] = param.type_converter(value)
        return self

    def set_params(self, **kwargs: Any) -> "Params":
        for name, value in kwargs.items():
            self.set(name, value)
        return self

    def _set_default(self, param, value: Any) -> "Params":
        param = self._resolve(param)
        self._default_param_map[param] = value
        return self

    def _set_defaults(self, **kwargs: Any) -> "Params":
        for name, value in kwargs.items():
            self._set_default(name, value)
        return self

    def is_set(self, param) -> bool:
        return self._resolve(param) in self._param_map

    def has_default(self, param) -> bool:
        return self._resolve(param) in self._default_param_map

    def is_defined(self, param) -> bool:
        return self.is_set(param) or self.has_default(param)

    def get(self, param) -> Any:
        param = self._resolve(param)
        if param in self._param_map:
            return self._param_map[param]
        if param in self._default_param_map:
            return self._default_param_map[param]
        raise KeyError(
            f"Param {param.name!r} is not set and has no default on "
            f"{type(self).__name__}"
        )

    def get_or_default(self, param, default: Any = None) -> Any:
        try:
            param = self._resolve(param)
        except AttributeError:
            # tolerate params the class doesn't declare: generic flows probe
            # e.g. "probability_col" across heterogeneous models
            return default
        if self.is_defined(param):
            return self.get(param)
        return default

    def clear(self, param) -> "Params":
        self._param_map.pop(self._resolve(param), None)
        return self

    # -- docs / copy / compare ------------------------------------------------------

    def explain_param(self, param) -> str:
        param = self._resolve(param)
        value_str = (
            f"current: {self._param_map[param]!r}"
            if param in self._param_map
            else (
                f"default: {self._default_param_map[param]!r}"
                if param in self._default_param_map
                else "undefined"
            )
        )
        return f"{param.name}: {param.doc} ({value_str})"

    def explain_params(self) -> str:
        return "\n".join(self.explain_param(p) for p in self.params())

    def copy(self, extra: Optional[Dict[Param, Any]] = None) -> "Params":
        that = copy.copy(self)
        that._param_map = dict(self._param_map)
        that._default_param_map = dict(self._default_param_map)
        if extra:
            for param, value in extra.items():
                that.set(param, value)
        return that

    def extract_param_map(self) -> Dict[Param, Any]:
        merged = dict(self._default_param_map)
        merged.update(self._param_map)
        return merged

    def _simple_params_json(self) -> str:
        """JSON of all set non-complex params (for persistence metadata).

        Fails loudly on non-JSON-serializable values: such params must be
        declared ComplexParam so persistence routes them through the
        type-dispatched complex writers instead of silently stringifying.
        """
        out = {}
        for param, value in self._param_map.items():
            if not param.is_complex:
                out[param.name] = value
        for name, v in out.items():
            check_json_simple(type(self).__name__, name, v)
        return json.dumps(out, sort_keys=True)

    def _complex_params(self) -> Iterator[Tuple[Param, Any]]:
        for param, value in self._param_map.items():
            if param.is_complex:
                yield param, value


# ---------------------------------------------------------------------------
# Shared column-param mixins (reference: core/contracts Params.scala:10-141).
# These keep the input/output column contract uniform across every stage.
# ---------------------------------------------------------------------------


class HasInputCol(Params):
    input_col = Param("input_col", "The name of the input column", TypeConverters.to_string)

    def set_input_col(self, value: str):
        return self.set(self.input_col, value)

    def get_input_col(self) -> str:
        return self.get(self.input_col)


class HasOutputCol(Params):
    output_col = Param("output_col", "The name of the output column", TypeConverters.to_string)

    def set_output_col(self, value: str):
        return self.set(self.output_col, value)

    def get_output_col(self) -> str:
        return self.get(self.output_col)


class HasInputCols(Params):
    input_cols = Param("input_cols", "The names of the input columns", TypeConverters.to_list_string)

    def set_input_cols(self, value: List[str]):
        return self.set(self.input_cols, value)

    def get_input_cols(self) -> List[str]:
        return self.get(self.input_cols)


class HasOutputCols(Params):
    output_cols = Param("output_cols", "The names of the output columns", TypeConverters.to_list_string)

    def set_output_cols(self, value: List[str]):
        return self.set(self.output_cols, value)

    def get_output_cols(self) -> List[str]:
        return self.get(self.output_cols)


class HasLabelCol(Params):
    label_col = Param("label_col", "The name of the label column", TypeConverters.to_string)

    def set_label_col(self, value: str):
        return self.set(self.label_col, value)

    def get_label_col(self) -> str:
        return self.get(self.label_col)


class HasFeaturesCol(Params):
    features_col = Param("features_col", "The name of the features column", TypeConverters.to_string)

    def set_features_col(self, value: str):
        return self.set(self.features_col, value)

    def get_features_col(self) -> str:
        return self.get(self.features_col)


class HasWeightCol(Params):
    weight_col = Param("weight_col", "The name of the weight column", TypeConverters.to_string)

    def set_weight_col(self, value: str):
        return self.set(self.weight_col, value)

    def get_weight_col(self) -> str:
        return self.get(self.weight_col)


class HasScoredLabelsCol(Params):
    scored_labels_col = Param(
        "scored_labels_col",
        "Scored labels column name, only required if using SparkML estimators",
        TypeConverters.to_string,
    )

    def set_scored_labels_col(self, value: str):
        return self.set(self.scored_labels_col, value)

    def get_scored_labels_col(self) -> str:
        return self.get(self.scored_labels_col)


class HasScoresCol(Params):
    scores_col = Param("scores_col", "Scores or raw prediction column name", TypeConverters.to_string)

    def set_scores_col(self, value: str):
        return self.set(self.scores_col, value)

    def get_scores_col(self) -> str:
        return self.get(self.scores_col)


class HasScoredProbabilitiesCol(Params):
    scored_probabilities_col = Param(
        "scored_probabilities_col", "Scored probabilities column name", TypeConverters.to_string
    )

    def set_scored_probabilities_col(self, value: str):
        return self.set(self.scored_probabilities_col, value)

    def get_scored_probabilities_col(self) -> str:
        return self.get(self.scored_probabilities_col)


class HasEvaluationMetric(Params):
    evaluation_metric = Param("evaluation_metric", "Metric to evaluate models with", TypeConverters.to_string)

    def set_evaluation_metric(self, value: str):
        return self.set(self.evaluation_metric, value)

    def get_evaluation_metric(self) -> str:
        return self.get(self.evaluation_metric)


class Wrappable:
    """Marker mixin: stage participates in doc/wrapper generation and the
    whole-library fuzzing sweep (reference: Wrappable in core/contracts)."""
