"""Stage persistence: save/load any PipelineStage to a directory.

TPU-native equivalent of the reference's ConstructorWritable/Readable +
ComplexParam serialization (src/core/serialize/src/main/scala/Serializer.scala:21-200,
ConstructorWriter.scala). Layout per stage directory:

    metadata.json      {"class": "module.Class", "params": {...simple...},
                        "complex": {"name": "<kind>"}, "version": ...}
    complex/<name>/    nested stage dirs, or
    complex/<name>.npz numpy arrays, or
    complex/<name>.json json-able payloads, or
    complex/<name>.pkl  pickle fallback (callables excluded)

Class resolution happens through an import-based registry — the analog of the
reference's classpath scan (JarLoadingUtils.scala:18-148).

Trust boundary: complex params and object columns fall back to pickle, so a
saved stage directory carries pickle semantics — loading one from an
untrusted source can execute arbitrary code. Treat stage directories like
model checkpoints: trusted input only.
"""

from __future__ import annotations

import importlib
import inspect
import json
import os
import pickle
from typing import Any, Dict

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.core.params import Params, check_json_simple

_FORMAT_VERSION = 1


def _class_path(obj: Any) -> str:
    return f"{type(obj).__module__}.{type(obj).__qualname__}"


def _resolve_class(path: str):
    module, _, name = path.rpartition(".")
    mod = importlib.import_module(module)
    if module == "__main__" and not hasattr(mod, name.split(".")[0]):
        raise ImportError(
            f"Stage class {path!r} was defined in __main__ of the saving process "
            "and cannot be resolved here. Define stage classes in an importable "
            "module to make saved pipelines portable across processes."
        )
    obj = mod
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


def save_stage(stage: Params, path: str, overwrite: bool = False) -> None:
    from mmlspark_tpu.io.checkpoint import staged_dir

    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists; pass overwrite=True")
    # The whole save is built in a unique sibling staging dir and swapped
    # in atomically with every file fsynced first (io/checkpoint.staged_dir)
    # — a mid-save failure never destroys a previous good save at `path`,
    # and tmp+os.replace alone would NOT be durable across power loss (the
    # rename can land while the data blocks it points at never did).
    with staged_dir(path) as tmp:
        _write_stage(stage, tmp)


def _write_stage(stage: Params, tmp_path: str) -> None:
    # `tmp_path` by contract: always a staging dir save_stage later
    # publishes atomically — writes here are never visible at a final path.
    meta: Dict[str, Any] = {
        "class": _class_path(stage),
        "version": _FORMAT_VERSION,
        "params": json.loads(stage._simple_params_json()),
        "default_params": {},
        "complex": {},
        "complex_defaults": {},
        "init_args": {},
    }
    complex_dir = os.path.join(tmp_path, "complex")
    # Persist the default param map too (reference serializes defaultParamMap:
    # ComplexParamsSerializer semantics) so stages whose __init__ takes
    # required args still round-trip their defaults.
    for param, value in stage._default_param_map.items():
        if param.is_complex:
            os.makedirs(complex_dir, exist_ok=True)
            meta["complex_defaults"][param.name] = _save_complex(
                value, complex_dir, f"_default_{param.name}"
            )
        else:
            check_json_simple(type(stage).__name__, param.name, value)
            meta["default_params"][param.name] = value
    for param, value in stage._complex_params():
        os.makedirs(complex_dir, exist_ok=True)
        meta["complex"][param.name] = _save_complex(value, complex_dir, param.name)
    # ConstructorWritable equivalent (reference: ConstructorWriter.scala —
    # objectsToSave): a stage whose __init__ takes required args declares
    # `_init_args() -> dict` naming them; they are saved through the complex
    # dispatch and fed back to __init__ on load, so instance state built in
    # __init__ is fully reconstructed.
    if hasattr(stage, "_init_args"):
        for name, value in stage._init_args().items():
            os.makedirs(complex_dir, exist_ok=True)
            meta["init_args"][name] = _save_complex(value, complex_dir, f"_init_{name}")
    with open(os.path.join(tmp_path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)


def load_stage(path: str) -> Params:
    from mmlspark_tpu.io.checkpoint import CorruptArtifactError

    recovery = (
        "Re-save the stage, or restore the directory from a backup/"
        "checkpoint generation. The atomic save protocol means a crash "
        "mid-save preserves the previous good artifact at this path — a "
        "missing or truncated metadata.json indicates the directory was "
        "built by hand or damaged after the fact."
    )
    try:
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
    except FileNotFoundError:
        raise CorruptArtifactError(
            path, "not a stage directory: metadata.json is missing", recovery
        ) from None
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CorruptArtifactError(
            path, f"metadata.json is truncated or garbled ({e})", recovery
        ) from None
    cls = _resolve_class(meta["class"])
    stage = cls.__new__(cls)
    Params.__init__(stage)
    complex_dir = os.path.join(path, "complex")
    init_kinds = meta.get("init_args", {})
    if init_kinds:
        # ConstructorWritable path: re-run __init__ with the persisted args so
        # non-param instance state is rebuilt exactly as at save time.
        kwargs = {
            name: _load_complex(kind, complex_dir, f"_init_{name}")
            for name, kind in init_kinds.items()
        }
        cls.__init__(stage, **kwargs)
    elif _init_is_arg_free(cls):
        cls.__init__(stage)
    # Stages with required __init__ args and no _init_args() protocol only
    # round-trip param state; non-param attributes set in __init__ are lost.
    for name, value in meta.get("default_params", {}).items():
        stage._set_default(name, value)
    for name, kind in meta.get("complex_defaults", {}).items():
        stage._set_default(name, _load_complex(kind, complex_dir, f"_default_{name}"))
    for name, value in meta["params"].items():
        stage.set(name, value)
    for name, kind in meta.get("complex", {}).items():
        stage.set(name, _load_complex(kind, complex_dir, name))
    return stage


def _init_is_arg_free(cls) -> bool:
    try:
        sig = inspect.signature(cls.__init__)
    except (TypeError, ValueError):
        return False
    for p in list(sig.parameters.values())[1:]:  # skip self
        if p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
            continue
        if p.default is p.empty:
            return False
    return True


# -- complex value dispatch ---------------------------------------------------


def _json_keys_safe(value: Any) -> bool:
    """True when JSON encoding round-trips exactly: every dict key
    (recursively) is already a str and no tuples (JSON would reload them
    as lists)."""
    if isinstance(value, dict):
        return all(
            isinstance(k, str) and _json_keys_safe(v) for k, v in value.items()
        )
    if isinstance(value, tuple):
        return False
    if isinstance(value, list):
        return all(_json_keys_safe(v) for v in value)
    return True


def _save_complex(value: Any, tmp_dir: str, name: str) -> str:
    # `tmp_dir` by contract: the complex/ dir of a STAGED save
    # (save_stage's tmp), so direct writes here never touch a final path.
    # Nested stages/frames write STRAIGHT into the outer staging tree
    # (_write_stage/_write_dataframe, no per-child staging+publish): the
    # outermost save's single fsync pass + atomic swap covers the whole
    # tree, so per-child durability dances would only multiply fsyncs.
    if isinstance(value, list) and value and all(isinstance(v, Params) for v in value):
        tmp_sub = os.path.join(tmp_dir, name)
        os.makedirs(tmp_sub, exist_ok=True)
        with open(os.path.join(tmp_sub, "_list.json"), "w") as f:
            json.dump({"n": len(value)}, f)
        for i, stage in enumerate(value):
            child = os.path.join(tmp_sub, str(i))
            os.makedirs(child, exist_ok=True)
            _write_stage(stage, child)
        return "stage_list"
    if isinstance(value, Params):
        child = os.path.join(tmp_dir, name)
        os.makedirs(child, exist_ok=True)
        _write_stage(value, child)
        return "stage"
    if isinstance(value, DataFrame):
        child = os.path.join(tmp_dir, name)
        os.makedirs(child, exist_ok=True)
        _write_dataframe(value, child)
        return "dataframe"
    if isinstance(value, np.ndarray):
        np.save(os.path.join(tmp_dir, f"{name}.npy"), value, allow_pickle=False)
        return "ndarray"
    if (
        isinstance(value, dict)
        and all(isinstance(k, str) for k in value)  # np.savez(**) needs str keys
        and all(isinstance(v, np.ndarray) for v in value.values())
    ):
        np.savez(os.path.join(tmp_dir, f"{name}.npz"), **value)
        return "ndarray_dict"
    if isinstance(value, (str, int, float, bool, list, dict, type(None))):
        # json.dump silently STRINGIFIES non-str dict keys (float 1.0 ->
        # "1.0"), corrupting lookup tables like ClassBalancerModel.weights;
        # only JSON-encode values that round-trip exactly
        if _json_keys_safe(value):
            try:
                with open(os.path.join(tmp_dir, f"{name}.json"), "w") as f:
                    json.dump(value, f)
                return "json"
            except TypeError:
                pass
    if hasattr(value, "save_to_dir") and hasattr(type(value), "load_from_dir"):
        tmp_sub = os.path.join(tmp_dir, name)
        # protocol guarantee kept from before ISSUE 8: the target dir
        # exists when save_to_dir runs (external custom classes rely on it)
        os.makedirs(tmp_sub, exist_ok=True)
        # save_to_dir first: directory-replacing implementations (Network)
        # atomically swap tmp_sub, so the marker must be written after
        value.save_to_dir(tmp_sub)
        os.makedirs(tmp_sub, exist_ok=True)
        with open(os.path.join(tmp_sub, "_custom.json"), "w") as f:
            json.dump({"class": _class_path(value)}, f)
        return "custom"
    with open(os.path.join(tmp_dir, f"{name}.pkl"), "wb") as f:
        pickle.dump(value, f)
    return "pickle"


def _load_complex(kind: str, directory: str, name: str) -> Any:
    if kind == "stage":
        return load_stage(os.path.join(directory, name))
    if kind == "stage_list":
        sub = os.path.join(directory, name)
        with open(os.path.join(sub, "_list.json")) as f:
            n = json.load(f)["n"]
        return [load_stage(os.path.join(sub, str(i))) for i in range(n)]
    if kind == "dataframe":
        return load_dataframe(os.path.join(directory, name))
    if kind == "ndarray":
        return np.load(os.path.join(directory, f"{name}.npy"), allow_pickle=False)
    if kind == "ndarray_dict":
        with np.load(os.path.join(directory, f"{name}.npz")) as z:
            return {k: z[k] for k in z.files}
    if kind == "json":
        with open(os.path.join(directory, f"{name}.json")) as f:
            return json.load(f)
    if kind == "custom":
        sub = os.path.join(directory, name)
        with open(os.path.join(sub, "_custom.json")) as f:
            cls = _resolve_class(json.load(f)["class"])
        return cls.load_from_dir(sub)
    if kind == "pickle":
        with open(os.path.join(directory, f"{name}.pkl"), "rb") as f:
            return pickle.load(f)
    raise ValueError(f"Unknown complex param kind {kind!r}")


# -- DataFrame persistence ----------------------------------------------------


def save_dataframe(df: DataFrame, path: str) -> None:
    # Atomic like save_stage: staged in a tmp sibling, swapped in whole, so
    # a crash mid-save never leaves a schema.json/npz torn hybrid or
    # destroys a previous good frame at `path`.
    from mmlspark_tpu.io.checkpoint import staged_dir

    with staged_dir(path) as tmp:
        _write_dataframe(df, tmp)


def _write_dataframe(df: DataFrame, tmp_path: str) -> None:
    # `tmp_path` by contract: a staging dir published atomically by the
    # caller (save_dataframe's staged_dir, or an enclosing stage save).
    numeric = {}
    objects = {}
    meta = {"fields": [], "num_partitions": df.num_partitions, "n": len(df)}
    for field in df.schema:
        col = df.column(field.name)
        meta["fields"].append(
            {"name": field.name, "dtype": field.dtype.value, "metadata": field.metadata}
        )
        if col.values.dtype == object:
            objects[field.name] = col.values
        else:
            numeric[field.name] = col.values
    if numeric:
        np.savez(os.path.join(tmp_path, "numeric.npz"), **numeric)
    if objects:
        with open(os.path.join(tmp_path, "objects.pkl"), "wb") as f:
            pickle.dump({k: list(v) for k, v in objects.items()}, f)
    with open(os.path.join(tmp_path, "schema.json"), "w") as f:
        json.dump(meta, f, indent=1)


def load_dataframe(path: str) -> DataFrame:
    with open(os.path.join(path, "schema.json")) as f:
        meta = json.load(f)
    numeric = {}
    npz_path = os.path.join(path, "numeric.npz")
    if os.path.exists(npz_path):
        with np.load(npz_path) as z:
            numeric = {k: z[k] for k in z.files}
    objects = {}
    pkl_path = os.path.join(path, "objects.pkl")
    if os.path.exists(pkl_path):
        with open(pkl_path, "rb") as f:
            objects = pickle.load(f)
    data = {}
    types = {}
    metadata = {}
    for field in meta["fields"]:
        name = field["name"]
        types[name] = DataType(field["dtype"])
        metadata[name] = field["metadata"]
        data[name] = numeric.get(name, objects.get(name))
    df = DataFrame.from_dict(data, meta["num_partitions"], types, metadata)
    return df
