"""Shape-bucketed compiled-dispatch cache — one XLA program per bucket.

Serving traffic arrives at ragged batch sizes; jit compiles one program per
input shape, so naive dispatch recompiles per distinct batch size (the TPU
analog of the reference re-allocating JNI minibatch buffers per batch,
CNTKModel.scala:71-140). This module generalizes the old per-module
`_FWD_CACHE` in models/tpu_model.py into the process-wide policy every
device-consuming stage shares:

- **Bucketing**: row counts round up to the next power of two (capped at the
  stage's mini_batch_size), so any traffic mix hits at most
  ``log2(max_batch) + 1`` compiled programs. Padded rows repeat the last
  real row (valid network inputs) and are sliced off after dispatch.
- **Compile accounting**: the cache notes each (program, input shape) pair
  the first time it is dispatched and reports it to
  utils.profiling.dataplane_counters() — compiles are a measured metric
  (bench.py --smoke), not a guess.
- **Bounded retention**: compiled callables evict FIFO past `max_fns`, same
  bound the old _FWD_CACHE had.

`bucketing(False)` restores the pre-bucketing behavior (pad every batch to
the full cap) — the rollback lever and the baseline bench.py --smoke
measures against.

- **Donation**: callers that OWN an input buffer (a freshly uploaded or
  freshly padded batch nobody will read again) may dispatch through a
  donating program (``jax.jit(..., donate_argnums=...)``): XLA releases —
  and where shapes/dtypes line up, reuses — the input's HBM at dispatch
  instead of holding it until Python GC. Under steady serving traffic this
  is the difference between bounded HBM churn and per-request buffer
  accumulation. ``donation(False)`` is the scoped rollback lever, mirroring
  ``bucketing(False)``; donating and non-donating variants are distinct
  compiled programs, so they must use distinct cache/accounting keys.
- **AOT cost-model capture**: hot-path callers dispatch through
  ``aot_program``, which compiles each (key, signature) ONCE via jax's AOT
  path (``jit_fn.lower(...).compile()``) — the compile is *timed* into the
  ``dispatch_compile_seconds{site}`` histogram and the executable's
  ``cost_analysis()`` (flops, bytes accessed) is harvested into the device
  profiler (obs/profiler.py), making runtime MFU computable per program.
  Dispatching the returned executable skips jax's python-side cache lookup,
  and by construction cannot silently recompile. ``aot(False)`` restores
  the plain jit-call dispatch (the rollback lever); backends where
  lower/compile or the cost model fail fall back per program, with
  ``Network.flops_per_example()`` as the documented analytic cross-check.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from mmlspark_tpu.utils.profiling import dataplane_counters

_BUCKETING_ENABLED = True


@contextlib.contextmanager
def bucketing(enabled: bool) -> Iterator[None]:
    """Scoped toggle for power-of-two bucketing (True is the default
    behavior; False pads to the full cap — the pre-bucketing dataflow)."""
    global _BUCKETING_ENABLED
    prev = _BUCKETING_ENABLED
    _BUCKETING_ENABLED = enabled
    try:
        yield
    finally:
        _BUCKETING_ENABLED = prev


def bucketing_enabled() -> bool:
    """Current state of the bucketing rollback lever (read by callers
    outside this module — e.g. parallel/mesh.shard_target_rows — so the
    one toggle governs every shape-bucketed pad in the dataplane)."""
    return _BUCKETING_ENABLED


_DONATION_ENABLED = True


@contextlib.contextmanager
def donation(enabled: bool) -> Iterator[None]:
    """Scoped toggle for donation-backed dispatch (True is the default;
    False keeps every program non-donating — the rollback lever)."""
    global _DONATION_ENABLED
    prev = _DONATION_ENABLED
    _DONATION_ENABLED = enabled
    try:
        yield
    finally:
        _DONATION_ENABLED = prev


def donation_enabled() -> bool:
    """Whether donation-backed dispatch is currently enabled."""
    return _DONATION_ENABLED


_AOT_ENABLED = True


@contextlib.contextmanager
def aot(enabled: bool) -> Iterator[None]:
    """Scoped toggle for AOT executable dispatch (True is the default;
    False makes aot_program return None so callers dispatch the plain jit
    wrapper — the rollback lever, mirroring bucketing/donation)."""
    global _AOT_ENABLED
    prev = _AOT_ENABLED
    _AOT_ENABLED = enabled
    try:
        yield
    finally:
        _AOT_ENABLED = prev


def _extract_cost(compiled) -> Optional[Dict[str, float]]:
    """{'flops', 'bytes'} from compiled.cost_analysis(), tolerating the
    per-version shapes (dict, or list of per-module dicts) and backends
    with no cost model at all (returns None -> analytic fallback)."""
    try:
        ca = compiled.cost_analysis()
    except Exception as e:  # backend has no cost model: analytic fallback
        _aot_log().debug("cost_analysis_unavailable", error=repr(e))
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out: Dict[str, float] = {}
    if ca.get("flops") is not None:
        out["flops"] = float(ca["flops"])
    if ca.get("bytes accessed") is not None:
        out["bytes"] = float(ca["bytes accessed"])
    return out or None


def _executable_nbytes(compiled) -> int:
    """Resident-footprint estimate for one AOT executable: the compiler's
    own generated-code size when the backend reports it (memory_analysis),
    else the HLO text length as a coarse serialized-size proxy, else 0
    (untracked). Whatever this returns at insert is EXACTLY what eviction
    hands back, so the ledger balances even when the estimate is rough."""
    try:
        ma = compiled.memory_analysis()
        size = getattr(ma, "generated_code_size_in_bytes", None)
        if size:
            return int(size)
    except Exception:  # backend-specific probe; fall to the next estimate  # graftcheck: ignore[broad-except]
        pass
    try:
        return len(compiled.as_text())
    except Exception:  # best-effort size probe; 0 = untracked, not an error  # graftcheck: ignore[broad-except]
        return 0


def _program_device() -> str:
    """Executables live on the attached backend; attribute them to the
    default device (per-device program residency would need per-device
    caches, which nothing has)."""
    from mmlspark_tpu.obs.memory import default_device_label

    try:
        return default_device_label()
    except Exception:  # no backend attached: attribution, not correctness  # graftcheck: ignore[broad-except]
        return "unknown"


def bucket_rows(n: int, cap: Optional[int] = None) -> int:
    """Smallest power of two >= n, capped at `cap` (cap need not be a power
    of two — it wins, keeping mini_batch_size semantics exact)."""
    if n <= 0:
        return cap if cap else 1
    if cap is not None and (n >= cap or not _BUCKETING_ENABLED):
        return cap
    bucket = 1 << int(n - 1).bit_length()
    return min(bucket, cap) if cap is not None else bucket


def pad_rows(arr: Any, target: int) -> Tuple[Any, int]:
    """Pad axis 0 up to `target` rows by repeating the last row (padded rows
    stay valid inputs); returns (padded, real_rows). Works for host ndarrays
    and device jax.Arrays — the device path runs as a compiled program with
    a static pad amount, so it is transfer-free on warm dispatch."""
    n = int(arr.shape[0])
    if n == 0 or n >= target:
        return arr, n
    if isinstance(arr, np.ndarray):
        pad_block = np.take(arr, [-1] * (target - n), axis=0)
        return np.concatenate([arr, pad_block], axis=0), n
    return _pad_rows_device(arr, target=target), n


def trim_rows(arr: Any, real: int) -> Any:
    """Undo pad_rows: first `real` rows. Device arrays slice through a
    compiled program (eager `arr[:real]` would promote the index scalar
    host->device on every call, tripping jax.transfer_guard)."""
    if int(arr.shape[0]) == real:
        return arr
    if isinstance(arr, np.ndarray):
        return arr[:real]
    return _trim_rows_device(arr, real=real)


def slice_rows(arr: Any, start: int, stop: int) -> Any:
    """arr[start:stop] along axis 0, transfer-free for device arrays: the
    chunking loops in TPUModel/Booster slice device inputs through a
    compiled program with static bounds, where eager `x[a:b]` would promote
    its index scalars host->device on every chunk."""
    stop = min(stop, int(arr.shape[0]))
    if start == 0 and stop == int(arr.shape[0]):
        return arr
    if isinstance(arr, np.ndarray):
        return arr[start:stop]
    return _slice_rows_device(arr, start=start, stop=stop)


# jit wrappers built once per process (a fresh jax.jit per call would
# re-trace every time); jax's own cache then keys on (shape, static arg)
_DEVICE_HELPERS: Dict[str, Callable] = {}


def _pad_rows_device(arr, *, target: int):
    pad = _DEVICE_HELPERS.get("pad")
    if pad is None:
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("t",))
        def pad(x, *, t):
            tail = jnp.broadcast_to(x[-1:], (t - x.shape[0],) + x.shape[1:])
            return jnp.concatenate([x, tail], axis=0)

        _DEVICE_HELPERS["pad"] = pad
    return pad(arr, t=target)


def _trim_rows_device(arr, *, real: int):
    trim = _DEVICE_HELPERS.get("trim")
    if trim is None:
        import functools

        import jax

        @functools.partial(jax.jit, static_argnames=("r",))
        def trim(x, *, r):
            return jax.lax.slice_in_dim(x, 0, r, axis=0)

        _DEVICE_HELPERS["trim"] = trim
    return trim(arr, r=real)


def _slice_rows_device(arr, *, start: int, stop: int):
    sl = _DEVICE_HELPERS.get("slice")
    if sl is None:
        import functools

        import jax

        @functools.partial(jax.jit, static_argnames=("a", "b"))
        def sl(x, *, a, b):
            return jax.lax.slice_in_dim(x, a, b, axis=0)

        _DEVICE_HELPERS["slice"] = sl
    return sl(arr, a=start, b=stop)


class DispatchCache:
    """Process-wide cache of compiled callables plus per-shape compile
    accounting. Keys are caller-chosen hashables (TPUModel uses
    (spec, input_shape, dtype)); `compiled` builds-and-caches, `note_dispatch`
    records the (key, shape) pairs that force an XLA compile.

    Scrape surface (obs/metrics.py): `dispatch_cache_fns` /
    `dispatch_cache_programs` gauges track retention, and
    `dispatch_cache_evictions_total` counts FIFO evictions — a rising
    eviction rate on a serving box means max_fns is too small for the
    deployed model mix (every eviction is a future recompile)."""

    def __init__(self, max_fns: int = 32, max_programs: int = 128):
        from mmlspark_tpu.obs.metrics import registry

        self._lock = threading.Lock()
        self._max_fns = max_fns
        self._max_programs = max_programs
        self._fns: Dict[Any, Callable] = {}
        self._shapes: set = set()
        # AOT executables, one per (key, input signature); None marks a
        # program whose lower/compile failed (callers dispatch the jit
        # wrapper instead — retrying every dispatch would re-pay the failure)
        self._aot: "OrderedDict[Tuple[Any, Any], Any]" = OrderedDict()
        self._aot_inflight: Dict[Tuple[Any, Any], threading.Event] = {}
        # entry -> (nbytes, owner tag) as recorded in the device-memory
        # ledger at insert; eviction/clear free exactly these
        self._aot_sizes: Dict[Tuple[Any, Any], Tuple[int, str]] = {}
        # process-wide eviction tally (an unlabeled counter: every instance
        # adds to the same series, which is the total the metric means)
        self._evictions = registry().counter(
            "dispatch_cache_evictions_total",
            "Compiled callables evicted FIFO from the dispatch cache",
        )

    def compiled(self, key: Any, build: Callable[[], Callable]) -> Callable:
        with self._lock:
            fn = self._fns.get(key)
            if fn is not None:
                return fn
        fn = build()  # build outside the lock: builders may import jax
        with self._lock:
            if len(self._fns) >= self._max_fns:
                evicted = next(iter(self._fns))
                del self._fns[evicted]
                self._shapes = {
                    (k, s) for k, s in self._shapes if k != evicted
                }
                self._evictions.inc()
            return self._fns.setdefault(key, fn)

    def aot_program(self, key: Any, signature: Any, jit_fn: Callable,
                    args: Tuple, site: str = "dispatch") -> Optional[Callable]:
        """The AOT executable for `key` at `signature` (caller-chosen, must
        pin everything that changes the program — shape AND dtype). First
        sighting lowers+compiles via ``jit_fn.lower(*args).compile()``,
        timing the compile into ``dispatch_compile_seconds{site}`` and
        harvesting ``cost_analysis()`` into the device profiler; later
        sightings return the cached executable. Returns None when AOT is
        rolled back (``aot(False)``) or this program's compile failed —
        the caller dispatches its plain jit wrapper instead."""
        if not _AOT_ENABLED:
            return None
        entry = (key, signature)
        # single-flight: concurrent first dispatches of the same entry
        # (multi-replica servers share this process-wide cache) must not
        # each pay a multi-second XLA compile — or double-observe
        # dispatch_compile_seconds and trip the compile-storm counter on
        # one genuine program. The loser waits for the winner's result.
        while True:
            with self._lock:
                if entry in self._aot:
                    return self._aot[entry]
                waiter = self._aot_inflight.get(entry)
                if waiter is None:
                    self._aot_inflight[entry] = threading.Event()
                    break
            waiter.wait()
        compiled = None
        cost = None
        dt = None
        try:
            try:
                t0 = time.perf_counter()
                compiled = jit_fn.lower(*args).compile()
                dt = time.perf_counter() - t0
                cost = _extract_cost(compiled)
            except Exception as e:
                _aot_log().warning(
                    "aot_compile_failed", site=site, error=repr(e),
                    signature=[str(s) for s in signature]
                    if isinstance(signature, (tuple, list))
                    else str(signature),
                )
            if dt is not None:
                from mmlspark_tpu.obs.profiler import device_profiler

                device_profiler().note_compile(key, signature, site, dt, cost)
        finally:
            # always release waiters — a BaseException here must not park
            # other dispatch threads forever (an interrupted compile caches
            # None, the same plain-jit fallback as a failed one)
            nbytes = _executable_nbytes(compiled) if compiled is not None else 0
            owner = f"aot:{site}"
            freed = []
            with self._lock:
                while len(self._aot) >= self._max_programs:
                    old_entry, _ = self._aot.popitem(last=False)
                    self._evictions.inc()
                    old_size = self._aot_sizes.pop(old_entry, None)
                    if old_size is not None:
                        freed.append(old_size)
                self._aot[entry] = compiled
                if nbytes > 0:
                    self._aot_sizes[entry] = (nbytes, owner)
                self._aot_inflight.pop(entry).set()
            from mmlspark_tpu.obs.memory import memory_ledger

            led = memory_ledger()
            if nbytes > 0 or freed:
                dev = _program_device()
                if nbytes > 0:
                    led.record_alloc(dev, "dispatch_programs", nbytes,
                                     owner=owner)
                # evictions RECLAIM: the executable's bytes leave the ledger
                # with it, instead of lingering as phantom residency
                for old_bytes, old_owner in freed:
                    led.record_free(dev, "dispatch_programs", old_bytes,
                                    owner=old_owner)
        return compiled

    def note_dispatch(self, key: Any, shape: Tuple[int, ...]) -> bool:
        """Record a dispatch of `key` at `shape`; returns True (and counts a
        compile) the first time this program/shape pair is seen."""
        entry = (key, tuple(int(d) for d in shape))
        with self._lock:
            if entry in self._shapes:
                return False
            self._shapes.add(entry)
        dataplane_counters().record_compile()
        return True

    def distinct_programs(self, key: Any) -> int:
        """How many shapes (== compiled programs) `key` has dispatched."""
        with self._lock:
            return sum(1 for k, _ in self._shapes if k == key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._fns)

    def clear(self) -> None:
        with self._lock:
            self._fns.clear()
            self._shapes.clear()
            self._aot.clear()
            freed = list(self._aot_sizes.values())
            self._aot_sizes.clear()
        if freed:
            from mmlspark_tpu.obs.memory import memory_ledger

            led = memory_ledger()
            dev = _program_device()
            for nbytes, owner in freed:
                led.record_free(dev, "dispatch_programs", nbytes, owner=owner)


def _aot_log():
    from mmlspark_tpu.obs.logging import get_logger

    return get_logger("mmlspark_tpu.dispatch")


_CACHE = DispatchCache()


def _register_cache_gauges() -> None:
    """Size gauges for THE singleton only — registered at module scope so a
    throwaway DispatchCache instance can never hijack the process series or
    get pinned by the registry."""
    from mmlspark_tpu.obs.metrics import registry

    reg = registry()
    reg.gauge(
        "dispatch_cache_fns", "Compiled callables currently cached"
    ).set_function(lambda: float(len(_CACHE._fns)))
    reg.gauge(
        "dispatch_cache_programs",
        "Distinct (program, shape) pairs dispatched",
    ).set_function(lambda: float(len(_CACHE._shapes)))
    reg.gauge(
        "dispatch_cache_aot_programs",
        "AOT executables currently cached (cost-model capture path)",
    ).set_function(lambda: float(len(_CACHE._aot)))


_register_cache_gauges()


def dispatch_cache() -> DispatchCache:
    """The process-wide dispatch cache singleton."""
    return _CACHE
