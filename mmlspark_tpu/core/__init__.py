"""Core runtime: params, dataframe, pipeline, schema, serialization, config.

Equivalent role to the reference's `src/core` (SURVEY.md §2.1): the L1 layer
every other module depends on.
"""
