"""TPU runtime bootstrap: device discovery, mesh construction, multi-host init.

TPU-native re-design of the reference's core/env:
- NativeLoader (NativeLoader.java:28) — dlopen of jarred .so files — becomes
  JAX backend initialization: there is no native lib to extract, the XLA TPU
  plugin IS the backend.
- EnvironmentUtils.GPUCount via `nvidia-smi` (EnvironmentUtils.scala:41-47)
  becomes `jax.devices()` / `jax.local_device_count()`.
- The MPI/ssh rendezvous of cntk-train and the LightGBM driver ServerSocket
  (SURVEY.md §2.7) collapse into `jax.distributed.initialize` over DCN.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

_initialized_distributed = False


# -- peak-FLOPs table (the MFU denominator) -----------------------------------
#
# bf16 peak FLOP/s by TPU device kind, from the public spec sheets. This is
# the single source both the offline bench (bench.py mfu lines) and the
# runtime profiler's `device_mfu` gauges (obs/profiler.py) divide by, so
# "6% MFU in the bench artifact" and "0.06 on /metrics" mean the same thing.
PEAK_FLOPS_BY_KIND = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v5": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
    "v3": 123e12,
    "v2": 45e12,
}

# Nominal peak for the CPU backend: one modern x86 core sustains roughly
# 100 GFLOP/s of f32 FMA (AVX2, 2 FMA ports). A deliberately coarse anchor —
# CPU MFU numbers are for *relative* movement (a regression doubling device
# seconds halves the gauge) and for exercising the MFU plumbing in CI, not
# for absolute hardware claims. The profiler smoke bench compares runtime
# and analytic MFU against this same constant, so the tolerance gate is
# self-consistent (docs/observability.md "Profiling & MFU").
CPU_NOMINAL_PEAK_FLOPS = 100e9


def peak_flops_per_sec() -> float:
    """Best-effort peak FLOP/s for the attached backend: the bf16 table for
    known TPU kinds, the documented nominal for CPU, 0.0 when unknown
    (callers omit MFU rather than report a wrong one)."""
    if default_backend() == "cpu":
        return CPU_NOMINAL_PEAK_FLOPS
    return _per_kind_lookup(PEAK_FLOPS_BY_KIND)


# -- HBM capacity table (the memory-pressure denominator) ----------------------
#
# HBM bytes per chip by TPU device kind, from the public spec sheets — the
# capacity the device-memory ledger (obs/memory.py) divides resident bytes
# by for its `device_memory_pressure` gauge, and the budget every future
# HBM byte-budget manager enforces against. Same single-source discipline
# as PEAK_FLOPS_BY_KIND: bench artifacts and /metrics agree by construction.
HBM_BYTES_BY_KIND = {
    "v5 lite": 16e9,
    "v5e": 16e9,
    "v4": 32e9,
    "v5p": 95e9,
    "v5": 95e9,
    "v6 lite": 32e9,
    "v6e": 32e9,
    "v3": 16e9,
    "v2": 8e9,
}

# Nominal per-virtual-device capacity for the CPU backend. Like
# CPU_NOMINAL_PEAK_FLOPS this anchors *relative* movement (a pressure gauge
# doubling means residency doubled) and exercises the pressure plumbing in
# CI — it is not a host-RAM claim. 4 GB keeps smoke-scale residency well
# under 1.0 while leaving leak-injection headroom visible.
CPU_NOMINAL_HBM_BYTES = 4e9


def hbm_bytes_per_device() -> float:
    """Best-effort HBM bytes per attached device: the spec-sheet table for
    known TPU kinds, the documented nominal for CPU, 0.0 when unknown
    (callers omit the pressure gauge rather than report a wrong one)."""
    if default_backend() == "cpu":
        return CPU_NOMINAL_HBM_BYTES
    return _per_kind_lookup(HBM_BYTES_BY_KIND)


def _per_kind_lookup(table: dict) -> float:
    """Per-chip constants are a DEVICE-KIND property, not a device-0
    property: probe every local device and require agreement, so a
    (hypothetical) mixed-kind mesh reports 0.0 (unknown) instead of
    silently assuming the whole pod matches device 0."""
    import jax

    kinds = {d.device_kind.lower() for d in jax.local_devices()}
    if len(kinds) != 1:
        return 0.0
    kind = kinds.pop()
    for key, value in table.items():
        if key in kind:
            return value
    return 0.0


def device_count() -> int:
    import jax

    return jax.device_count()


def local_device_count() -> int:
    import jax

    return jax.local_device_count()


def default_backend() -> str:
    import jax

    return jax.default_backend()


def is_tpu() -> bool:
    return default_backend() == "tpu"


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up over DCN. Replaces the reference's driver
    ServerSocket rendezvous (LightGBMUtils.scala:97-137) and mpirun/ssh ring
    (CommandBuilders.scala:105-269): every host calls this once, JAX's
    coordination service does discovery, and all collectives afterwards ride
    ICI/DCN via XLA."""
    global _initialized_distributed
    if _initialized_distributed:
        return
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    _initialized_distributed = True


def make_mesh(
    shape: Optional[Sequence[int]] = None,
    axis_names: Sequence[str] = ("data",),
    devices: Optional[Sequence] = None,
):
    """Build a `jax.sharding.Mesh`. Default: 1-D data mesh over all devices
    (the reference's scope — SURVEY.md §2.7 item 6: its distributed axes are
    rows and models). parallel/mesh.py builds richer dp/tp/sp meshes.

    `prod(shape)` must equal the number of devices used: pass `devices`
    explicitly to use a subset — silent truncation is a wrong-mesh bug."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if shape is None:
        shape = (len(devices),)
    n = int(np.prod(shape))
    if n != len(devices):
        raise ValueError(
            f"Mesh shape {tuple(shape)} needs {n} devices but {len(devices)} "
            "were given; pass an explicit devices= subset to use fewer"
        )
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def cpu_host_devices(n: int = 8) -> None:
    """Force `n` virtual CPU devices — the single-process multi-worker test
    mode (SURVEY.md §4: the local[*] partition≈worker trick). Must run before
    first JAX import in the process; conftest.py uses it."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
