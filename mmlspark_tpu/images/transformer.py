"""Image pipeline stages: ImageTransformer, UnrollImage, augmentation.

Reference: image-transformer/src/main/scala/ImageTransformer.scala:22-335
(fluent stage-list transformer), UnrollImage.scala:25-49 (image struct ->
CHW DenseVector in BGR order — the layout CNTK consumed and our Networks
consume after reshape), ResizeImageTransformer (pure-JVM fallback, here the
same numpy path), ImageSetAugmenter (flip augmentation producing extra rows).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, Field
from mmlspark_tpu.core.params import Param, TypeConverters, Wrappable
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import make_image_row
from mmlspark_tpu.images import ops


class ImageTransformer(Transformer, Wrappable):
    """Apply a list of image ops per row; fluent builder API mirrors the
    reference (it.resize(h, w).crop(...).flip(...))."""

    stages = Param("stages", "Image processing stages (list of op dicts)", TypeConverters.to_list)
    input_col = Param("input_col", "The name of the input column", TypeConverters.to_string)
    output_col = Param("output_col", "The name of the output column", TypeConverters.to_string)

    def __init__(self, input_col: str = "image", output_col: Optional[str] = None):
        super().__init__()
        self.set(self.stages, [])
        self.set(self.input_col, input_col)
        self.set(self.output_col, output_col or input_col)

    def set_input_col(self, v: str):
        return self.set(self.input_col, v)

    def set_output_col(self, v: str):
        return self.set(self.output_col, v)

    def _add(self, op: str, **params: Any) -> "ImageTransformer":
        new = list(self.get(self.stages))
        new.append({"op": op, **params})
        return self.set(self.stages, new)

    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._add("resize", height=int(height), width=int(width))

    def crop(self, x: int, y: int, height: int, width: int) -> "ImageTransformer":
        return self._add("crop", x=int(x), y=int(y), height=int(height), width=int(width))

    def color_format(self, fmt: str) -> "ImageTransformer":
        return self._add("colorformat", format=fmt)

    def flip(self, flip_code: int = 1) -> "ImageTransformer":
        return self._add("flip", flip_code=int(flip_code))

    def blur(self, height: int, width: int) -> "ImageTransformer":
        return self._add("blur", height=int(height), width=int(width))

    def threshold(self, threshold: float, max_val: float,
                  threshold_type: str = "binary") -> "ImageTransformer":
        return self._add(
            "threshold", threshold=float(threshold), max_val=float(max_val),
            threshold_type=threshold_type,
        )

    def gaussian_kernel(self, aperture_size: int, sigma: float) -> "ImageTransformer":
        return self._add(
            "gaussiankernel", aperture_size=int(aperture_size), sigma=float(sigma)
        )

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        out_col = self.get(self.output_col)
        if any(f.name == out_col for f in schema):
            return schema
        return schema + [Field(out_col, DataType.STRUCT)]

    def transform(self, df: DataFrame) -> DataFrame:
        stage_list = self.get(self.stages)
        values = df[self.get(self.input_col)]
        out = np.empty(len(values), dtype=object)

        # Fast path: resize-only pipeline over a no-null column (the
        # ImageFeaturizer prep) batches the column into vectorized
        # resize_batch passes instead of a per-row Python loop — one call
        # for a uniform-shape column, one call per distinct source shape
        # (resize_groups) for ragged decode output.
        if (
            len(values)
            and stage_list
            and all(st["op"] == "resize" for st in stage_list)
            and all(v is not None for v in values)
        ):
            arrays = [np.asarray(v["data"]) for v in values]
            if len({a.shape for a in arrays}) == 1:
                batch = np.stack(arrays)
                for st in stage_list:
                    batch = ops.resize_batch(batch, st["height"], st["width"])
                arrays = list(batch)
            else:
                for st in stage_list:
                    arrays = ops.resize_groups(arrays, st["height"], st["width"])
            for i, row in enumerate(values):
                out[i] = make_image_row(arrays[i], row.get("path", ""))
            return df.with_column(
                self.get(self.output_col), Column(out, DataType.STRUCT)
            )

        for i, row in enumerate(values):
            if row is None:
                out[i] = None
                continue
            img = np.asarray(row["data"])
            for st in stage_list:
                img = ops.OPS[st["op"]](img, st)
            out[i] = make_image_row(img, row.get("path", ""))
        return df.with_column(
            self.get(self.output_col), Column(out, DataType.STRUCT)
        )


class ResizeImageTransformer(Transformer, Wrappable):
    """Resize-only stage (reference's JVM fallback when OpenCV is absent —
    same numpy path here, kept for API parity)."""

    input_col = Param("input_col", "The name of the input column", TypeConverters.to_string)
    output_col = Param("output_col", "The name of the output column", TypeConverters.to_string)
    height = Param("height", "Target height", TypeConverters.to_int)
    width = Param("width", "Target width", TypeConverters.to_int)

    def __init__(self, input_col: str = "image", output_col: Optional[str] = None,
                 height: int = 224, width: int = 224):
        super().__init__()
        self.set(self.input_col, input_col)
        self.set(self.output_col, output_col or input_col)
        self.set(self.height, height)
        self.set(self.width, width)

    def transform(self, df: DataFrame) -> DataFrame:
        return (
            ImageTransformer(self.get(self.input_col), self.get(self.output_col))
            .resize(self.get(self.height), self.get(self.width))
            .transform(df)
        )


class UnrollImage(Transformer, Wrappable):
    """Image struct -> flat CHW float VECTOR (BGR channel planes), the layout
    the reference feeds CNTK (UnrollImage.scala:25-49). All images in the
    column must share a shape (resize first).

    `to_device=True` emits a DEVICE-BACKED column instead: the uint8 batch
    uploads once (4x fewer bytes than the f64 host unroll) and the CHW
    transpose runs as a compiled device program, so an
    unroll -> TPUModel chain stays on HBM end to end. Host consumers still
    work — the column syncs lazily (counted) like any device column."""

    input_col = Param("input_col", "The name of the input column", TypeConverters.to_string)
    output_col = Param("output_col", "The name of the output column", TypeConverters.to_string)
    to_device = Param(
        "to_device",
        "Emit a device-backed unrolled column via the fused device program "
        "(one uint8 upload) instead of host numpy",
        TypeConverters.to_boolean,
    )

    def __init__(self, input_col: str = "image", output_col: str = "unrolled",
                 to_device: bool = False):
        super().__init__()
        self.set(self.input_col, input_col)
        self.set(self.output_col, output_col)
        self.set(self.to_device, to_device)

    def set_input_col(self, v: str):
        return self.set(self.input_col, v)

    def set_output_col(self, v: str):
        return self.set(self.output_col, v)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.VECTOR)]

    def transform(self, df: DataFrame) -> DataFrame:
        from mmlspark_tpu.images import device_ops

        values = df[self.get(self.input_col)]
        if self.get(self.to_device) and len(values):
            arrays = device_ops.image_row_arrays(values)
            fused = (
                device_ops.fused_unrolled_batch(arrays, size=None)
                if arrays is not None else None
            )
            if fused is None:
                raise ValueError(
                    "UnrollImage(to_device=True) needs a uniform-shape, "
                    "no-null image column; resize first"
                )
            out_dev, meta = fused
            return df.with_column(
                self.get(self.output_col), out_dev, DataType.VECTOR,
                metadata=meta,
            )
        imgs = []
        shape = None
        for row in values:
            img = np.asarray(row["data"])
            if img.ndim == 2:
                img = img[:, :, None]
            if shape is None:
                shape = img.shape
            elif img.shape != shape:
                raise ValueError(
                    f"UnrollImage needs uniform shapes: {img.shape} vs {shape}; "
                    "resize first"
                )
            imgs.append(img)
        # HWC -> CHW planes, flattened (reference unroll order) — one
        # vectorized pass over the whole batch (ops.unroll, the device
        # path's semantic oracle)
        out = (
            ops.unroll(np.stack(imgs)) if imgs else np.zeros((0, 0))
        )
        # Layout metadata: consumers (TPUModel) reorder CHW -> their input
        # layout instead of silently misreading the planes as NHWC
        meta = {}
        if shape is not None:
            h, w, c = shape
            meta["unrolled"] = {"order": "CHW", "height": h, "width": w, "channels": c}
        return df.with_column(
            self.get(self.output_col), out, DataType.VECTOR, metadata=meta
        )


class UnrollBinaryImage(Transformer, Wrappable):
    """Decode BINARY image bytes and unroll (UnrollImage.scala:177
    UnrollBinaryImage). Optional uniform resize during decode."""

    input_col = Param("input_col", "The name of the input column", TypeConverters.to_string)
    output_col = Param("output_col", "The name of the output column", TypeConverters.to_string)
    height = Param("height", "Optional target height", TypeConverters.to_int)
    width = Param("width", "Optional target width", TypeConverters.to_int)

    def __init__(self, input_col: str = "value", output_col: str = "unrolled",
                 height: Optional[int] = None, width: Optional[int] = None):
        super().__init__()
        self.set(self.input_col, input_col)
        self.set(self.output_col, output_col)
        if height is not None:
            self.set(self.height, height)
        if width is not None:
            self.set(self.width, width)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.VECTOR)]

    def transform(self, df: DataFrame) -> DataFrame:
        from mmlspark_tpu.io.image import decode_image

        values = df[self.get(self.input_col)]
        rows = [decode_image(bytes(raw)) for raw in values]
        imgs = np.empty(len(values), dtype=object)
        if self.is_set(self.height) and self.is_set(self.width) and rows:
            # one resize_batch call per distinct decoded shape instead of a
            # per-row ops.resize loop (decode output is ragged by nature)
            resized = ops.resize_groups(
                [np.asarray(r["data"]) for r in rows],
                self.get(self.height), self.get(self.width),
            )
            for i, (r, data) in enumerate(zip(rows, resized)):
                imgs[i] = make_image_row(data, r.get("path", ""))
        else:
            for i, r in enumerate(rows):
                imgs[i] = r
        tmp = df.with_column("__img__", Column(imgs, DataType.STRUCT))
        unrolled = UnrollImage("__img__", self.get(self.output_col)).transform(tmp)
        return unrolled.drop("__img__")


class ImageSetAugmenter(Transformer, Wrappable):
    """Dataset augmentation by flips: emits the original rows plus flipped
    copies (reference: ImageSetAugmenter — flipLeftRight/flipUpDown params)."""

    input_col = Param("input_col", "The name of the input column", TypeConverters.to_string)
    output_col = Param("output_col", "The name of the output column", TypeConverters.to_string)
    flip_left_right = Param("flip_left_right", "Add horizontal flips", TypeConverters.to_boolean)
    flip_up_down = Param("flip_up_down", "Add vertical flips", TypeConverters.to_boolean)

    def __init__(self, input_col: str = "image", output_col: str = "image",
                 flip_left_right: bool = True, flip_up_down: bool = False):
        super().__init__()
        self.set(self.input_col, input_col)
        self.set(self.output_col, output_col)
        self.set(self.flip_left_right, flip_left_right)
        self.set(self.flip_up_down, flip_up_down)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get(self.input_col)
        out_col = self.get(self.output_col)
        base = df.with_column(out_col, df.column(in_col).copy()) if in_col != out_col else df
        frames = [base]
        if self.get(self.flip_left_right):
            frames.append(
                ImageTransformer(in_col, out_col).flip(1).transform(df)
            )
        if self.get(self.flip_up_down):
            frames.append(
                ImageTransformer(in_col, out_col).flip(0).transform(df)
            )
        from mmlspark_tpu.core.dataframe import concat

        aligned = [f.select(*frames[0].columns) for f in frames]
        return concat(aligned)
