"""SLIC superpixel decomposition + the SuperpixelTransformer stage.

Reference: image-featurizer/src/main/scala/Superpixel.scala:154-273 (the
popscan SLIC variant: hexagonal seed grid, iterative windowed assignment with
D = sqrt(color^2) + sqrt(spatial^2 * (m/S)^2), mean-recenter until stable),
SuperpixelTransformer.scala:33-55 (the stage), SuperpixelData (clusters as
pixel-coordinate lists), censorImage (Superpixel.scala:106-122 — black out
OFF clusters) and clusterStateSampler (:140-151).

TPU-first redesign: the reference loops pixel-by-pixel in Java. Here every
phase is vectorized numpy — assignment evaluates each cluster's 2S window as
an array op, recenter is one np.bincount pass over the label map, and
censoring is a single gather (states[labels]) that can batch ALL of a LIME
sample set in one op (lime.py) instead of one image copy per sample.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, Field
from mmlspark_tpu.core.params import Param, TypeConverters, Wrappable
from mmlspark_tpu.core.pipeline import Transformer

_MAX_LOOPS = 50  # reference maxClusteringLoops


class SuperpixelData:
    """Cluster decomposition of one image.

    clusters: list of pixel-coordinate lists [(x, y), ...] (reference
    SuperpixelData.clusters). Also carries the dense (H, W) label map the
    vectorized censor path uses; it is derivable from clusters, so only
    clusters participate in equality/serialization.
    """

    __slots__ = ("clusters", "_labels", "_shape")

    def __init__(
        self,
        clusters: Sequence[Sequence[tuple]],
        labels: Optional[np.ndarray] = None,
        shape: Optional[tuple] = None,
    ):
        self.clusters = [list(map(tuple, c)) for c in clusters]
        self._labels = labels
        self._shape = shape

    def __len__(self) -> int:
        return len(self.clusters)

    def label_map(self, height: int, width: int) -> np.ndarray:
        """(H, W) int32 pixel -> cluster index."""
        if (
            self._labels is not None
            and self._shape == (height, width)
        ):
            return self._labels
        lab = np.zeros((height, width), np.int32)
        for i, cluster in enumerate(self.clusters):
            if cluster:
                xs, ys = zip(*cluster)
                lab[np.asarray(ys), np.asarray(xs)] = i
        self._labels = lab
        self._shape = (height, width)
        return lab

    def to_dict(self) -> Dict[str, Any]:
        return {"clusters": [[list(p) for p in c] for c in self.clusters]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SuperpixelData":
        return cls(d["clusters"])


def slic(
    img: np.ndarray, cell_size: float = 16.0, modifier: float = 130.0
) -> SuperpixelData:
    """Cluster an (H, W, C) image into superpixels.

    Same algorithm as the reference's Superpixel class — hex-grid seeds at
    cell_size spacing, windowed nearest-cluster assignment with the
    sqrt(color) + sqrt(spatial * inv) distance, mean recentering — with the
    per-pixel Java loops replaced by per-cluster window array ops.
    """
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    h, w = img.shape[:2]
    rgb = img[:, :, :3].astype(np.float64)
    if rgb.shape[2] == 1:
        rgb = np.repeat(rgb, 3, axis=2)
    S = float(cell_size)
    inv = 1.0 / ((S / float(modifier)) ** 2)

    # hexagonal seed grid (reference createClusters: x start alternates
    # cell_size and cell_size/2 per row)
    centers = []  # (x, y) float
    even = False
    y = S / 2.0
    while y < h:
        xstart = S / 2.0 if even else S
        even = not even
        x = xstart
        while x < w:
            centers.append((x, y))
            x += S
        y += S
    if not centers:  # image smaller than a cell: one cluster
        centers = [(w / 2.0, h / 2.0)]
    k = len(centers)
    cx = np.array([c[0] for c in centers])
    cy = np.array([c[1] for c in centers])
    ccol = rgb[cy.astype(int), cx.astype(int)]  # (k, 3) seed colors

    yy, xx = np.mgrid[0:h, 0:w]
    labels = np.full((h, w), -1, np.int32)
    distances = np.full((h, w), np.inf)

    for _ in range(_MAX_LOOPS):
        changed = False
        for ci in range(k):
            xs = max(int(cx[ci] - S), 0)
            ys = max(int(cy[ci] - S), 0)
            xe = min(int(cx[ci] + S), w)
            ye = min(int(cy[ci] + S), h)
            if xs >= xe or ys >= ye:
                continue
            win = rgb[ys:ye, xs:xe]
            dc = ((win - ccol[ci]) ** 2).sum(axis=2)
            ds = (xx[ys:ye, xs:xe] - cx[ci]) ** 2 + (yy[ys:ye, xs:xe] - cy[ci]) ** 2
            d = np.sqrt(dc) + np.sqrt(ds * inv)
            upd = (d < distances[ys:ye, xs:xe]) & (labels[ys:ye, xs:xe] != ci)
            if upd.any():
                changed = True
                distances[ys:ye, xs:xe] = np.where(upd, d, distances[ys:ye, xs:xe])
                labels[ys:ye, xs:xe] = np.where(upd, ci, labels[ys:ye, xs:xe])
        # pixels outside every window (image smaller than the seed grid's
        # reach) go to the nearest center — must happen BEFORE the bincount
        # recenter, which rejects -1 labels
        if (labels < 0).any():
            miss = np.argwhere(labels < 0)
            d = (miss[:, 0, None] - cy[None]) ** 2 + (miss[:, 1, None] - cx[None]) ** 2
            labels[miss[:, 0], miss[:, 1]] = np.argmin(d, axis=1).astype(np.int32)
            changed = True
        if not changed:
            break
        # windows tile the image, so every pixel is labeled after the fill;
        # recenter = one bincount pass (the reference's addPixel loop)
        flat = labels.ravel()
        cnt = np.bincount(flat, minlength=k).astype(np.float64)
        cnt_safe = np.maximum(cnt, 1.0)
        cx = np.bincount(flat, weights=xx.ravel(), minlength=k) / cnt_safe
        cy = np.bincount(flat, weights=yy.ravel(), minlength=k) / cnt_safe
        ccol = np.stack(
            [
                np.bincount(flat, weights=rgb[:, :, c].ravel(), minlength=k)
                / cnt_safe
                for c in range(3)
            ],
            axis=1,
        )

    clusters: List[List[tuple]] = [[] for _ in range(k)]
    ys_all, xs_all = np.nonzero(labels >= 0)
    for yv, xv in zip(ys_all.tolist(), xs_all.tolist()):
        clusters[labels[yv, xv]].append((xv, yv))
    # drop empty clusters, keep label map consistent
    keep = [i for i, c in enumerate(clusters) if c]
    if len(keep) != k:
        remap = {old: new for new, old in enumerate(keep)}
        relabeled = np.vectorize(remap.get)(labels).astype(np.int32)
        return SuperpixelData(
            [clusters[i] for i in keep], relabeled, (h, w)
        )
    return SuperpixelData(clusters, labels, (h, w))


class Superpixel:
    """Object API mirroring the reference's Superpixel class: cluster on
    construction, expose `.clusters` (pixel lists)."""

    def __init__(self, image: np.ndarray, cell_size: float = 16.0,
                 modifier: float = 130.0):
        self.data = slic(image, cell_size, modifier)
        self.clusters = self.data.clusters

    def __len__(self) -> int:
        return len(self.clusters)


def censor_image(
    img: np.ndarray, sp: SuperpixelData, states: np.ndarray
) -> np.ndarray:
    """Black out clusters whose state is False (reference censorImage,
    Superpixel.scala:106-122)."""
    img = np.asarray(img)
    lab = sp.label_map(img.shape[0], img.shape[1])
    on = np.asarray(states, bool)[lab]  # (H, W)
    return img * on[..., None].astype(img.dtype)


def censor_batch(
    img: np.ndarray, sp: SuperpixelData, states: np.ndarray
) -> np.ndarray:
    """(nS, K) state matrix -> (nS, H, W, C) censored batch in ONE gather —
    the whole LIME sample set materializes without a Python loop."""
    img = np.asarray(img)
    lab = sp.label_map(img.shape[0], img.shape[1])
    on = np.asarray(states, bool)[:, lab]  # (nS, H, W)
    return img[None] * on[..., None].astype(img.dtype)


def cluster_state_sampler(
    sampling_fraction: float, num_clusters: int, n_samples: int, seed: int = 0
) -> np.ndarray:
    """(n_samples, num_clusters) bool ON-states. Mirrors the reference's
    clusterStateSampler (Superpixel.scala:140-151): seeded at 0 per image,
    each cluster ON with probability 1 - sampling_fraction."""
    rng = np.random.default_rng(seed)
    return rng.random((n_samples, num_clusters)) > sampling_fraction


class SuperpixelTransformer(Transformer, Wrappable):
    """Decompose an image column into superpixels
    (SuperpixelTransformer.scala:33-55). Accepts image STRUCT or BINARY
    columns; output is a STRUCT column of SuperpixelData dicts."""

    input_col = Param("input_col", "The name of the input column", TypeConverters.to_string)
    output_col = Param("output_col", "The name of the output column", TypeConverters.to_string)
    cell_size = Param(
        "cell_size", "Number that controls the size of the superpixels",
        TypeConverters.to_float,
    )
    modifier = Param(
        "modifier", "Controls the trade-off between spatial and color distance",
        TypeConverters.to_float,
    )

    def __init__(
        self,
        input_col: str = "image",
        output_col: str = "superpixels",
        cell_size: float = 16.0,
        modifier: float = 130.0,
    ):
        super().__init__()
        self._set_defaults(
            input_col="image", output_col="superpixels",
            cell_size=16.0, modifier=130.0,
        )
        self.set(self.input_col, input_col)
        self.set(self.output_col, output_col)
        self.set(self.cell_size, cell_size)
        self.set(self.modifier, modifier)

    def set_input_col(self, v: str):
        return self.set(self.input_col, v)

    def set_output_col(self, v: str):
        return self.set(self.output_col, v)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.STRUCT)]

    def transform(self, df: DataFrame) -> DataFrame:
        from mmlspark_tpu.io.image import decode_image

        col = df.column(self.get(self.input_col))
        out = np.empty(len(col.values), dtype=object)
        for i, row in enumerate(col.values):
            if row is None:
                out[i] = None
                continue
            if isinstance(row, (bytes, bytearray, np.void)):
                row = decode_image(bytes(row))
            sp = slic(
                np.asarray(row["data"]),
                self.get(self.cell_size), self.get(self.modifier),
            )
            out[i] = sp.to_dict()
        return df.with_column(
            self.get(self.output_col), Column(out, DataType.STRUCT)
        )
