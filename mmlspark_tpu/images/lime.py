"""ImageLIME: local interpretable model-agnostic explanations for images.

Reference: image-featurizer/src/main/scala/ImageLIME.scala:75-163 — per
image: decompose into superpixels (SuperpixelTransformer), sample n_samples
cluster on/off states, censor OFF clusters to black, map the censored
samples through the model, then fit a linear model (state -> label) whose
coefficients are the per-superpixel importances.

TPU-first redesign: the reference builds a Spark DataFrame per image and
round-trips every censored sample through the JVM. Here the whole sample set
materializes as one (n_samples, H, W, C) gather (superpixel.censor_batch),
the inner model scores it in its own batched jit path, and the local linear
fit is a closed-form least squares solve (n_clusters x n_clusters normal
equations) — no iterative solver, no per-sample Python.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, Field
from mmlspark_tpu.core.params import (
    ComplexParam,
    Param,
    TypeConverters,
    Wrappable,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import make_image_row
from mmlspark_tpu.images.superpixel import (
    censor_batch,
    cluster_state_sampler,
    slic,
)


def fit_local_linear(states: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Least-squares fit with intercept; returns the K state coefficients
    (the reference's LinearRegression.fit coefficients, ImageLIME.scala:148)."""
    x = np.asarray(states, np.float64)
    y = np.asarray(y, np.float64).reshape(-1)
    design = np.concatenate([x, np.ones((x.shape[0], 1))], axis=1)
    coef, *_ = np.linalg.lstsq(design, y, rcond=None)
    return coef[:-1]


class ImageLIME(Transformer, Wrappable):
    """Explain an image model's output as per-superpixel weights."""

    model = ComplexParam("model", "Model to try to locally approximate")
    input_col = Param("input_col", "The name of the input column", TypeConverters.to_string)
    output_col = Param("output_col", "The name of the output column", TypeConverters.to_string)
    label_col = Param(
        "label_col", "The model output column to explain", TypeConverters.to_string
    )
    n_samples = Param("n_samples", "The number of samples to generate", TypeConverters.to_int)
    sampling_fraction = Param(
        "sampling_fraction", "The fraction of superpixels to censor per sample",
        TypeConverters.to_float,
    )
    cell_size = Param(
        "cell_size", "Number that controls the size of the superpixels",
        TypeConverters.to_float,
    )
    modifier = Param(
        "modifier", "Controls the trade-off between spatial and color distance",
        TypeConverters.to_float,
    )
    superpixel_col = Param(
        "superpixel_col", "The column holding the superpixel decompositions",
        TypeConverters.to_string,
    )

    def __init__(
        self,
        model: Optional[Transformer] = None,
        input_col: str = "image",
        output_col: str = "weights",
        label_col: str = "prediction",
    ):
        super().__init__()
        self._set_defaults(
            input_col="image",
            output_col="weights",
            label_col="prediction",
            n_samples=900,
            sampling_fraction=0.3,
            cell_size=16.0,
            modifier=130.0,
            superpixel_col="superpixels",
        )
        if model is not None:
            self.set_model(model)
        self.set(self.input_col, input_col)
        self.set(self.output_col, output_col)
        self.set(self.label_col, label_col)

    def set_model(self, v: Transformer) -> "ImageLIME":
        return self.set(self.model, v)

    def get_model(self) -> Transformer:
        return self.get(self.model)

    def set_n_samples(self, v: int):
        return self.set(self.n_samples, v)

    def set_sampling_fraction(self, v: float):
        return self.set(self.sampling_fraction, v)

    def set_cell_size(self, v: float):
        return self.set(self.cell_size, v)

    def set_modifier(self, v: float):
        return self.set(self.modifier, v)

    def set_superpixel_col(self, v: str):
        return self.set(self.superpixel_col, v)

    def set_label_col(self, v: str):
        return self.set(self.label_col, v)

    # -- stage contract --------------------------------------------------------

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [
            Field(self.get(self.superpixel_col), DataType.STRUCT),
            Field(self.get(self.output_col), DataType.VECTOR),
        ]

    # Pixel budget per model call: bounds host memory for the concatenated
    # censored sample block (uint8), while letting many small images share
    # one model dispatch. 2^28 px ~= 256 MB of uint8 RGB.
    _CHUNK_PIXEL_BUDGET = 2 ** 28

    def transform(self, df: DataFrame) -> DataFrame:
        from mmlspark_tpu.io.image import decode_image

        in_col = self.get(self.input_col)
        model = self.get_model()
        n_samples = self.get(self.n_samples)
        frac = self.get(self.sampling_fraction)
        label_col = self.get(self.label_col)

        # Streaming batches ACROSS images: same-shape sample blocks
        # concatenate into one model.transform, so a 100-image explain pays
        # a handful of model dispatches instead of 100 (round-5 verdict
        # item 6; the reference's per-image mapGroups could never do this).
        # Chunks flush as soon as the pixel budget or an image-shape change
        # is hit, so peak host memory stays bounded by the budget no matter
        # how many images are explained. Weights are identical to the
        # sequential path: per-image states/censoring are unchanged, the
        # model just sees the rows in one batch.
        sp_dicts = np.empty(len(df), dtype=object)
        weights = np.empty(len(df), dtype=object)
        chunk = []  # (row_idx, path, states, censored (nS,H,W,C))
        chunk_px = 0

        def flush():
            nonlocal chunk, chunk_px
            if not chunk:
                return
            rows_total = sum(c[3].shape[0] for c in chunk)
            rows = np.empty(rows_total, dtype=object)
            r = 0
            for _i, path, _states, censored in chunk:
                for sample in censored:  # views, no copies
                    rows[r] = make_image_row(sample, path)
                    r += 1
            local_df = DataFrame({in_col: Column(rows, DataType.STRUCT)})
            scored = model.transform(local_df)
            y_all = np.asarray(scored[label_col], np.float64)
            r = 0
            for i, _path, states, censored in chunk:
                y = y_all[r: r + censored.shape[0]]
                r += censored.shape[0]
                weights[i] = fit_local_linear(states, y)
            chunk, chunk_px = [], 0

        for i, img_val in enumerate(df[in_col]):
            if img_val is None:
                sp_dicts[i] = None
                weights[i] = None
                continue
            if isinstance(img_val, (bytes, bytearray)):
                img_row = decode_image(bytes(img_val))
            else:
                img_row = img_val
            img = np.asarray(img_row["data"])
            sp = slic(img, self.get(self.cell_size), self.get(self.modifier))
            sp_dicts[i] = sp.to_dict()
            # seeded per image like the reference sampler (Random.setSeed(0))
            states = cluster_state_sampler(frac, len(sp), n_samples, seed=0)
            censored = censor_batch(img, sp, states)  # (nS, H, W, C)
            px = int(np.prod(censored.shape))
            if chunk and (
                censored.shape[1:] != chunk[0][3].shape[1:]
                or chunk_px + px > self._CHUNK_PIXEL_BUDGET
            ):
                flush()
            chunk.append((i, img_row.get("path", ""), states, censored))
            chunk_px += px
        flush()

        return df.with_column(
            self.get(self.superpixel_col), Column(sp_dicts, DataType.STRUCT)
        ).with_column(
            self.get(self.output_col), Column(weights, DataType.VECTOR)
        )
