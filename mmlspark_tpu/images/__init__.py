"""images — image pipeline stages.

Equivalent of the reference's image-transformer module (OpenCV-backed,
SURVEY.md §2.2): ImageTransformer.scala:22-335, UnrollImage.scala:25-49.

Design note: pre-resize images are ragged (per-row sizes differ), so the
transform ops run per-row on host in numpy — exactly where the reference
runs OpenCV. The TPU path begins at UnrollImage: fixed-size CHW vectors,
batched into HBM by TPUModel/ImageFeaturizer.
"""

from mmlspark_tpu.images.transformer import (
    ImageSetAugmenter,
    ImageTransformer,
    ResizeImageTransformer,
    UnrollBinaryImage,
    UnrollImage,
)

__all__ = [
    "ImageSetAugmenter",
    "ImageTransformer",
    "ResizeImageTransformer",
    "UnrollBinaryImage",
    "UnrollImage",
]
