"""images — image pipeline stages, featurization, and interpretability.

Equivalent of the reference's image-transformer AND image-featurizer modules
(SURVEY.md §2.2): ImageTransformer.scala:22-335, UnrollImage.scala:25-49,
ImageFeaturizer.scala:129-177, ImageLIME.scala:75-163,
Superpixel.scala:154-273, SuperpixelTransformer.scala:33.

Design note: image DECODE is inherently host work (ragged object rows), but
everything after it is batchable. Uniform batches run the fused device prep
path (images/device_ops.py): the whole resize/crop/flip/color/normalize/
unroll chain compiles into ONE XLA program over the (N, H, W, C) batch, fed
by a single uint8 upload — images/ops.py stays the numpy semantic oracle it
is parity-gated against. Ragged host fallbacks batch by shape
(ops.resize_groups) instead of looping per row. See docs/dataplane.md
"Image dataplane".
"""

from mmlspark_tpu.images.transformer import (
    ImageSetAugmenter,
    ImageTransformer,
    ResizeImageTransformer,
    UnrollBinaryImage,
    UnrollImage,
)
from mmlspark_tpu.images.featurizer import ImageFeaturizer
from mmlspark_tpu.images.lime import ImageLIME
from mmlspark_tpu.images.superpixel import (
    Superpixel,
    SuperpixelData,
    SuperpixelTransformer,
)

__all__ = [
    "ImageFeaturizer",
    "ImageLIME",
    "ImageSetAugmenter",
    "ImageTransformer",
    "ResizeImageTransformer",
    "Superpixel",
    "SuperpixelData",
    "SuperpixelTransformer",
    "UnrollBinaryImage",
    "UnrollImage",
]
