"""images — image pipeline stages, featurization, and interpretability.

Equivalent of the reference's image-transformer AND image-featurizer modules
(SURVEY.md §2.2): ImageTransformer.scala:22-335, UnrollImage.scala:25-49,
ImageFeaturizer.scala:129-177, ImageLIME.scala:75-163,
Superpixel.scala:154-273, SuperpixelTransformer.scala:33.

Design note: pre-resize images are ragged (per-row sizes differ), so the
transform ops run per-row on host in numpy — exactly where the reference
runs OpenCV. The TPU path begins at UnrollImage: fixed-size CHW vectors,
batched into HBM by TPUModel/ImageFeaturizer.
"""

from mmlspark_tpu.images.transformer import (
    ImageSetAugmenter,
    ImageTransformer,
    ResizeImageTransformer,
    UnrollBinaryImage,
    UnrollImage,
)
from mmlspark_tpu.images.featurizer import ImageFeaturizer
from mmlspark_tpu.images.lime import ImageLIME
from mmlspark_tpu.images.superpixel import (
    Superpixel,
    SuperpixelData,
    SuperpixelTransformer,
)

__all__ = [
    "ImageFeaturizer",
    "ImageLIME",
    "ImageSetAugmenter",
    "ImageTransformer",
    "ResizeImageTransformer",
    "Superpixel",
    "SuperpixelData",
    "SuperpixelTransformer",
    "UnrollBinaryImage",
    "UnrollImage",
]
