"""Device-resident image preprocessing: the ImageTransformer op set as
jitted batched ops on (N, H, W, C) tensors.

The numpy ops in images/ops.py remain the SEMANTIC ORACLE — every op here
mirrors one of them and is parity-gated against it (tests/
test_image_dataplane.py: ±1 uint8 LSB for resize/crop/flip/color, 1e-5 for
normalize/unroll). The difference is execution shape: instead of a Python
loop resizing one row at a time on the host (BENCH_r05: 279 imgs/sec
through that path vs 6,375 device-resident — a 23x gap), a whole stage
CHAIN compiles into ONE XLA program over the full batch. The chip sees a
single fused gather+FMA+transpose kernel; the host sees one upload.

Programs are cached process-wide in core.dispatch.DispatchCache keyed by
the canonical chain signature, and every first (chain, input-shape)
dispatch is counted as a compile in profiling.dataplane_counters() — the
same accounting every other device stage uses.

Uint8 semantics: the oracle quantizes (np.rint -> uint8) after every op, so
the fused chain quantizes between stages too (jnp.rint on the f32
intermediate) — per-op parity holds through a chain, not just for single
ops. Values stay in [0, 255] (bilinear/gray are convex combinations), so
no clipping is needed. normalize/unroll are float-valued terminal ops.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.dispatch import dispatch_cache
from mmlspark_tpu.utils.profiling import dataplane_counters

#: ops the fused device path supports (blur/threshold/gaussian stay
#: host-only for now: rarely on the featurize hot path)
DEVICE_OPS = ("resize", "crop", "colorformat", "flip", "normalize")

#: OpenCV BGR2GRAY weights over (B, G, R) planes — same constants as the
#: numpy oracle (images/ops.py color_format)
_GRAY_W = (0.114, 0.587, 0.299)


def _resize_plan(h: int, w: int, height: int, width: int):
    """Static gather indices + lerp weights for OpenCV INTER_LINEAR
    pixel-center mapping — identical math to ops.resize_batch, computed
    once on the host and baked into the program as constants."""
    out_y = (np.arange(height) + 0.5) * h / height - 0.5
    out_x = (np.arange(width) + 0.5) * w / width - 0.5
    y0 = np.clip(np.floor(out_y).astype(np.int32), 0, h - 1)
    x0 = np.clip(np.floor(out_x).astype(np.int32), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    fy = np.clip(out_y - y0, 0, 1).astype(np.float32)[None, :, None, None]
    fx = np.clip(out_x - x0, 0, 1).astype(np.float32)[None, None, :, None]
    return y0, y1, x0, x1, fy, fx


def _resize(x, st):
    import jax.numpy as jnp

    height, width = st["height"], st["width"]
    h, w = int(x.shape[1]), int(x.shape[2])
    if (h, w) == (height, width):
        return x
    y0, y1, x0, x1, fy, fx = _resize_plan(h, w, height, width)
    top_rows = jnp.take(x, y0, axis=1)
    bot_rows = jnp.take(x, y1, axis=1)
    t_l = jnp.take(top_rows, x0, axis=2)
    t_r = jnp.take(top_rows, x1, axis=2)
    b_l = jnp.take(bot_rows, x0, axis=2)
    b_r = jnp.take(bot_rows, x1, axis=2)
    top = t_l * (1 - fx) + t_r * fx
    bot = b_l * (1 - fx) + b_r * fx
    return jnp.rint(top * (1 - fy) + bot * fy)


def _crop(x, st):
    cx, cy = st["x"], st["y"]
    ch, cw = st["height"], st["width"]
    h, w = int(x.shape[1]), int(x.shape[2])
    if cy + ch > h or cx + cw > w or cx < 0 or cy < 0:
        raise ValueError(f"crop ({cx},{cy},{cw}x{ch}) outside image {w}x{h}")
    return x[:, cy : cy + ch, cx : cx + cw, :]


def _flip(x, st):
    code = st["flip_code"]
    if code == 0:
        return x[:, ::-1, :, :]
    if code > 0:
        return x[:, :, ::-1, :]
    return x[:, ::-1, ::-1, :]


def _colorformat(x, st):
    import jax.numpy as jnp

    fmt = st["format"].lower()
    if fmt in ("bgr", "identity"):
        return x
    if int(x.shape[3]) == 1:
        if fmt == "gray":
            return x
        raise ValueError("cannot convert grayscale to color")
    if fmt == "gray":
        w = jnp.asarray(_GRAY_W, x.dtype)
        return jnp.rint((x[..., :3] * w).sum(axis=-1, keepdims=True))
    if fmt == "rgb":
        return x[..., ::-1]
    raise ValueError(f"unknown color format {fmt!r}")


def _normalize(x, st):
    import jax.numpy as jnp

    mean = jnp.asarray(np.asarray(st["mean"], np.float32))
    std = jnp.asarray(np.asarray(st["std"], np.float32))
    scale = np.float32(st.get("color_scale_factor", 1.0))
    return (x * scale - mean) / std


_APPLY: Dict[str, Callable] = {
    "resize": _resize,
    "crop": _crop,
    "flip": _flip,
    "colorformat": _colorformat,
    "normalize": _normalize,
}


def _unroll(x):
    """NHWC -> flat CHW float vectors — the UnrollImage layout (BGR channel
    planes), so fused prep output carries the same "unrolled" metadata
    contract host unroll does."""
    import jax.numpy as jnp

    n = x.shape[0]
    return jnp.transpose(x, (0, 3, 1, 2)).reshape(n, -1)


def chain_out_shape(
    stages: Sequence[Dict[str, Any]], in_shape: Tuple[int, int, int]
) -> Tuple[int, int, int]:
    """(H, W, C) after running `stages` — drives the "unrolled" metadata and
    the consuming network's input-shape check without tracing anything."""
    h, w, c = in_shape
    for st in stages:
        op = st["op"]
        if op == "resize":
            h, w = st["height"], st["width"]
        elif op == "crop":
            h, w = st["height"], st["width"]
        elif op == "colorformat" and st["format"].lower() == "gray":
            c = 1
        # flip / rgb / normalize: shape-preserving
    return h, w, c


def supported_chain(stages: Sequence[Dict[str, Any]]) -> bool:
    """True when every stage has a device implementation."""
    return all(st.get("op") in DEVICE_OPS for st in stages)


def _chain_key(
    stages: Sequence[Dict[str, Any]],
    unroll: bool,
    in_shape: Optional[Tuple[int, int, int]] = None,
):
    sig = tuple(
        tuple(sorted((k, _hashable(v)) for k, v in st.items())) for st in stages
    )
    return ("images.fused_prep", sig, unroll, in_shape)


def _hashable(v: Any):
    if isinstance(v, (list, tuple, np.ndarray)):
        return tuple(float(x) for x in np.asarray(v).ravel())
    return v


def fused_prep_program(
    stages: Sequence[Dict[str, Any]],
    unroll: bool = True,
    in_shape: Optional[Tuple[int, int, int]] = None,
) -> Callable:
    """Compile `stages` (ImageTransformer stage dicts) into ONE jitted
    program over an (N, H, W, C) batch; returns a callable batch -> device
    array ((N, C*H*W) f32 when `unroll`, else (N, H', W', C') f32).

    `in_shape=(H, W, C)` accepts flat (N, H*W*C) input instead and folds
    the un-flatten into the same program — the serving shape, where pixel
    columns travel as flat uint8 VECTORs (core/dataframe has no 4-D column
    type) and the reshape must not be a separate dispatch.

    Oracle parity holds per stage: value-producing uint8 ops round to
    integers (jnp.rint) exactly like the numpy oracle does before the next
    stage reads them (integers <= 255 are exact in f32), so a chain's ±1
    LSB bound does not compound. Programs are shared process-wide through
    the dispatch cache; per-shape compiles are counted in
    dataplane_counters().
    """
    stages = [dict(st) for st in stages]
    for st in stages:
        if st.get("op") not in DEVICE_OPS:
            raise ValueError(
                f"op {st.get('op')!r} has no device implementation "
                f"(supported: {DEVICE_OPS})"
            )
    in_shape = tuple(int(d) for d in in_shape) if in_shape is not None else None
    key = _chain_key(stages, unroll, in_shape)

    def build():
        import jax
        import jax.numpy as jnp

        def prep(x):
            y = x.astype(jnp.float32)
            if in_shape is not None:
                y = y.reshape((-1,) + in_shape)
            for st in stages:
                y = _APPLY[st["op"]](y, st)
            return _unroll(y) if unroll else y

        return jax.jit(prep)

    fn = dispatch_cache().compiled(key, build)

    def run(batch):
        if in_shape is None and batch.ndim == 3:  # grayscale HWC=1 convention
            batch = batch[:, :, :, None] if isinstance(batch, np.ndarray) else batch[..., None]
        dispatch_cache().note_dispatch(key, tuple(int(d) for d in batch.shape))
        return fn(batch)

    return run


def image_row_arrays(values: Sequence[Any]) -> Optional[list]:
    """Validate image-struct rows into HWC ndarrays (grayscale widened to
    HxWx1), or None when any row can't batch (null, non-dict, data=None).
    The ONE place the row contract lives — every fused_unrolled_batch call
    site goes through it."""
    if not len(values):
        return None
    arrays = []
    for row in values:
        if row is None or not isinstance(row, dict) or row.get("data") is None:
            return None
        img = np.asarray(row["data"])
        if img.ndim == 2:
            img = img[:, :, None]
        arrays.append(img)
    return arrays


def upload_batch(host_batch: np.ndarray, sharding: Any = None):
    """Counted host->HBM upload of a staged uint8/float batch — the one
    pipeline-entry transfer of a fused image chain. Delegates to the
    generic dataplane upload (core/prefetch.upload_host_chunk) so image and
    columnar chunks share one counted transfer point."""
    from mmlspark_tpu.core.prefetch import upload_host_chunk

    return upload_host_chunk(host_batch, sharding)


def prep_image_batch(
    batch: Any,
    stages: Sequence[Dict[str, Any]],
    unroll: bool = True,
    sharding: Any = None,
):
    """Run the fused chain over `batch`: a host (N, H, W, C) uint8 array
    (uploaded once, counted) or an already device-resident batch (no
    transfer). Returns the device result."""
    if isinstance(batch, np.ndarray):
        batch = upload_batch(batch, sharding)
    return fused_prep_program(stages, unroll=unroll)(batch)


def fused_unrolled_batch(
    arrays: Sequence[np.ndarray],
    size: Optional[Tuple[int, int]] = None,
    sharding: Any = None,
    max_rows: Optional[int] = None,
    pad_to_bucket: bool = False,
):
    """The ONE uniform/ragged dispatch behind every fused-unroll call site
    (ImageFeaturizer, UnrollImage(to_device=True), the image serving
    handler): pick the minimal stage chain for a list of HWC arrays, run
    the fused program, and return (device_vector, metadata).

    arrays: HWC ndarrays (grayscale already widened to HxWx1, no Nones —
        the image_row_arrays contract).
    size: (height, width) target; None keeps the native size (uniform
        batches only).
    max_rows: upload/program row bound. A larger batch stages and
        dispatches in max_rows chunks (last chunk padded so every chunk
        shares ONE compiled program) and the device outputs concatenate —
        a 500k-row column must not become a single giant h2d + XLA
        program sized to the whole frame (ImageFeaturizer passes its
        mini_batch_size).
    pad_to_bucket: pad the row count to the next power of two and trim the
        result (compiled, transfer-free) — the serving shape, where the
        adaptive coalescer produces many distinct batch sizes and tracing
        a program per exact N would stall the parse stage (same bucketing
        discipline as TPUModel dispatch).
    Returns None when the batch cannot fuse: empty, mixed channel counts,
    or ragged shapes with no target size.

    Chain selection: a uniform batch already at target size unrolls with
    stages=[] (nothing to resize); a uniform off-size batch fuses the
    resize into the device program; ragged source shapes host-resize
    grouped by shape (one ops.resize_batch per distinct shape) and the
    device chain is unroll-only.
    """
    from mmlspark_tpu.core.dispatch import bucket_rows, pad_rows, trim_rows
    from mmlspark_tpu.images import ops

    if not len(arrays):
        return None
    if len({a.shape[2] for a in arrays}) != 1:
        return None
    uniform = len({a.shape for a in arrays}) == 1
    if uniform:
        batch = np.stack(arrays)
        if size is None or tuple(batch.shape[1:3]) == tuple(size):
            stages: list = []
        else:
            stages = [{"op": "resize", "height": size[0], "width": size[1]}]
    elif size is None:
        return None
    else:
        batch = np.stack(ops.resize_groups(list(arrays), size[0], size[1]))
        stages = []
    meta = unrolled_metadata(chain_out_shape(stages, batch.shape[1:]))
    n = int(batch.shape[0])
    if pad_to_bucket:
        padded, real = pad_rows(batch, bucket_rows(n))
        dev = prep_image_batch(padded, stages, unroll=True, sharding=sharding)
        return trim_rows(dev, real), meta
    if max_rows is not None and n > max_rows:
        import jax.numpy as jnp

        parts = []
        for i in range(0, n, max_rows):
            chunk, _ = pad_rows(batch[i:i + max_rows], max_rows)
            parts.append(
                prep_image_batch(chunk, stages, unroll=True, sharding=sharding)
            )
        # only the LAST chunk carried pad rows, so one tail trim undoes it
        return trim_rows(jnp.concatenate(parts, axis=0), n), meta
    return prep_image_batch(batch, stages, unroll=True, sharding=sharding), meta


def unrolled_metadata(shape_hwc: Tuple[int, int, int]) -> Dict[str, Any]:
    """The "unrolled" column metadata consumers (TPUModel's
    extract_feature_matrix) use to un-scramble CHW planes."""
    h, w, c = shape_hwc
    return {"unrolled": {"order": "CHW", "height": int(h), "width": int(w),
                         "channels": int(c)}}
