"""Numpy implementations of the reference's OpenCV image ops.

Semantics match ImageTransformer.scala:22-207 (each op is one stage class
there): resize (bilinear), crop, color format, flip (OpenCV flip codes), box
blur, binary threshold, gaussian blur. Images are HxWxC uint8 arrays in BGR
channel order (the OpenCV/reference convention, preserved so unrolled
vectors feed models trained on BGR inputs identically).
"""

from __future__ import annotations

import numpy as np


def resize(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """Bilinear resize (OpenCV INTER_LINEAR semantics: pixel-center mapping).
    Delegates to resize_batch so there is exactly one interpolation kernel."""
    return resize_batch(img[None], height, width)[0]


def crop(img: np.ndarray, x: int, y: int, height: int, width: int) -> np.ndarray:
    h, w = img.shape[:2]
    if y + height > h or x + width > w or x < 0 or y < 0:
        raise ValueError(
            f"crop ({x},{y},{width}x{height}) outside image {w}x{h}"
        )
    return img[y : y + height, x : x + width].copy()


def flip(img: np.ndarray, flip_code: int) -> np.ndarray:
    """OpenCV codes: 0 = around x-axis (vertical flip), >0 = around y-axis
    (horizontal), <0 = both."""
    if flip_code == 0:
        return img[::-1].copy()
    if flip_code > 0:
        return img[:, ::-1].copy()
    return img[::-1, ::-1].copy()


def color_format(img: np.ndarray, fmt: str) -> np.ndarray:
    """Convert BGR to: gray | rgb | bgr (identity)."""
    fmt = fmt.lower()
    if fmt in ("bgr", "identity"):
        return img.copy()
    if img.ndim == 2 or img.shape[2] == 1:
        if fmt == "gray":
            return img.copy()
        raise ValueError("cannot convert grayscale to color")
    b, g, r = img[..., 0].astype(np.float64), img[..., 1].astype(np.float64), img[..., 2].astype(np.float64)
    if fmt == "gray":
        # OpenCV BGR2GRAY weights
        y = 0.114 * b + 0.587 * g + 0.299 * r
        return np.rint(y).astype(img.dtype)
    if fmt == "rgb":
        return img[..., ::-1].copy()
    raise ValueError(f"unknown color format {fmt!r}")


def _box_1d(im: np.ndarray, k: int, axis: int) -> np.ndarray:
    """Mean filter along one axis with BORDER_REFLECT_101-style edge padding."""
    if k <= 1:
        return im
    pad_l = (k - 1) // 2
    pad_r = k - 1 - pad_l
    pads = [(0, 0)] * im.ndim
    pads[axis] = (pad_l, pad_r)
    padded = np.pad(im, pads, mode="reflect" if im.shape[axis] > 1 else "edge")
    c = np.cumsum(padded, axis=axis, dtype=np.float64)
    zero = np.zeros_like(np.take(c, [0], axis=axis))
    c = np.concatenate([zero, c], axis=axis)
    n = im.shape[axis]
    hi = np.take(c, np.arange(k, k + n), axis=axis)
    lo = np.take(c, np.arange(0, n), axis=axis)
    return (hi - lo) / k


def blur(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """Box blur (OpenCV Imgproc.blur) with reflect borders."""
    out = _box_1d(img.astype(np.float64), int(height), 0)
    out = _box_1d(out, int(width), 1)
    return np.rint(out).astype(img.dtype)


def threshold(img: np.ndarray, thresh: float, max_val: float,
              threshold_type: str = "binary") -> np.ndarray:
    """OpenCV threshold types: binary | binary_inv | trunc | tozero |
    tozero_inv."""
    im = img.astype(np.float64)
    t = float(thresh)
    if threshold_type == "binary":
        out = np.where(im > t, max_val, 0)
    elif threshold_type == "binary_inv":
        out = np.where(im > t, 0, max_val)
    elif threshold_type == "trunc":
        out = np.minimum(im, t)
    elif threshold_type == "tozero":
        out = np.where(im > t, im, 0)
    elif threshold_type == "tozero_inv":
        out = np.where(im > t, 0, im)
    else:
        raise ValueError(f"unknown threshold type {threshold_type!r}")
    return out.astype(img.dtype)


def gaussian_kernel(img: np.ndarray, aperture_size: int, sigma: float) -> np.ndarray:
    """Gaussian blur (OpenCV GaussianBlur), separable implementation."""
    k = int(aperture_size)
    if k % 2 == 0:
        k += 1
    if sigma <= 0:  # OpenCV default sigma from kernel size
        sigma = 0.3 * ((k - 1) * 0.5 - 1) + 0.8
    r = k // 2
    xs = np.arange(-r, r + 1, dtype=np.float64)
    kern = np.exp(-(xs ** 2) / (2 * sigma * sigma))
    kern /= kern.sum()

    def conv_axis(im, axis):
        pads = [(0, 0)] * im.ndim
        pads[axis] = (r, r)
        padded = np.pad(im, pads, mode="reflect" if im.shape[axis] > 1 else "edge")
        out = np.zeros_like(im, dtype=np.float64)
        for i, kv in enumerate(kern):
            sl = [slice(None)] * im.ndim
            sl[axis] = slice(i, i + im.shape[axis])
            out += kv * padded[tuple(sl)]
        return out

    out = conv_axis(img.astype(np.float64), 0)
    out = conv_axis(out, 1)
    return np.rint(out).astype(img.dtype)


def normalize(img: np.ndarray, mean, std, color_scale_factor: float = 1.0) -> np.ndarray:
    """Per-channel standardization (reference ImageTransformer.normalize):
    (img * color_scale_factor - mean) / std, broadcast over the channel
    axis. Float-valued — a terminal prep op feeding unroll/a network, not a
    row-materializing stage (uint8 rows cannot hold it)."""
    im = np.asarray(img, np.float64)
    if im.ndim == 2:
        im = im[:, :, None]
    mean = np.asarray(mean, np.float64)
    std = np.asarray(std, np.float64)
    return ((im * float(color_scale_factor) - mean) / std).astype(np.float32)


def unroll(imgs: np.ndarray) -> np.ndarray:
    """Uniform (N, H, W, C) batch -> (N, C*H*W) float CHW-flattened vectors
    (the UnrollImage layout, BGR channel planes) — the oracle the fused
    device unroll is parity-gated against."""
    imgs = np.asarray(imgs)
    if imgs.ndim == 3:
        imgs = imgs[:, :, :, None]
    return (
        np.transpose(imgs, (0, 3, 1, 2))
        .reshape(imgs.shape[0], -1)
        .astype(np.float64)
    )


def resize_groups(imgs, height: int, width: int):
    """Resize a ragged list of HxWxC images by grouping same-shape images
    into resize_batch calls — the batched host fallback for call sites that
    would otherwise loop `resize(img)` per row (decode output is ragged by
    nature; most datasets still cluster on a few source shapes). Returns
    per-input resized arrays in input order."""
    arrays = [np.asarray(im) for im in imgs]
    by_shape: dict = {}
    for i, im in enumerate(arrays):
        by_shape.setdefault(im.shape, []).append(i)
    out: list = [None] * len(arrays)
    for idx in by_shape.values():
        batch = resize_batch(np.stack([arrays[i] for i in idx]), height, width)
        for j, i in enumerate(idx):
            out[i] = batch[j]
    return out


OPS = {
    "resize": lambda img, p: resize(img, p["height"], p["width"]),
    "crop": lambda img, p: crop(img, p["x"], p["y"], p["height"], p["width"]),
    "colorformat": lambda img, p: color_format(img, p["format"]),
    "flip": lambda img, p: flip(img, p["flip_code"]),
    "blur": lambda img, p: blur(img, p["height"], p["width"]),
    "threshold": lambda img, p: threshold(
        img, p["threshold"], p["max_val"], p.get("threshold_type", "binary")
    ),
    "gaussiankernel": lambda img, p: gaussian_kernel(
        img, p["aperture_size"], p["sigma"]
    ),
}


def resize_batch(imgs: np.ndarray, height: int, width: int) -> np.ndarray:
    """Bilinear resize of a uniform (N, H, W, C) batch in one vectorized
    pass — the ImageTransformer fast path for resize-only pipelines (the
    ImageFeaturizer prep), replacing N per-image calls."""
    n, h, w = imgs.shape[:3]
    if (h, w) == (height, width):
        return imgs.copy()
    out_y = (np.arange(height) + 0.5) * h / height - 0.5
    out_x = (np.arange(width) + 0.5) * w / width - 0.5
    y0 = np.clip(np.floor(out_y).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(out_x).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    fy = np.clip(out_y - y0, 0, 1)[None, :, None, None]
    fx = np.clip(out_x - x0, 0, 1)[None, None, :, None]
    im = imgs.astype(np.float64)
    if im.ndim == 3:
        im = im[:, :, :, None]
    t_l = im[:, y0][:, :, x0]
    t_r = im[:, y0][:, :, x1]
    b_l = im[:, y1][:, :, x0]
    b_r = im[:, y1][:, :, x1]
    top = t_l * (1 - fx) + t_r * fx
    bot = b_l * (1 - fx) + b_r * fx
    out = np.rint(top * (1 - fy) + bot * fy).astype(imgs.dtype)
    return out if imgs.ndim == 4 else out[:, :, :, 0]
