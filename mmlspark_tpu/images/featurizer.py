"""ImageFeaturizer: headless DNN featurization of image columns.

Reference: image-featurizer/src/main/scala/ImageFeaturizer.scala:129-177 —
resize/unroll the image column to the model's input shape, truncate the
network `cut_output_layers` layers from the output (layer_names[cut] names
the new output node), run the inner model, emit a VECTOR column. setModel
consumes a downloader ModelSchema (:73-77), wiring layerNames + inputNode.

TPU notes: the heavy path is the inner TPUModel's jit minibatch eval
(models/tpu_model.py) — one compiled program per (truncated spec, batch
bucket), bfloat16-able, windowed H2D. The featurizer itself is glue.

Dataplane: the emitted feature column is DEVICE-BACKED (the inner
TPUModel's result stays on HBM), so `featurize -> TPUModel -> postprocess`
chains score with zero host round-trips between stages — the image decode /
resize / unroll prologue is host work by nature (object-dtype rows) and is
where the single pipeline-entry upload happens. See docs/dataplane.md.
"""

from __future__ import annotations

from typing import Any, List, Optional, Union

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, Field
from mmlspark_tpu.core.params import (
    ComplexParam,
    Param,
    TypeConverters,
    Wrappable,
)
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.dnn.network import NetworkBundle
from mmlspark_tpu.images.transformer import (
    ResizeImageTransformer,
    UnrollImage,
)
from mmlspark_tpu.models.tpu_model import TPUModel


class ImageFeaturizer(Transformer, Wrappable):
    """Featurize an image (or binary) column through a truncated network.

    cut_output_layers=0 leaves the network intact; 1 (default) removes the
    output layer so the penultimate activations become the features — the
    transfer-learning configuration.
    """

    model = ComplexParam("model", "The NetworkBundle used in the featurizer")
    input_col = Param("input_col", "The name of the input column", TypeConverters.to_string)
    output_col = Param("output_col", "The name of the output column", TypeConverters.to_string)
    cut_output_layers = Param(
        "cut_output_layers",
        "The number of layers to cut off the end of the network; 0 leaves "
        "the network intact, 1 removes the output layer, etc",
        TypeConverters.to_int,
    )
    layer_names = Param(
        "layer_names",
        "Named layers to choose from; the first entries of this array "
        "should be closer to the output node",
        TypeConverters.to_list,
    )
    drop_na = Param(
        "drop_na", "Whether to drop null images before mapping",
        TypeConverters.to_boolean,
    )
    mini_batch_size = Param(
        "mini_batch_size", "Rows per device dispatch", TypeConverters.to_int
    )
    fused = Param(
        "fused",
        "Use the fused device prep path (stack once, upload once, one XLA "
        "resize+unroll program) when the image column is batchable; False "
        "restores the per-row host prep",
        TypeConverters.to_boolean,
    )
    dtype = Param(
        "dtype",
        "Compute dtype override for the inner TPUModel eval: bfloat16 "
        "halves MXU cycle cost on TPU, float32 forces full precision (the "
        "rollback); empty (default) inherits the bundle network's own "
        "dtype. Feature columns stay float32 (parity gated by the zoo "
        "bf16 tests)",
        TypeConverters.to_string,
    )

    def __init__(
        self,
        model: Optional[Any] = None,
        input_col: str = "image",
        output_col: Optional[str] = None,
        cut_output_layers: int = 1,
    ):
        super().__init__()
        self._set_defaults(
            input_col="image",
            output_col="features",
            cut_output_layers=1,
            drop_na=True,
            mini_batch_size=64,
            fused=True,
            dtype="",
        )
        if model is not None:
            self.set_model(model)
        self.set(self.input_col, input_col)
        if output_col is not None:
            self.set(self.output_col, output_col)
        self.set(self.cut_output_layers, cut_output_layers)

    # -- fluent setters --------------------------------------------------------

    def set_model(self, value: Union[NetworkBundle, "ModelSchema"]) -> "ImageFeaturizer":
        """Accepts a NetworkBundle directly, or a downloader ModelSchema
        (reference setModel(modelSchema), ImageFeaturizer.scala:73-77) whose
        layerNames and uri wire the featurizer in one call."""
        from mmlspark_tpu.downloader.schema import ModelSchema

        if isinstance(value, ModelSchema):
            self.set_layer_names(list(value.layer_names))
            bundle = NetworkBundle.load_from_dir(value.local_path())
            return self.set(self.model, bundle)
        if not isinstance(value, NetworkBundle):
            raise TypeError("set_model expects a NetworkBundle or ModelSchema")
        return self.set(self.model, value)

    def get_model(self) -> NetworkBundle:
        return self.get(self.model)

    def set_input_col(self, v: str):
        return self.set(self.input_col, v)

    def set_output_col(self, v: str):
        return self.set(self.output_col, v)

    def set_cut_output_layers(self, v: int):
        return self.set(self.cut_output_layers, v)

    def set_layer_names(self, v: List[str]):
        return self.set(self.layer_names, v)

    def set_mini_batch_size(self, v: int):
        return self.set(self.mini_batch_size, v)

    def set_fused(self, v: bool):
        return self.set(self.fused, v)

    def set_dtype(self, v: str):
        return self.set(self.dtype, v)

    # -- helpers ---------------------------------------------------------------

    def _effective_layer_names(self) -> List[str]:
        """Output->input order. Defaults to the bundle network's own layer
        names reversed, so cut_output_layers indexes straight into it."""
        if self.is_set(self.layer_names):
            return list(self.get(self.layer_names))
        return list(reversed(self.get_model().network.layer_names))

    def _output_layer(self) -> Optional[str]:
        cut = self.get(self.cut_output_layers)
        if cut == 0:
            return None  # intact network
        names = self._effective_layer_names()
        if not 0 <= cut < len(names):
            raise ValueError(
                f"cut_output_layers={cut} out of range for {len(names)} layers"
            )
        return names[cut]

    # -- fused device prep -----------------------------------------------------

    def _fused_unrolled(self, df: DataFrame, in_col: str,
                        resized: str, h: int, w: int) -> Optional[DataFrame]:
        """Device-resident prep: stack rows once on host, upload ONCE, run
        the fused resize+unroll XLA program, emit a device-backed unrolled
        column. Returns None when the column is not batchable (nulls,
        mixed channel counts) — the host path then runs. Ragged source
        shapes still qualify: they host-resize grouped by shape (one
        resize_batch per distinct shape) and the device chain is
        unroll-only."""
        from mmlspark_tpu.images import device_ops

        arrays = device_ops.image_row_arrays(list(df[in_col]))
        if arrays is None:
            return None
        fused = device_ops.fused_unrolled_batch(
            arrays, size=(h, w),
            # bound the staged upload + program rows: a frame-sized batch
            # must not become one giant h2d/XLA program (chunks share one
            # compiled shape, device outputs concatenate)
            max_rows=self.get(self.mini_batch_size),
        )
        if fused is None:
            return None
        dev, meta = fused
        return df.with_column(resized, dev, DataType.VECTOR, metadata=meta)

    # -- stage contract --------------------------------------------------------

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.VECTOR)]

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get(self.input_col)
        bundle = self.get_model()
        h, w = bundle.network.input_shape[0], bundle.network.input_shape[1]
        resized = "__resized__"

        if self.get(self.drop_na):
            keep = np.array([v is not None for v in df[in_col]], bool)
            if not keep.all():
                df = df.filter(keep)

        dtype = df.dtype(in_col)
        if dtype not in (DataType.STRUCT, DataType.BINARY):
            raise ValueError(
                f"input column {in_col!r} needs image STRUCT or BINARY type, "
                f"got {dtype.value}"
            )
        work_col = in_col
        if dtype == DataType.BINARY:
            # decode ONCE — the fused attempt and the host fallback read
            # the same decoded rows (decode is the dominant host cost;
            # falling back must not pay it twice)
            from mmlspark_tpu.io.image import decode_image

            rows = np.empty(len(df), object)
            rows[:] = [decode_image(bytes(raw)) for raw in df[in_col]]
            work_col = "__decoded__"
            df = df.with_column(work_col, Column(rows, DataType.STRUCT))
        unrolled = (
            self._fused_unrolled(df, work_col, resized, h, w)
            if self.get(self.fused) else None
        )
        if unrolled is None:
            prepared = (
                ResizeImageTransformer(work_col, "__prep__", height=h, width=w)
                .transform(df)
            )
            unrolled = UnrollImage("__prep__", resized).transform(prepared)
            unrolled = unrolled.drop("__prep__")
        if work_col != in_col:
            unrolled = unrolled.drop(work_col)

        inner = TPUModel(
            bundle,
            input_col=resized,
            output_col=self.get(self.output_col),
            mini_batch_size=self.get(self.mini_batch_size),
            dtype=self.get(self.dtype),
        )
        out_layer = self._output_layer()
        if out_layer is not None:
            inner.set_output_layer(out_layer)
        return inner.transform(unrolled).drop(resized)
