"""recommendation — SAR recommender + ranking evaluation.

Equivalent of the reference's recommendation module (SURVEY.md §2.3, 2,407
LoC): SAR.scala:64-188 (item-item similarity x time-decayed user affinity),
SARModel.scala:141 (recommendForAllUsers), RecommendationIndexer,
RankingAdapter, RankingEvaluator (NDCG/MAP@k), RankingTrainValidationSplit.

TPU-first design: the reference computes co-occurrence and scores with Spark
joins/aggregations; here interactions densify to a user x item matrix so
co-occurrence (B^T B) and scoring (A @ S) are two MXU matmuls under jit.
"""

from mmlspark_tpu.recommendation.indexer import (
    RecommendationIndexer,
    RecommendationIndexerModel,
)
from mmlspark_tpu.recommendation.sar import SAR, SARModel
from mmlspark_tpu.recommendation.ranking import (
    RankingAdapter,
    RankingEvaluator,
    RankingTrainValidationSplit,
)

__all__ = [
    "RankingAdapter",
    "RankingEvaluator",
    "RankingTrainValidationSplit",
    "RecommendationIndexer",
    "RecommendationIndexerModel",
    "SAR",
    "SARModel",
]
