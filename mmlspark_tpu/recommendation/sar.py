"""SAR — Smart Adaptive Recommendations.

Reference: SAR.scala:64-188 (item-item similarity: cooccurrence | lift |
jaccard, SAR.scala:187-188; time-decayed user affinity), SARModel.scala:141
(recommendForAllUsers). The reference builds these with Spark joins; here:

    B (users x items, binary occurrence)  ->  C = B^T B      (one matmul)
    A (users x items, decayed affinity)   ->  scores = A @ S (one matmul)

both jit-compiled — co-occurrence and scoring ride the MXU instead of a
shuffle. Seen items are masked out of recommendations like the reference.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, Field
from mmlspark_tpu.core.params import ComplexParam, Param, TypeConverters, Wrappable
from mmlspark_tpu.core.pipeline import Estimator, Model

SIMILARITY_FUNCTIONS = ("jaccard", "lift", "cooccurrence")


def _is_sparse(m) -> bool:
    return hasattr(m, "tocsr") and hasattr(m, "nnz")


@functools.partial(__import__("jax").jit, static_argnames=())
def _cooccurrence(b):
    return b.T @ b


@functools.partial(__import__("jax").jit, static_argnames=())
def _score(a, s):
    return a @ s


class SAR(Estimator, Wrappable):
    """Smart Adaptive Recommendations estimator: item-item similarity + time-decayed user affinity (SAR.scala:64-188)."""

    user_col = Param("user_col", "User id column (integer-indexed)", TypeConverters.to_string)
    item_col = Param("item_col", "Item id column (integer-indexed)", TypeConverters.to_string)
    rating_col = Param("rating_col", "Rating column", TypeConverters.to_string)
    time_col = Param("time_col", "Event timestamp column (seconds or datetime64)", TypeConverters.to_string)
    similarity_function = Param(
        "similarity_function", "jaccard | lift | cooccurrence", TypeConverters.to_string
    )
    support_threshold = Param(
        "support_threshold", "Min co-occurrence count to keep a similarity", TypeConverters.to_int
    )
    time_decay_coeff = Param(
        "time_decay_coeff", "Affinity half-life in days", TypeConverters.to_int
    )
    start_time = Param(
        "start_time", "Custom reference 'now' for historical data "
        "(reference SAR.scala:236-238 startTime); default: max activity time",
        TypeConverters.to_string,
    )
    start_time_format = Param(
        "start_time_format", "strptime format for start_time "
        "(Python format strings, not Java SimpleDateFormat)",
        TypeConverters.to_string,
    )
    activity_time_format = Param(
        "activity_time_format", "strptime format for string time columns",
        TypeConverters.to_string,
    )

    # past this many user x item cells, fit builds sparse matrices
    _DENSE_LIMIT = 50_000_000

    def __init__(self, user_col: str = "user_idx", item_col: str = "item_idx",
                 rating_col: str = "rating", time_col: Optional[str] = None,
                 similarity_function: str = "jaccard", support_threshold: int = 4,
                 time_decay_coeff: int = 30,
                 start_time: Optional[str] = None):
        super().__init__()
        self._set_defaults(
            user_col="user_idx", item_col="item_idx", rating_col="rating",
            similarity_function="jaccard", support_threshold=4, time_decay_coeff=30,
            start_time_format="%Y/%m/%dT%H:%M:%S",
            activity_time_format="%Y/%m/%dT%H:%M:%S",
        )
        if start_time:
            self.set(self.start_time, start_time)
        self.set(self.user_col, user_col)
        self.set(self.item_col, item_col)
        self.set(self.rating_col, rating_col)
        if time_col:
            self.set(self.time_col, time_col)
        if similarity_function not in SIMILARITY_FUNCTIONS:
            raise ValueError(f"similarity_function must be one of {SIMILARITY_FUNCTIONS}")
        self.set(self.similarity_function, similarity_function)
        self.set(self.support_threshold, support_threshold)
        self.set(self.time_decay_coeff, time_decay_coeff)

    def fit(self, df: DataFrame) -> "SARModel":
        import jax

        users = df[self.get(self.user_col)].astype(np.int64)
        items = df[self.get(self.item_col)].astype(np.int64)
        ratings = (
            df[self.get(self.rating_col)].astype(np.float64)
            if self.get(self.rating_col) in df
            else np.ones(len(df))
        )
        n_users = int(users.max()) + 1 if len(users) else 0
        n_items = int(items.max()) + 1 if len(items) else 0

        # time-decayed affinity: a(u,i) = sum_k r_k * 2^(-(t_ref - t_k)/T).
        # Differences quantize to whole MINUTES before the exponent — the
        # upstream truncation (SAR.scala:87-91 divides epoch-ms by 1000*60 in
        # Long arithmetic), kept so affinities match reference fixtures bit
        # for bit.
        if self.is_set(self.time_col):
            t = df[self.get(self.time_col)]
            if t.dtype == object or t.dtype.kind in "SU":
                from datetime import datetime, timezone

                # UTC-pin parsed timestamps: naive strptime().timestamp()
                # would apply the machine's local DST rules, shifting decay
                # across a DST boundary by a whole minute bucket
                fmt = self.get(self.activity_time_format)
                t = np.array(
                    [
                        datetime.strptime(str(v), fmt)
                        .replace(tzinfo=timezone.utc).timestamp()
                        for v in t
                    ],
                    np.float64,
                )
            elif t.dtype.kind == "M":
                t = t.astype("datetime64[s]").astype(np.float64)
            else:
                t = t.astype(np.float64)
            if self.is_set(self.start_time):
                from datetime import datetime, timezone

                t_ref = datetime.strptime(
                    self.get(self.start_time), self.get(self.start_time_format)
                ).replace(tzinfo=timezone.utc).timestamp()
            else:
                t_ref = float(t.max())
            halflife_min = self.get(self.time_decay_coeff) * 24.0 * 60.0
            # trunc of the DIFFERENCE (not per-timestamp): the reference
            # computes (refMs - actMs) / 60000 in Long arithmetic
            # (SAR.scala:89) — subtraction first, truncating division after
            diff_min = np.trunc((t_ref - t) / 60.0)
            decay = np.power(2.0, -diff_min / halflife_min)
        else:
            decay = np.ones(len(df))

        # Dense user x item matrices ride the MXU; past _DENSE_LIMIT cells
        # (4 GB-class at 100k users x 10k items) both matrices go
        # scipy.sparse — the reference's SAR is built from co-occurrence
        # aggregations for exactly this reason, and events are sparse.
        sparse_mode = n_users * n_items > self._DENSE_LIMIT
        if sparse_mode:
            import scipy.sparse as sp

            affinity = sp.coo_matrix(
                ((ratings * decay).astype(np.float32), (users, items)),
                shape=(n_users, n_items),
            ).tocsr()  # coo->csr sums duplicate (user, item) entries
            occ = sp.coo_matrix(
                (np.ones(len(users), np.float32), (users, items)),
                shape=(n_users, n_items),
            ).tocsr()
            occ.data[:] = 1.0  # binary occurrence, duplicates collapsed
            occurrence = occ
            c = np.asarray((occ.T @ occ).todense(), np.float64)
        else:
            affinity = np.zeros((n_users, n_items), np.float32)
            np.add.at(affinity, (users, items), ratings * decay)

            occurrence = np.zeros((n_users, n_items), np.float32)
            occurrence[users, items] = 1.0
            c = np.asarray(_cooccurrence(jax.device_put(occurrence)), np.float64)

        thr = float(self.get(self.support_threshold))
        c = np.where(c >= thr, c, 0.0)
        diag = np.diag(c).copy()
        fn = self.get(self.similarity_function)
        with np.errstate(divide="ignore", invalid="ignore"):
            if fn == "cooccurrence":
                sim = c
            elif fn == "lift":
                sim = c / (diag[:, None] * diag[None, :])
            else:  # jaccard
                sim = c / (diag[:, None] + diag[None, :] - c)
        sim = np.nan_to_num(sim, nan=0.0, posinf=0.0, neginf=0.0)

        model = SARModel(
            sim.astype(np.float32), affinity,
            occurrence.astype(bool),
        )
        for p in ("user_col", "item_col", "rating_col"):
            model.set(p, self.get(p))
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field("prediction", DataType.DOUBLE)]


class SARModel(Model, Wrappable):
    """Fitted SAR: scores = user affinity @ item similarity; top-k with seen-item masking (SARModel.scala:141)."""

    user_col = Param("user_col", "User id column", TypeConverters.to_string)
    item_col = Param("item_col", "Item id column", TypeConverters.to_string)
    rating_col = Param("rating_col", "Rating column", TypeConverters.to_string)
    item_similarity = ComplexParam("item_similarity", "Item-item similarity matrix")
    user_affinity = ComplexParam("user_affinity", "User-item affinity matrix")
    seen = ComplexParam("seen", "Seen user-item occurrence mask")

    def __init__(self, item_similarity: Optional[np.ndarray] = None,
                 user_affinity: Optional[np.ndarray] = None,
                 seen: Optional[np.ndarray] = None):
        super().__init__()
        self._set_defaults(user_col="user_idx", item_col="item_idx", rating_col="rating")
        def _keep(m):  # scipy sparse passes through; everything else densifies
            return m if _is_sparse(m) else np.asarray(m)

        if item_similarity is not None:
            self.set(self.item_similarity, np.asarray(item_similarity))
        if user_affinity is not None:
            self.set(self.user_affinity, _keep(user_affinity))
        if seen is not None:
            self.set(self.seen, _keep(seen))

    def get_item_similarity(self) -> np.ndarray:
        return self.get(self.item_similarity)

    def get_user_affinity(self) -> np.ndarray:
        return self.get(self.user_affinity)

    _BLOCK = 4096  # users scored per block in the sparse path

    def _scores(self) -> np.ndarray:
        """Full dense (n_users, n_items) score matrix. For sparse models
        prefer _score_block / recommend_for_all_users, which never
        materialize more than _BLOCK rows at once."""
        aff = self.get(self.user_affinity)
        if _is_sparse(aff):
            return np.asarray(
                (aff @ self.get(self.item_similarity)), np.float32
            )
        import jax

        return np.asarray(
            _score(
                jax.device_put(aff.astype(np.float32)),
                jax.device_put(self.get(self.item_similarity).astype(np.float32)),
            )
        )

    def _score_block(self, user_idx: np.ndarray) -> np.ndarray:
        """(len(user_idx), n_items) scores for a block of users."""
        aff = self.get(self.user_affinity)
        sim = self.get(self.item_similarity)
        if _is_sparse(aff):
            return np.asarray(aff[user_idx] @ sim, np.float32)
        return aff[user_idx].astype(np.float32) @ sim

    def transform(self, df: DataFrame) -> DataFrame:
        """Score each (user, item) row: affinity-weighted similarity."""
        aff = self.get(self.user_affinity)
        n_users, n_items = aff.shape
        users = df[self.get(self.user_col)].astype(np.int64)
        items = df[self.get(self.item_col)].astype(np.int64)
        pred = np.zeros(len(df), np.float64)
        ok = (users < n_users) & (items < n_items) & (users >= 0) & (items >= 0)
        uniq, inv = np.unique(users[ok], return_inverse=True)
        ok_rows = np.nonzero(ok)[0]
        ok_items = items[ok]
        # block over the distinct users actually referenced; only _BLOCK
        # scored rows live at a time (the point of the sparse path)
        for s in range(0, len(uniq), self._BLOCK):
            blk = uniq[s : s + self._BLOCK]
            scored = self._score_block(blk)
            in_blk = (inv >= s) & (inv < s + len(blk))
            pred[ok_rows[in_blk]] = scored[inv[in_blk] - s, ok_items[in_blk]]
        return df.with_column("prediction", pred, DataType.DOUBLE)

    def recommend_for_all_users(self, num_items: int = 10,
                                remove_seen: bool = True) -> DataFrame:
        """-> DataFrame(user, recommendations: [item ids], ratings: [scores])
        (reference: SARModel.recommendForAllUsers). Blocked: peak memory is
        O(_BLOCK x n_items) regardless of user count."""
        aff = self.get(self.user_affinity)
        seen = self.get(self.seen)
        n_users, n_items = aff.shape
        k = min(num_items, n_items)
        recs = np.empty(n_users, dtype=object)
        vals = np.empty(n_users, dtype=object)
        for s in range(0, n_users, self._BLOCK):
            idx = np.arange(s, min(s + self._BLOCK, n_users))
            scores = self._score_block(idx).astype(np.float64)
            if remove_seen:
                blk_seen = seen[idx]
                if _is_sparse(blk_seen):
                    blk_seen = np.asarray(blk_seen.todense())
                scores[np.asarray(blk_seen, bool)] = -np.inf
            top = np.argsort(-scores, axis=1)[:, :k]
            top_scores = np.take_along_axis(scores, top, axis=1)
            for r, u in enumerate(idx):
                keep = np.isfinite(top_scores[r])
                recs[u] = [int(i) for i in top[r][keep]]
                vals[u] = [float(x) for x in top_scores[r][keep]]
        return DataFrame(
            {
                self.get(self.user_col): Column(
                    np.arange(n_users, dtype=np.int64), DataType.LONG
                ),
                "recommendations": Column(recs, DataType.ARRAY),
                "ratings": Column(vals, DataType.ARRAY),
            }
        )

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field("prediction", DataType.DOUBLE)]
