"""Ranking evaluation: RankingAdapter, RankingEvaluator,
RankingTrainValidationSplit.

Reference: recommendation/RankingAdapter.scala, RankingEvaluator.scala
(ndcgAt, map, precisionAtk, recallAtK), RankingTrainValidationSplit.scala
(per-user stratified split + param-map search).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, Field
from mmlspark_tpu.core.params import ComplexParam, Param, TypeConverters, Wrappable
from mmlspark_tpu.core.pipeline import Estimator, Evaluator, Model


def _ndcg_at_k(pred: List[Any], label: List[Any], k: int) -> float:
    if not label:
        return 0.0
    rel = set(label)
    dcg = sum(
        1.0 / np.log2(i + 2) for i, p in enumerate(pred[:k]) if p in rel
    )
    idcg = sum(1.0 / np.log2(i + 2) for i in range(min(len(rel), k)))
    return float(dcg / idcg) if idcg else 0.0


def _ap_numerator(pred: List[Any], rel: set) -> float:
    hits = 0
    total = 0.0
    for i, p in enumerate(pred):
        if p in rel:
            hits += 1
            total += hits / (i + 1.0)
    return total


def _map_at_k(pred: List[Any], label: List[Any], k: int) -> float:
    """Spark meanAveragePrecision semantics (reference RankingEvaluator
    "map"): scan the FULL prediction list (no cutoff) and normalize by the
    full relevant-set size."""
    if not label:
        return 0.0
    rel = set(label)
    return float(_ap_numerator(pred, rel) / len(rel))


def _map_at_k_cut(pred: List[Any], label: List[Any], k: int) -> float:
    """mapAtK variant: cut off at k, normalize by min(|relevant|, k)."""
    if not label:
        return 0.0
    rel = set(label)
    return float(_ap_numerator(pred[:k], rel) / min(len(rel), k))


def _precision_at_k(pred: List[Any], label: List[Any], k: int) -> float:
    if k == 0:
        return 0.0
    rel = set(label)
    return float(sum(1 for p in pred[:k] if p in rel) / k)


def _recall_at_k(pred: List[Any], label: List[Any], k: int) -> float:
    if not label:
        return 0.0
    rel = set(label)
    return float(sum(1 for p in pred[:k] if p in rel) / len(rel))


_METRICS = {
    "ndcgAt": _ndcg_at_k,
    "map": _map_at_k,
    "mapAtK": _map_at_k_cut,
    "precisionAtk": _precision_at_k,
    "recallAtK": _recall_at_k,
}


class RankingEvaluator(Evaluator, Wrappable):
    """Evaluate a (prediction list, label list) per-user DataFrame."""

    k = Param("k", "Cutoff for @k metrics", TypeConverters.to_int)
    metric_name = Param("metric_name", f"One of {sorted(_METRICS)}", TypeConverters.to_string)
    prediction_col = Param("prediction_col", "Recommended item list column", TypeConverters.to_string)
    label_col = Param("label_col", "Relevant item list column", TypeConverters.to_string)

    def __init__(self, metric_name: str = "ndcgAt", k: int = 10,
                 prediction_col: str = "prediction", label_col: str = "label"):
        super().__init__()
        self._set_defaults(
            metric_name="ndcgAt", k=10, prediction_col="prediction", label_col="label"
        )
        if metric_name not in _METRICS:
            raise ValueError(f"metric_name must be one of {sorted(_METRICS)}")
        self.set(self.metric_name, metric_name)
        self.set(self.k, k)
        self.set(self.prediction_col, prediction_col)
        self.set(self.label_col, label_col)

    def evaluate(self, df: DataFrame) -> float:
        fn = _METRICS[self.get(self.metric_name)]
        k = self.get(self.k)
        preds = df[self.get(self.prediction_col)]
        labels = df[self.get(self.label_col)]
        values = [fn(list(p), list(l), k) for p, l in zip(preds, labels)]
        return float(np.mean(values)) if values else 0.0

    def is_larger_better(self) -> bool:
        return True


class RankingAdapter(Estimator, Wrappable):
    """Fit a recommender, emit per-user (prediction, label) lists for the
    evaluator (reference RankingAdapter mode='allUsers')."""

    recommender = ComplexParam("recommender", "The recommendation estimator (SAR)")
    k = Param("k", "Recommendations per user", TypeConverters.to_int)
    min_ratings_per_user = Param(
        "min_ratings_per_user", "Drop users with fewer relevant items", TypeConverters.to_int
    )

    def __init__(self, recommender=None, k: int = 10, min_ratings_per_user: int = 1):
        super().__init__()
        self._set_defaults(k=10, min_ratings_per_user=1)
        if recommender is not None:
            self.set(self.recommender, recommender)
        self.set(self.k, k)
        self.set(self.min_ratings_per_user, min_ratings_per_user)

    def fit(self, df: DataFrame) -> "RankingAdapterModel":
        rec = self.get(self.recommender)
        fitted = rec.fit(df)
        model = RankingAdapterModel(fitted, rec.get("user_col"), rec.get("item_col"))
        model.set(model.k, self.get(self.k))
        model.set(model.min_ratings_per_user, self.get(self.min_ratings_per_user))
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return [
            Field("user", DataType.LONG),
            Field("prediction", DataType.ARRAY),
            Field("label", DataType.ARRAY),
        ]


class RankingAdapterModel(Model, Wrappable):
    """Fitted RankingAdapter: per-user top-k recommendations + ground-truth lists for ranking metrics."""

    recommender_model = ComplexParam("recommender_model", "Fitted recommender")
    user_col_name = Param("user_col_name", "User column", TypeConverters.to_string)
    item_col_name = Param("item_col_name", "Item column", TypeConverters.to_string)
    k = Param("k", "Recommendations per user", TypeConverters.to_int)
    min_ratings_per_user = Param(
        "min_ratings_per_user", "Drop users with fewer relevant items", TypeConverters.to_int
    )

    def __init__(self, recommender_model=None, user_col: str = "user_idx",
                 item_col: str = "item_idx"):
        super().__init__()
        self._set_defaults(k=10, min_ratings_per_user=1)
        if recommender_model is not None:
            self.set(self.recommender_model, recommender_model)
        self.set(self.user_col_name, user_col)
        self.set(self.item_col_name, item_col)

    def transform(self, df: DataFrame) -> DataFrame:
        """df = held-out interactions; label = the user's actual items there,
        prediction = the model's top-k (seen-in-training removed)."""
        rec_model = self.get(self.recommender_model)
        recs = rec_model.recommend_for_all_users(self.get(self.k))
        rec_by_user: Dict[int, List[int]] = {
            int(u): list(r)
            for u, r in zip(recs[recs.columns[0]], recs["recommendations"])
        }
        u_col, i_col = self.get(self.user_col_name), self.get(self.item_col_name)
        actual: Dict[int, List[int]] = {}
        for u, i in zip(df[u_col].astype(np.int64), df[i_col].astype(np.int64)):
            actual.setdefault(int(u), []).append(int(i))
        min_r = self.get(self.min_ratings_per_user)
        rows_u, rows_p, rows_l = [], [], []
        for u, items in sorted(actual.items()):
            if len(items) < min_r:
                continue
            rows_u.append(u)
            rows_p.append(rec_by_user.get(u, []))
            rows_l.append(items)
        pred = np.empty(len(rows_p), object)
        lab = np.empty(len(rows_l), object)
        for i, (p, l) in enumerate(zip(rows_p, rows_l)):
            pred[i], lab[i] = p, l
        return DataFrame(
            {
                "user": Column(np.asarray(rows_u, np.int64), DataType.LONG),
                "prediction": Column(pred, DataType.ARRAY),
                "label": Column(lab, DataType.ARRAY),
            }
        )


class RankingTrainValidationSplit(Estimator, Wrappable):
    """Per-user stratified train/validation split + param search
    (reference RankingTrainValidationSplit.scala)."""

    estimator = ComplexParam("estimator", "Recommender estimator (SAR)")
    evaluator = ComplexParam("evaluator", "RankingEvaluator")
    param_maps = ComplexParam("param_maps", "List of {param_name: value} dicts")
    train_ratio = Param("train_ratio", "Per-user train fraction", TypeConverters.to_float)
    seed = Param("seed", "Split RNG seed", TypeConverters.to_int)
    user_col = Param("user_col", "User column", TypeConverters.to_string)
    item_col = Param("item_col", "Item column", TypeConverters.to_string)

    def __init__(self, estimator=None, evaluator: Optional[RankingEvaluator] = None,
                 param_maps: Optional[List[Dict[str, Any]]] = None,
                 train_ratio: float = 0.75, seed: int = 0,
                 user_col: str = "user_idx", item_col: str = "item_idx"):
        super().__init__()
        self._set_defaults(
            train_ratio=0.75, seed=0, user_col="user_idx", item_col="item_idx"
        )
        if estimator is not None:
            self.set(self.estimator, estimator)
        self.set(self.evaluator, evaluator or RankingEvaluator())
        self.set(self.param_maps, param_maps or [{}])
        self.set(self.train_ratio, train_ratio)
        self.set(self.seed, seed)
        self.set(self.user_col, user_col)
        self.set(self.item_col, item_col)

    def _split(self, df: DataFrame) -> Tuple[DataFrame, DataFrame]:
        rng = np.random.default_rng(self.get(self.seed))
        users = df[self.get(self.user_col)].astype(np.int64)
        ratio = self.get(self.train_ratio)
        train_mask = np.zeros(len(df), bool)
        for u in np.unique(users):
            idx = np.nonzero(users == u)[0]
            idx = idx[rng.permutation(len(idx))]
            n_train = max(1, int(round(len(idx) * ratio)))
            train_mask[idx[:n_train]] = True
        return df.filter(train_mask), df.filter(~train_mask)

    def fit(self, df: DataFrame) -> "Model":
        train, valid = self._split(df)
        evaluator: RankingEvaluator = self.get(self.evaluator)
        best_model, best_value = None, None
        for pmap in self.get(self.param_maps):
            est = self.get(self.estimator).copy()
            for name, value in pmap.items():
                est.set(name, value)
            adapter = RankingAdapter(est, k=evaluator.get(evaluator.k))
            fitted = adapter.fit(train)
            ranked = fitted.transform(valid)
            value = evaluator.evaluate(ranked)
            if best_value is None or value > best_value:
                best_model, best_value = fitted, value
        best_model._validation_metric = best_value
        return best_model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema
