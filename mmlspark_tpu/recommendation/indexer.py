"""RecommendationIndexer: map raw user/item ids to dense indices.

Reference: recommendation/RecommendationIndexer.scala — a two-column
ValueIndexer whose model also exposes the inverse mapping for presenting
recommendations in original id space.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, DataType, Field
from mmlspark_tpu.core.params import ComplexParam, Param, TypeConverters, Wrappable
from mmlspark_tpu.core.pipeline import Estimator, Model


class _RecColParams:
    user_input_col = Param("user_input_col", "Raw user id column", TypeConverters.to_string)
    user_output_col = Param("user_output_col", "Indexed user column", TypeConverters.to_string)
    item_input_col = Param("item_input_col", "Raw item id column", TypeConverters.to_string)
    item_output_col = Param("item_output_col", "Indexed item column", TypeConverters.to_string)


class RecommendationIndexer(Estimator, _RecColParams, Wrappable):
    """String user/item ids -> contiguous double indices (RecommendationIndexer.scala)."""

    def __init__(self, user_input_col: str = "user", user_output_col: str = "user_idx",
                 item_input_col: str = "item", item_output_col: str = "item_idx"):
        super().__init__()
        self.set(self.user_input_col, user_input_col)
        self.set(self.user_output_col, user_output_col)
        self.set(self.item_input_col, item_input_col)
        self.set(self.item_output_col, item_output_col)

    def fit(self, df: DataFrame) -> "RecommendationIndexerModel":
        users = sorted(set(df._hashable_col(self.get(self.user_input_col))))
        items = sorted(set(df._hashable_col(self.get(self.item_input_col))))
        model = RecommendationIndexerModel(users, items)
        for p in ("user_input_col", "user_output_col", "item_input_col", "item_output_col"):
            model.set(p, self.get(p))
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [
            Field(self.get(self.user_output_col), DataType.DOUBLE),
            Field(self.get(self.item_output_col), DataType.DOUBLE),
        ]


class RecommendationIndexerModel(Model, _RecColParams, Wrappable):
    """Fitted indexer: transform ids to indices and recover them back."""

    user_levels = ComplexParam("user_levels", "Ordered user ids")
    item_levels = ComplexParam("item_levels", "Ordered item ids")

    def __init__(self, user_levels: Optional[List[Any]] = None,
                 item_levels: Optional[List[Any]] = None):
        super().__init__()
        if user_levels is not None:
            self.set(self.user_levels, list(user_levels))
        if item_levels is not None:
            self.set(self.item_levels, list(item_levels))

    def transform(self, df: DataFrame) -> DataFrame:
        u_index = {v: float(i) for i, v in enumerate(self.get(self.user_levels))}
        i_index = {v: float(i) for i, v in enumerate(self.get(self.item_levels))}
        u = [u_index[v] for v in df._hashable_col(self.get(self.user_input_col))]
        it = [i_index[v] for v in df._hashable_col(self.get(self.item_input_col))]
        out = df.with_column(
            self.get(self.user_output_col), np.asarray(u, np.float64), DataType.DOUBLE
        )
        return out.with_column(
            self.get(self.item_output_col), np.asarray(it, np.float64), DataType.DOUBLE
        )

    def recover_user(self, idx: int) -> Any:
        return self.get(self.user_levels)[int(idx)]

    def recover_item(self, idx: int) -> Any:
        return self.get(self.item_levels)[int(idx)]
