"""FindBestModel — model selection by evaluation metric.

Reference: find-best-model/src/main/scala/FindBestModel.scala:51 +
EvaluationUtils.scala:13. Fit evaluates every candidate trained model on
the given dataset and returns a BestModel carrying the winner, its scored
dataset, its ROC curve, and the all-model metrics DataFrame.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from mmlspark_tpu.core import metrics as M
from mmlspark_tpu.core.dataframe import DataFrame, Field
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasEvaluationMetric,
    Param,
    TypeConverters,
    Wrappable,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.automl.statistics import (
    ComputeModelStatistics,
    roc_curve,
)


def evaluate_scored(df: DataFrame, label_col: str, metric: str) -> float:
    # raw score frames (TPUModel: a (n, classes) scores column, no label
    # column) evaluate through their argmax; the wrapped-trainer frames
    # already carry scored_labels/prediction and are left alone
    if (
        M.SCORED_LABELS_COL not in df
        and M.PREDICTION_COL not in df
        and M.SCORES_COL in df
    ):
        sv = np.asarray(df[M.SCORES_COL])
        if sv.ndim == 2 and sv.shape[1] >= 2:
            df = df.with_column(
                M.SCORED_LABELS_COL, sv.argmax(axis=1).astype(np.int64)
            )
    stats = ComputeModelStatistics(
        evaluation_metric="all", label_col=label_col
    ).transform(df)
    row = stats.collect()[0]
    if metric not in row:
        raise ValueError(
            f"metric {metric!r} not produced; available: {list(row)}"
        )
    return float(row[metric])


class FindBestModel(Estimator, HasEvaluationMetric, Wrappable):
    """Evaluate candidate models on a validation metric and keep the best (FindBestModel.scala:43-95)."""

    models = ComplexParam("models", "Candidate trained models")

    def __init__(self, models: Optional[List[Transformer]] = None,
                 evaluation_metric: str = M.ACCURACY):
        super().__init__()
        self._set_defaults(evaluation_metric=M.ACCURACY)
        if models is not None:
            self.set(self.models, list(models))
        self.set(self.evaluation_metric, evaluation_metric)

    def fit(self, df: DataFrame) -> "BestModel":
        metric = self.get(self.evaluation_metric)
        larger_better = M.LARGER_IS_BETTER.get(metric, True)
        rows = []
        best = None
        best_value = None
        best_scored = None
        for candidate in self.get(self.models):
            label_col = candidate.get_or_default("label_col", "label")
            scored = candidate.transform(df)
            value = evaluate_scored(scored, label_col, metric)
            rows.append({"model": type(candidate).__name__ + "_" + candidate.uid,
                         metric: value})
            better = (
                best_value is None
                or (value > best_value if larger_better else value < best_value)
            )
            if better:
                best, best_value, best_scored = candidate, value, scored
        if best is None:
            raise ValueError("no models to evaluate")
        roc = None
        if M.SCORED_PROBABILITIES_COL in best_scored:
            probs = best_scored[M.SCORED_PROBABILITIES_COL]
            scores = probs[:, -1] if probs.ndim == 2 else probs
            labels = best_scored[best.get_or_default("label_col", "label")]
            try:
                roc = roc_curve(np.asarray([float(v) for v in labels]), scores)
            except (TypeError, ValueError):
                roc = None
        model = BestModel(
            best, best_scored, DataFrame.from_rows(rows), roc, best_value
        )
        model.set(model.evaluation_metric, metric)
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        models = self.get(self.models)
        return models[0].transform_schema(schema) if models else schema


class BestModel(Model, HasEvaluationMetric, Wrappable):
    """The winning model plus all-candidate metrics and ROC data (FindBestModel.scala bestModel output)."""

    best_model = ComplexParam("best_model", "The winning model")
    scored_dataset = ComplexParam("scored_dataset", "Winner's scored eval dataset")
    all_model_metrics = ComplexParam("all_model_metrics", "Per-candidate metrics")
    roc_curve_df = ComplexParam("roc_curve_df", "Winner's ROC curve")
    best_metric_value = Param("best_metric_value", "Winning metric value", TypeConverters.to_float)

    def __init__(self, best_model=None, scored_dataset=None,
                 all_model_metrics=None, roc=None, best_value: float = 0.0):
        super().__init__()
        if best_model is not None:
            self.set(self.best_model, best_model)
        if scored_dataset is not None:
            self.set(self.scored_dataset, scored_dataset)
        if all_model_metrics is not None:
            self.set(self.all_model_metrics, all_model_metrics)
        if roc is not None:
            self.set(self.roc_curve_df, roc)
        self.set(self.best_metric_value, float(best_value))

    def get_best_model(self):
        return self.get(self.best_model)

    def get_all_model_metrics(self) -> DataFrame:
        return self.get(self.all_model_metrics)

    def get_roc_curve(self) -> Optional[DataFrame]:
        return self.get_or_default(self.roc_curve_df)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get(self.best_model).transform(df)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return self.get(self.best_model).transform_schema(schema)
