"""TrainClassifier / TrainRegressor — auto-featurizing model wrappers.

Reference: train/src/main/scala/TrainClassifier.scala:91-140 (label
auto-indexing via ValueIndexer, featurization via Featurize, model fit,
TrainedClassifierModel that scores and un-indexes labels), AutoTrainer /
AutoTrainedModel bases, TrainRegressor. Output column names keep the
reference contract: scored_labels / scores / scored_probabilities
(core/metrics.py constants).
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core import metrics as M
from mmlspark_tpu.core.dataframe import DataFrame, DataType, Field
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasFeaturesCol,
    HasLabelCol,
    Param,
    TypeConverters,
    Wrappable,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.core.schema import find_unused_column_name
from mmlspark_tpu.featurize import Featurize
from mmlspark_tpu.stages.dataprep import ValueIndexer, ValueIndexerModel


class _AutoTrainer(HasLabelCol, HasFeaturesCol):
    model = ComplexParam("model", "Inner estimator to auto-train")
    number_of_features = Param(
        "number_of_features", "Hash width for string features", TypeConverters.to_int
    )

    def _feature_inputs(self, df: DataFrame) -> List[str]:
        label = self.get(self.label_col)
        return [c for c in df.columns if c != label]

    def _featurize(self, df: DataFrame, label_col: str):
        feat_col = find_unused_column_name("features", df)
        featurizer = Featurize(
            feature_columns=[c for c in df.columns if c != label_col],
            output_col=feat_col,
            number_of_features=self.get(self.number_of_features),
        )
        return featurizer.fit(df), feat_col


class TrainClassifier(Estimator, _AutoTrainer, Wrappable):
    """Featurize + reindex labels + fit an inner classifier in one estimator (TrainClassifier.scala:53-207)."""

    reindex_label = Param("reindex_label", "Re-index labels to 0..K-1", TypeConverters.to_boolean)

    def __init__(self, model: Optional[Estimator] = None, label_col: str = "label",
                 number_of_features: int = 4096, reindex_label: bool = True):
        super().__init__()
        self._set_defaults(
            label_col="label", features_col="features", number_of_features=4096,
            reindex_label=True,
        )
        if model is not None:
            self.set(self.model, model)
        self.set(self.label_col, label_col)
        self.set(self.number_of_features, number_of_features)
        self.set(self.reindex_label, reindex_label)

    def set_model(self, model: Estimator):
        return self.set(self.model, model)

    def fit(self, df: DataFrame) -> "TrainedClassifierModel":
        label = self.get(self.label_col)
        levels = None
        work = df
        indexed_label = label
        if self.get(self.reindex_label):
            indexed_label = find_unused_column_name("indexed_label", df)
            indexer: ValueIndexerModel = ValueIndexer(label, indexed_label).fit(df)
            levels = indexer.get_levels()
            work = indexer.transform(df)
            work = work.drop(label)
        feat_model, feat_col = self._featurize(work, indexed_label)
        featurized = feat_model.transform(work)
        inner = self.get(self.model).copy()
        inner.set("label_col", indexed_label)
        inner.set("features_col", feat_col)
        fitted = inner.fit(featurized)
        model = TrainedClassifierModel(feat_model, fitted, levels, feat_col)
        model.set(model.label_col, label)
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [
            Field(M.SCORES_COL, DataType.VECTOR),
            Field(M.SCORED_PROBABILITIES_COL, DataType.VECTOR),
            Field(M.SCORED_LABELS_COL, DataType.DOUBLE),
        ]


class TrainedClassifierModel(Model, HasLabelCol, Wrappable):
    """Fitted TrainClassifier: featurize, score, and un-index predicted labels."""

    featurize_model = ComplexParam("featurize_model", "Fitted featurizer")
    inner_model = ComplexParam("inner_model", "Fitted inner model")
    levels = ComplexParam("levels", "Original label levels (index order)")
    features_col_name = Param("features_col_name", "Assembled features column", TypeConverters.to_string)

    def __init__(self, featurize_model=None, inner_model=None,
                 levels: Optional[List[Any]] = None, features_col: str = "features"):
        super().__init__()
        self._set_defaults(label_col="label", features_col_name="features")
        if featurize_model is not None:
            self.set(self.featurize_model, featurize_model)
        if inner_model is not None:
            self.set(self.inner_model, inner_model)
        if levels is not None:
            self.set(self.levels, list(levels))
        self.set(self.features_col_name, features_col)

    def transform(self, df: DataFrame) -> DataFrame:
        featurized = self.get(self.featurize_model).transform(df)
        inner = self.get(self.inner_model)
        scored = inner.transform(featurized)
        # normalize inner column names to the scored_* contract
        out = df
        raw_col = inner.get_or_default("raw_prediction_col", "rawPrediction")
        prob_col = inner.get_or_default("probability_col", "probability")
        pred_col = inner.get_or_default("prediction_col", "prediction")
        if raw_col in scored:
            out = out.with_column(M.SCORES_COL, scored[raw_col], DataType.VECTOR)
        if prob_col in scored:
            out = out.with_column(
                M.SCORED_PROBABILITIES_COL, scored[prob_col], DataType.VECTOR
            )
        preds = scored[pred_col]
        if self.is_set(self.levels):
            levels = self.get(self.levels)
            values = [levels[int(p)] for p in preds]
            out = out.with_column(M.SCORED_LABELS_COL, values)
        else:
            out = out.with_column(M.SCORED_LABELS_COL, preds, DataType.DOUBLE)
        return out

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [
            Field(M.SCORES_COL, DataType.VECTOR),
            Field(M.SCORED_PROBABILITIES_COL, DataType.VECTOR),
            Field(M.SCORED_LABELS_COL, DataType.DOUBLE),
        ]


class TrainRegressor(Estimator, _AutoTrainer, Wrappable):
    """Featurize + fit an inner regressor in one estimator (TrainRegressor.scala)."""

    def __init__(self, model: Optional[Estimator] = None, label_col: str = "label",
                 number_of_features: int = 4096):
        super().__init__()
        self._set_defaults(
            label_col="label", features_col="features", number_of_features=4096
        )
        if model is not None:
            self.set(self.model, model)
        self.set(self.label_col, label_col)
        self.set(self.number_of_features, number_of_features)

    def set_model(self, model: Estimator):
        return self.set(self.model, model)

    def fit(self, df: DataFrame) -> "TrainedRegressorModel":
        label = self.get(self.label_col)
        feat_model, feat_col = self._featurize(df, label)
        featurized = feat_model.transform(df)
        inner = self.get(self.model).copy()
        inner.set("label_col", label)
        inner.set("features_col", feat_col)
        fitted = inner.fit(featurized)
        model = TrainedRegressorModel(feat_model, fitted, feat_col)
        model.set(model.label_col, label)
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(M.SCORES_COL, DataType.DOUBLE)]


class TrainedRegressorModel(Model, HasLabelCol, Wrappable):
    """Fitted TrainRegressor: featurize and score."""

    featurize_model = ComplexParam("featurize_model", "Fitted featurizer")
    inner_model = ComplexParam("inner_model", "Fitted inner model")
    features_col_name = Param("features_col_name", "Assembled features column", TypeConverters.to_string)

    def __init__(self, featurize_model=None, inner_model=None,
                 features_col: str = "features"):
        super().__init__()
        self._set_defaults(label_col="label", features_col_name="features")
        if featurize_model is not None:
            self.set(self.featurize_model, featurize_model)
        if inner_model is not None:
            self.set(self.inner_model, inner_model)
        self.set(self.features_col_name, features_col)

    def transform(self, df: DataFrame) -> DataFrame:
        featurized = self.get(self.featurize_model).transform(df)
        inner = self.get(self.inner_model)
        scored = inner.transform(featurized)
        pred_col = inner.get_or_default("prediction_col", "prediction")
        return df.with_column(
            M.SCORES_COL, scored[pred_col].astype(np.float64), DataType.DOUBLE
        )

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(M.SCORES_COL, DataType.DOUBLE)]
