"""Hyperparameter spaces: grid and random distributions.

Reference: tune-hyperparameters ParamSpace.scala:25-34 (GridSpace /
RandomSpace), HyperparamBuilder.scala:98, DefaultHyperparams.scala:17-95.
A param point is {(estimator_uid, param_name): value}.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterator, List, Sequence, Tuple

import numpy as np


class HyperParam:
    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError

    def grid_values(self) -> List[Any]:
        raise NotImplementedError


class DiscreteHyperParam(HyperParam):
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]

    def grid_values(self):
        return list(self.values)


class IntRangeHyperParam(HyperParam):
    def __init__(self, low: int, high: int):  # [low, high)
        self.low, self.high = int(low), int(high)

    def sample(self, rng):
        return int(rng.integers(self.low, self.high))

    def grid_values(self):
        return list(range(self.low, self.high))


class DoubleRangeHyperParam(HyperParam):
    def __init__(self, low: float, high: float):
        self.low, self.high = float(low), float(high)

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))

    def grid_values(self):
        return list(np.linspace(self.low, self.high, 5))


class HyperparamBuilder:
    """Collects (estimator, param-name) -> HyperParam entries."""

    def __init__(self):
        self._entries: List[Tuple[Any, str, HyperParam]] = []

    def add_hyperparam(self, estimator, param_name: str, dist: HyperParam) -> "HyperparamBuilder":
        estimator.get_param(param_name)  # validate it exists
        self._entries.append((estimator, param_name, dist))
        return self

    def build(self) -> List[Tuple[Any, str, HyperParam]]:
        return list(self._entries)


class GridSpace:
    """Exhaustive cartesian product of grid values (ParamSpace.scala:25)."""

    def __init__(self, entries: List[Tuple[Any, str, HyperParam]]):
        self.entries = entries

    def param_sets(self) -> Iterator[List[Tuple[Any, str, Any]]]:
        grids = [e[2].grid_values() for e in self.entries]
        for combo in itertools.product(*grids):
            yield [
                (est, name, value)
                for (est, name, _), value in zip(self.entries, combo)
            ]


class RandomSpace:
    """Random sampling from each distribution (ParamSpace.scala:34)."""

    def __init__(self, entries: List[Tuple[Any, str, HyperParam]], seed: int = 0):
        self.entries = entries
        self.seed = seed

    def param_sets(self) -> Iterator[List[Tuple[Any, str, Any]]]:
        rng = np.random.default_rng(self.seed)
        while True:
            yield [(est, name, dist.sample(rng)) for est, name, dist in self.entries]


class DefaultHyperparams:
    """Per-learner default search spaces (DefaultHyperparams.scala:17-95)."""

    @staticmethod
    def for_estimator(estimator) -> List[Tuple[Any, str, HyperParam]]:
        name = type(estimator).__name__
        builder = HyperparamBuilder()
        if name == "LightGBMClassifier" or name == "LightGBMRegressor":
            builder.add_hyperparam(estimator, "num_leaves", DiscreteHyperParam([15, 31, 63]))
            builder.add_hyperparam(estimator, "learning_rate", DoubleRangeHyperParam(0.01, 0.3))
            builder.add_hyperparam(estimator, "num_iterations", DiscreteHyperParam([25, 50, 100]))
        elif name == "LogisticRegression":
            builder.add_hyperparam(estimator, "reg_param", DoubleRangeHyperParam(0.0, 0.3))
            builder.add_hyperparam(estimator, "max_iter", DiscreteHyperParam([20, 50]))
        elif name == "TPULearner":
            builder.add_hyperparam(estimator, "learning_rate", DoubleRangeHyperParam(0.001, 0.3))
            builder.add_hyperparam(estimator, "epochs", DiscreteHyperParam([10, 25, 50]))
        elif name in ("RandomForestClassifier", "RandomForestRegressor"):
            # DefaultHyperparams.scala:55-63 (RandomForestClassifier ranges)
            builder.add_hyperparam(estimator, "max_bins", IntRangeHyperParam(16, 32))
            builder.add_hyperparam(estimator, "max_depth", IntRangeHyperParam(2, 5))
            builder.add_hyperparam(estimator, "min_info_gain", DoubleRangeHyperParam(0.0, 0.5))
            builder.add_hyperparam(estimator, "min_instances_per_node", IntRangeHyperParam(1, 8))
            builder.add_hyperparam(estimator, "num_trees", IntRangeHyperParam(10, 30))
            builder.add_hyperparam(estimator, "subsampling_rate", DoubleRangeHyperParam(0.1, 1.0))
        elif name in ("DecisionTreeClassifier", "DecisionTreeRegressor"):
            # DefaultHyperparams.scala:28-35 (DecisionTreeClassifier ranges)
            builder.add_hyperparam(estimator, "max_bins", IntRangeHyperParam(16, 32))
            builder.add_hyperparam(estimator, "max_depth", IntRangeHyperParam(2, 5))
            builder.add_hyperparam(estimator, "min_info_gain", DoubleRangeHyperParam(0.0, 0.5))
            builder.add_hyperparam(estimator, "min_instances_per_node", IntRangeHyperParam(1, 8))
        elif name == "NaiveBayes":
            # DefaultHyperparams.scala:88-92 (NaiveBayes smoothing range)
            builder.add_hyperparam(estimator, "smoothing", DoubleRangeHyperParam(0.0, 1.0))
        else:
            raise ValueError(f"no default hyperparams for {name}")
        return builder.build()
