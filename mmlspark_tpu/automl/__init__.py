"""automl — auto-training, evaluation, model selection, tuning.

Equivalent of the reference modules (SURVEY.md §2.3): train
(TrainClassifier.scala:91-140, TrainRegressor), compute-model-statistics
(ComputeModelStatistics.scala:69-466), compute-per-instance-statistics
(ComputePerInstanceStatistics.scala:42), find-best-model
(FindBestModel.scala:51), tune-hyperparameters
(TuneHyperparameters.scala:81-112, ParamSpace.scala, HyperparamBuilder,
DefaultHyperparams).
"""

from mmlspark_tpu.automl.train import (
    TrainClassifier,
    TrainRegressor,
    TrainedClassifierModel,
    TrainedRegressorModel,
)
from mmlspark_tpu.automl.statistics import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
)
from mmlspark_tpu.automl.find_best import BestModel, FindBestModel
from mmlspark_tpu.automl.hyperparam import (
    DiscreteHyperParam,
    DoubleRangeHyperParam,
    GridSpace,
    HyperparamBuilder,
    IntRangeHyperParam,
    RandomSpace,
)
from mmlspark_tpu.automl.tune import TuneHyperparameters, TuneHyperparametersModel

__all__ = [
    "BestModel",
    "ComputeModelStatistics",
    "ComputePerInstanceStatistics",
    "DiscreteHyperParam",
    "DoubleRangeHyperParam",
    "FindBestModel",
    "GridSpace",
    "HyperparamBuilder",
    "IntRangeHyperParam",
    "RandomSpace",
    "TrainClassifier",
    "TrainRegressor",
    "TrainedClassifierModel",
    "TrainedRegressorModel",
    "TuneHyperparameters",
    "TuneHyperparametersModel",
]
