"""Evaluation metrics: ComputeModelStatistics / ComputePerInstanceStatistics.

Reference: ComputeModelStatistics.scala:69-466 (confusion matrix, accuracy /
precision / recall, AUC via rank statistic, regression MSE/RMSE/R2/MAE,
per-class metrics, MetricsLogger) and ComputePerInstanceStatistics.scala:42
(per-row L1/L2 loss, per-instance log loss). Consumes the metric-name
constants from core/metrics.py.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core import metrics as M
from mmlspark_tpu.obs.logging import get_logger
from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, Field
from mmlspark_tpu.core.params import (
    HasEvaluationMetric,
    HasLabelCol,
    Param,
    TypeConverters,
    Wrappable,
)
from mmlspark_tpu.core.pipeline import Transformer


def auc_score(labels: np.ndarray, scores: np.ndarray) -> float:
    """Binary AUROC by the Mann-Whitney rank statistic (getAUC, :376)."""
    labels = np.asarray(labels) > 0
    scores = np.asarray(scores, np.float64)
    n_pos = int(labels.sum())
    n_neg = len(labels) - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    # average ranks for ties (Mann-Whitney requires midranks)
    uniq, inv, counts = np.unique(scores, return_inverse=True, return_counts=True)
    cum = np.cumsum(counts)
    avg_rank = (cum - (counts - 1) / 2.0)[inv]
    return float(
        (avg_rank[labels].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
    )


def roc_curve(labels: np.ndarray, scores: np.ndarray) -> DataFrame:
    """ROC points (false_positive_rate, true_positive_rate, threshold)."""
    labels = np.asarray(labels) > 0
    order = np.argsort(-np.asarray(scores, np.float64), kind="stable")
    sorted_labels = labels[order]
    tps = np.cumsum(sorted_labels)
    fps = np.cumsum(~sorted_labels)
    n_pos = max(1, int(labels.sum()))
    n_neg = max(1, int((~labels).sum()))
    return DataFrame.from_dict(
        {
            "false_positive_rate": np.concatenate([[0.0], fps / n_neg]),
            "true_positive_rate": np.concatenate([[0.0], tps / n_pos]),
            "threshold": np.concatenate(
                [[np.inf], np.asarray(scores, np.float64)[order]]
            ),
        }
    )


def confusion_matrix(labels: np.ndarray, predictions: np.ndarray,
                     num_classes: Optional[int] = None) -> np.ndarray:
    labels = np.asarray(labels, np.int64)
    predictions = np.asarray(predictions, np.int64)
    k = num_classes or int(max(labels.max(), predictions.max())) + 1
    out = np.zeros((k, k), np.int64)
    np.add.at(out, (labels, predictions), 1)
    return out


def classification_metrics(labels, predictions, scores=None) -> Dict[str, Any]:
    cm = confusion_matrix(labels, predictions)
    k = cm.shape[0]
    total = cm.sum()
    acc = float(np.trace(cm)) / max(1, total)
    per_class_prec, per_class_rec = [], []
    for c in range(k):
        tp = cm[c, c]
        fp = cm[:, c].sum() - tp
        fn = cm[c, :].sum() - tp
        per_class_prec.append(tp / max(1, tp + fp))
        per_class_rec.append(tp / max(1, tp + fn))
    if k == 2:
        precision, recall = float(per_class_prec[1]), float(per_class_rec[1])
    else:  # macro average
        precision, recall = float(np.mean(per_class_prec)), float(np.mean(per_class_rec))
    out = {
        M.ACCURACY: acc,
        M.PRECISION: precision,
        M.RECALL: recall,
        "confusion_matrix": cm,
        "per_class_precision": per_class_prec,
        "per_class_recall": per_class_rec,
    }
    if scores is not None and k == 2:
        out[M.AUC] = auc_score(labels, scores)
    return out


def regression_metrics(labels, predictions) -> Dict[str, float]:
    y = np.asarray(labels, np.float64)
    p = np.asarray(predictions, np.float64)
    err = p - y
    mse = float(np.mean(err ** 2))
    ss_tot = float(np.sum((y - y.mean()) ** 2))
    return {
        M.MSE: mse,
        M.RMSE: float(np.sqrt(mse)),
        M.R2: 1.0 - float(np.sum(err ** 2)) / ss_tot if ss_tot else float("nan"),
        M.MAE: float(np.mean(np.abs(err))),
    }


class ComputeModelStatistics(Transformer, HasLabelCol, HasEvaluationMetric, Wrappable):
    """Scored DataFrame -> one-row metrics DataFrame."""

    scores_col = Param("scores_col", "Probability / score column", TypeConverters.to_string)
    scored_labels_col = Param("scored_labels_col", "Predicted label column", TypeConverters.to_string)

    def __init__(self, evaluation_metric: str = "all", label_col: str = "label",
                 scored_labels_col: str = "scored_labels",
                 scores_col: Optional[str] = None):
        super().__init__()
        self._set_defaults(
            label_col="label", evaluation_metric="all",
            scored_labels_col="scored_labels",
        )
        self.set(self.evaluation_metric, evaluation_metric)
        self.set(self.label_col, label_col)
        self.set(self.scored_labels_col, scored_labels_col)
        if scores_col:
            self.set(self.scores_col, scores_col)

    def _is_regression(self, df: DataFrame, labels: np.ndarray) -> bool:
        metric = self.get(self.evaluation_metric)
        if metric in M.REGRESSION_METRICS or metric == "regression":
            return True
        if metric in M.CLASSIFICATION_METRICS or metric == "classification":
            return False
        return not np.allclose(labels, np.rint(labels))

    @staticmethod
    def _numeric_pair(raw_labels, raw_preds):
        """Cast label/prediction columns to float, indexing string levels
        (TrainClassifier keeps original label values in scored_labels)."""
        try:
            return (
                np.asarray([float(v) for v in raw_labels], np.float64),
                np.asarray([float(v) for v in raw_preds], np.float64),
                False,
            )
        except (TypeError, ValueError):
            levels = sorted(
                set(str(v) for v in raw_labels) | set(str(v) for v in raw_preds)
            )
            index = {v: float(i) for i, v in enumerate(levels)}
            return (
                np.asarray([index[str(v)] for v in raw_labels], np.float64),
                np.asarray([index[str(v)] for v in raw_preds], np.float64),
                True,
            )

    def transform(self, df: DataFrame) -> DataFrame:
        pred_col = self.get(self.scored_labels_col)
        if pred_col not in df and M.PREDICTION_COL in df:
            pred_col = M.PREDICTION_COL
        labels, preds, was_string = self._numeric_pair(
            df[self.get(self.label_col)], df[pred_col]
        )
        metric = self.get(self.evaluation_metric)
        log = get_logger("mmlspark_tpu.metrics")
        if not was_string and self._is_regression(df, labels):
            stats = regression_metrics(labels, preds)
            row = {"evaluation_type": "Regression", **stats}
        else:
            scores = None
            scol = self.get_or_default(self.scores_col)
            if scol is None:
                for cand in (M.SCORED_PROBABILITIES_COL, "probability", M.SCORES_COL):
                    if cand in df:
                        scol = cand
                        break
            if scol is not None and scol in df:
                sv = df[scol]
                scores = sv[:, -1] if sv.ndim == 2 else sv
            stats = classification_metrics(
                labels.astype(np.int64), preds.astype(np.int64), scores
            )
            cm = stats.pop("confusion_matrix")
            stats.pop("per_class_precision")
            stats.pop("per_class_recall")
            row = {
                "evaluation_type": "Classification",
                "confusion_matrix": cm.astype(np.float64),
                **stats,
            }
        if metric not in ("all", "classification", "regression"):
            row = {
                "evaluation_type": row["evaluation_type"],
                metric: row.get(metric, float("nan")),
            }
        for key, value in row.items():
            if isinstance(value, float):
                log.info("metric", name=key, value=round(value, 6))
        types = {"confusion_matrix": DataType.VECTOR} if "confusion_matrix" in row else None
        return DataFrame.from_dict(
            {k: [v] for k, v in row.items()}, types=types or {}
        )


class ComputePerInstanceStatistics(Transformer, HasLabelCol, HasEvaluationMetric, Wrappable):
    """Per-row loss columns (ComputePerInstanceStatistics.scala:42):
    regression -> L1_loss/L2_loss; classification -> log_loss."""

    scores_col = Param("scores_col", "Probability column", TypeConverters.to_string)
    scored_labels_col = Param("scored_labels_col", "Predicted label column", TypeConverters.to_string)

    def __init__(self, evaluation_metric: str = "auto", label_col: str = "label",
                 scored_labels_col: str = "scored_labels",
                 scores_col: Optional[str] = None):
        super().__init__()
        self._set_defaults(
            label_col="label", evaluation_metric="auto",
            scored_labels_col="scored_labels",
        )
        self.set(self.label_col, label_col)
        self.set(self.evaluation_metric, evaluation_metric)
        self.set(self.scored_labels_col, scored_labels_col)
        if scores_col:
            self.set(self.scores_col, scores_col)

    def transform(self, df: DataFrame) -> DataFrame:
        labels = df[self.get(self.label_col)].astype(np.float64)
        metric = self.get(self.evaluation_metric)
        scol = self.get_or_default(self.scores_col)
        if scol is None:
            for cand in (M.SCORED_PROBABILITIES_COL, "probability"):
                if cand in df:
                    scol = cand
                    break
        is_classification = metric == "classification" or (
            metric == "auto" and scol is not None and scol in df
        )
        if is_classification:
            prob = df[scol]
            idx = np.clip(labels.astype(np.int64), 0, prob.shape[1] - 1)
            p_true = np.clip(prob[np.arange(len(labels)), idx], 1e-15, 1.0)
            return df.with_column("log_loss", -np.log(p_true), DataType.DOUBLE)
        pred_col = self.get(self.scored_labels_col)
        if pred_col not in df:
            for cand in (M.SCORES_COL, M.PREDICTION_COL):
                if cand in df:
                    pred_col = cand
                    break
        preds = df[pred_col].astype(np.float64)
        err = preds - labels
        out = df.with_column("L1_loss", np.abs(err), DataType.DOUBLE)
        return out.with_column("L2_loss", err ** 2, DataType.DOUBLE)


class MetricsLogger:
    """Push scalar metrics into the framework logger under a run name
    (reference: ComputeModelStatistics.scala:469-489 MetricsLogger — the
    hook build dashboards scrape). Usage:

        MetricsLogger("my-experiment").log_metrics_df(stats_df)
    """

    def __init__(self, run_name: str = "run"):
        from mmlspark_tpu.obs.logging import get_logger

        self.run_name = run_name
        self._log = get_logger("mmlspark_tpu.metrics")

    def log_metric(self, name: str, value: float) -> None:
        self._log.info("metric", name=f"{self.run_name}/{name}",
                       value=float(value))

    def log_metrics(self, metrics: dict) -> None:
        for name in sorted(metrics):
            v = metrics[name]
            if isinstance(v, (int, float, np.floating, np.integer)):
                self.log_metric(name, v)

    def log_metrics_df(self, df: DataFrame) -> None:
        """Log every scalar cell of a (typically one-row) metrics frame."""
        for name in df.columns:
            col = df[name]
            for i, v in enumerate(np.asarray(col).reshape(-1)[:8]):
                if isinstance(v, (int, float, np.floating, np.integer)):
                    suffix = f"[{i}]" if len(col) > 1 else ""
                    self.log_metric(f"{name}{suffix}", v)
