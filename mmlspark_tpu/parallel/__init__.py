"""Parallelism: device meshes, shardings, collectives.

TPU-native replacement for the reference's four distributed mechanisms
(SURVEY.md §2.7 / §5 "Distributed communication backend"):

- LightGBM driver-socket rendezvous + native TCP allreduce
  (LightGBMUtils.scala:97-137, TrainUtils.scala:217)
- mpirun/ssh GPU ring for CNTK training (CommandBuilders.scala:105-269)
- Spark broadcast/shuffle
- HTTP serving edge

All collapse into `jax.sharding.Mesh` + NamedSharding + XLA collectives
(psum/all_gather) over ICI, with `jax.distributed.initialize` for multi-host
DCN rendezvous (core/env.py).
"""

from mmlspark_tpu.parallel.mesh import (
    batch_sharding,
    data_parallel_mesh,
    make_mesh,
    pad_to_multiple,
    replicated_sharding,
    shard_batch,
    shard_target_rows,
)

__all__ = [
    "batch_sharding",
    "data_parallel_mesh",
    "make_mesh",
    "pad_to_multiple",
    "replicated_sharding",
    "shard_batch",
    "shard_target_rows",
]
