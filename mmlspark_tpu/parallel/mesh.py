"""Mesh construction and sharding helpers.

The reference scales by rows — Spark partitions, 1 executor : 1 device
(SURVEY.md §2.7 item 1). Here the same axis is a named mesh dimension
("data"); model/tensor axes are available for wider meshes. XLA inserts the
collectives; callers only annotate shardings (the scaling-book recipe).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.env import make_mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def data_parallel_mesh(n_devices: Optional[int] = None):
    """1-D mesh over all (or the first n) devices with axis name "data"."""
    import jax

    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return make_mesh((len(devices),), (DATA_AXIS,), devices)


def dp_tp_mesh(dp: int, tp: int, devices: Optional[Sequence] = None):
    """2-D (data, model) mesh for DP x TP workloads. The model axis should
    map to the fastest ICI links; JAX device order on TPU already reflects
    physical topology, so a simple reshape is correct for slices."""
    return make_mesh((dp, tp), (DATA_AXIS, MODEL_AXIS), devices)


def batch_sharding(mesh, ndim: int = 1, axis: int = 0):
    """NamedSharding placing array dim `axis` on the mesh "data" axis,
    replicating the rest. The canonical input sharding for DP compute."""
    from jax.sharding import NamedSharding, PartitionSpec

    spec = [None] * ndim
    spec[axis] = DATA_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def pad_to_multiple(arr: np.ndarray, multiple: int, axis: int = 0) -> Tuple[np.ndarray, int]:
    """Pad `axis` up to a multiple (repeating the last row so padded rows are
    valid inputs); returns (padded, original_length). Static shapes keep XLA
    from recompiling per batch and let the batch dim divide the mesh."""
    n = arr.shape[axis]
    if n == 0 or n % multiple == 0:
        return arr, n
    pad_n = multiple - n % multiple
    pad_block = np.take(arr, [-1] * pad_n, axis=axis)
    return np.concatenate([arr, pad_block], axis=axis), n


def shard_target_rows(n: int, n_data: int) -> int:
    """The padded row count shard_batch uploads at: the power-of-two
    dispatch bucket (core/dispatch.bucket_rows — the PR 3 compile-capping
    discipline) rounded up to a data-axis multiple (XLA's equal-slice
    requirement). Ragged serving traffic thus compiles ONE program per
    bucket instead of one per distinct batch size. The dispatch
    `bucketing(False)` rollback lever applies here too: disabled, the pad
    reverts to the minimal data-axis multiple."""
    if n <= 0:
        return n_data
    from mmlspark_tpu.core import dispatch

    target = (
        max(dispatch.bucket_rows(n), n_data)
        if dispatch.bucketing_enabled() else n
    )
    if target % n_data:
        target += n_data - target % n_data
    return target


def shard_batch(mesh, arr: np.ndarray):
    """Host array -> device array sharded along "data". Pads the batch up
    to the shape-bucketed data-axis multiple (shard_target_rows) through
    the SHARED dispatch pad helper — core/dispatch.pad_rows, whose device
    path is a compiled program — so every chip gets an equal slice (XLA
    requirement) and non-divisible row counts stop minting one compiled
    shape per distinct batch size downstream. Returns (sharded_array,
    original_length); callers trim with core/dispatch.trim_rows (also
    compiled). The upload is counted in profiling.dataplane_counters()."""
    import jax

    from mmlspark_tpu.core.dispatch import pad_rows
    from mmlspark_tpu.utils.profiling import dataplane_counters

    n_data = mesh.shape[DATA_AXIS]
    arr = np.asarray(arr)
    padded, n = pad_rows(arr, shard_target_rows(arr.shape[0], n_data))
    sharding = batch_sharding(mesh, ndim=padded.ndim)
    dataplane_counters().record_h2d(padded.nbytes)
    return jax.device_put(padded, sharding), n


def shard_column(mesh, col):
    """Device-stage a DataFrame Column along the mesh "data" axis without
    going through host when it is already device-backed; host columns
    upload once under the batch sharding. Returns the column's jax.Array.
    The canonical way for mesh-wide stages to consume the columnar
    dataplane (docs/dataplane.md)."""
    if col.is_device_backed:
        return col.device_values()
    return col.device_values(batch_sharding(mesh, ndim=col.ndim))


def shard_frame(mesh, df, columns: Optional[Sequence[str]] = None):
    """Upload `df`'s device-eligible columns sharded along the mesh "data"
    axis, returning a frame whose columns are device-backed. This is how the
    serving engine's parse stage feeds a multi-device handler: uploads are
    sharded at the pipeline entry (outside the score stage's critical
    section), so user handlers consume mesh-distributed batches without any
    code changes. Non-numeric (object-dtype) columns pass through host-side.

    Ragged serving batch sizes rarely divide the data axis, so host columns
    go through shard_batch (pad to the shape-BUCKETED data-axis multiple
    via the shared core/dispatch pad_rows helper — one compiled shape per
    bucket, XLA's divisibility requirement met) and are trimmed back on
    device — the trim is the compiled static-bound dispatch slice
    (core/dispatch.trim_rows), so no row count ever round-trips through
    host."""
    from mmlspark_tpu.core.dataframe import DataType
    from mmlspark_tpu.core.dispatch import trim_rows

    out = df
    for name in (columns if columns is not None else df.columns):
        col = df.column(name)
        if col.dtype is None or not (
            col.dtype == DataType.VECTOR or col.dtype.is_numeric
        ):
            continue
        if col.is_device_backed:
            out = out.with_column(name, col.device_values(), col.dtype)
            continue
        if col.values.dtype == object:
            continue  # ragged vectors stay host-side
        sharded, n = shard_batch(mesh, col.values)
        if int(sharded.shape[0]) != n:
            sharded = trim_rows(sharded, n)
        out = out.with_column(name, sharded, col.dtype)
    return out
