"""NaiveBayes — closed-form Bayes classifiers on device.

Reference parity: TrainClassifier / TuneHyperparameters wrap SparkML's
NaiveBayes with a smoothing search range
(tune-hyperparameters/src/main/scala/DefaultHyperparams.scala:88-92).

TPU-first: both fits are single-pass matmuls — class-conditional sums are
one `onehot(y).T @ x` contraction, so the whole fit is MXU work with no
per-class Python loops.
- multinomial: count features (hashed TF vectors from Featurize/
  TextFeaturizer); log P(x|c) ~ x . log theta_c with Laplace smoothing.
- gaussian: per-class feature mean/variance; diagonal-covariance
  log-likelihood.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, DataType, Field
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasFeaturesCol,
    HasLabelCol,
    Param,
    TypeConverters,
    Wrappable,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.models.tpu_model import extract_feature_matrix


class NaiveBayes(Estimator, HasFeaturesCol, HasLabelCol, Wrappable):
    """Multinomial (default) or Gaussian naive Bayes classifier."""

    smoothing = Param("smoothing", "Additive (Laplace) smoothing",
                      TypeConverters.to_float)
    model_type = Param("model_type", "multinomial | gaussian",
                       TypeConverters.to_string)
    prediction_col = Param("prediction_col", "Prediction column",
                           TypeConverters.to_string)
    probability_col = Param("probability_col", "Probability column",
                            TypeConverters.to_string)

    def __init__(self, **kwargs: Any):
        super().__init__()
        self._set_defaults(
            features_col="features", label_col="label",
            prediction_col="prediction", probability_col="probability",
            smoothing=1.0, model_type="multinomial",
        )
        self.set_params(**kwargs)

    def fit(self, df: DataFrame) -> "NaiveBayesModel":
        import jax.numpy as jnp

        kind = self.get(self.model_type)
        if kind not in ("multinomial", "gaussian"):
            raise ValueError(f"model_type {kind!r}: multinomial | gaussian")
        fcol = df.column(self.get(self.features_col))
        d = fcol.values.shape[1] if fcol.values.ndim == 2 else 1
        x = np.asarray(
            extract_feature_matrix(fcol, (d,), self.get(self.features_col)),
            np.float32,
        )
        y = np.asarray(
            [float(v) for v in df[self.get(self.label_col)]], np.float32
        )
        k = int(np.nanmax(y)) + 1 if len(y) else 2
        k = max(2, k)
        if kind == "multinomial" and (x < 0).any():
            raise ValueError(
                "multinomial NaiveBayes needs non-negative features "
                "(counts); use model_type='gaussian'"
            )

        onehot = jnp.asarray(
            np.eye(k, dtype=np.float32)[y.astype(np.int64)]
        )                                              # (n, k)
        xj = jnp.asarray(x)
        counts = onehot.sum(axis=0)                    # (k,)
        sums = onehot.T @ xj                           # (k, d) — one matmul
        alpha = self.get(self.smoothing)
        log_prior = np.log(
            (np.asarray(counts) + alpha)
            / (len(y) + alpha * k)
        )
        if kind == "multinomial":
            tot = np.asarray(sums).sum(axis=1, keepdims=True)
            # clamp: alpha=0 with a zero count gives log(0) = -inf, and the
            # dense scoring matmul turns 0 * -inf into NaN probabilities
            a = max(alpha, 1e-10)
            log_theta = np.log(
                (np.asarray(sums) + a) / (tot + a * x.shape[1])
            )
            model = NaiveBayesModel(
                kind="multinomial", log_prior=log_prior, log_theta=log_theta
            )
        else:
            sq_sums = np.asarray(onehot.T @ (xj * xj))  # (k, d)
            cnt = np.maximum(np.asarray(counts), 1.0)[:, None]
            mean = np.asarray(sums) / cnt
            var = np.maximum(sq_sums / cnt - mean ** 2, 1e-9) + alpha * 1e-9
            model = NaiveBayesModel(
                kind="gaussian", log_prior=log_prior, mean=mean, var=var
            )
        for p in ("features_col", "prediction_col", "probability_col"):
            model.set(p, self.get(p))
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [
            Field(self.get(self.probability_col), DataType.VECTOR),
            Field(self.get(self.prediction_col), DataType.DOUBLE),
        ]


class NaiveBayesModel(Model, HasFeaturesCol, Wrappable):
    """Fitted NaiveBayes: log-likelihood scoring + argmax prediction."""

    kind = Param("kind", "multinomial | gaussian", TypeConverters.to_string)
    log_prior = ComplexParam("log_prior", "(k,) class log priors")
    log_theta = ComplexParam("log_theta", "(k, d) multinomial log params")
    mean = ComplexParam("mean", "(k, d) gaussian means")
    var = ComplexParam("var", "(k, d) gaussian variances")
    prediction_col = Param("prediction_col", "Prediction column",
                           TypeConverters.to_string)
    probability_col = Param("probability_col", "Probability column",
                            TypeConverters.to_string)

    def __init__(self, kind: Optional[str] = None, log_prior=None,
                 log_theta=None, mean=None, var=None):
        super().__init__()
        self._set_defaults(
            features_col="features", prediction_col="prediction",
            probability_col="probability",
        )
        if kind is not None:
            self.set(self.kind, kind)
        for name, v in (("log_prior", log_prior), ("log_theta", log_theta),
                        ("mean", mean), ("var", var)):
            if v is not None:
                self.set(name, np.asarray(v, np.float64))

    def transform(self, df: DataFrame) -> DataFrame:
        fcol = df.column(self.get(self.features_col))
        d = fcol.values.shape[1] if fcol.values.ndim == 2 else 1
        x = np.asarray(
            extract_feature_matrix(fcol, (d,), self.get(self.features_col)),
            np.float64,
        )
        log_prior = self.get(self.log_prior)
        if self.get(self.kind) == "multinomial":
            joint = x @ self.get(self.log_theta).T + log_prior[None, :]
        else:
            mean, var = self.get(self.mean), self.get(self.var)
            # (n, k): sum_d of -0.5*(log 2 pi var + (x-mu)^2/var)
            joint = (
                -0.5 * (
                    ((x[:, None, :] - mean[None]) ** 2 / var[None]).sum(-1)
                    + np.log(2 * np.pi * var).sum(-1)[None, :]
                )
                + log_prior[None, :]
            )
        m = joint.max(axis=1, keepdims=True)
        prob = np.exp(joint - m)
        prob /= prob.sum(axis=1, keepdims=True)
        pred = prob.argmax(axis=1).astype(np.float64)
        out = df.with_column(
            self.get(self.probability_col), prob, DataType.VECTOR
        )
        return out.with_column(
            self.get(self.prediction_col), pred, DataType.DOUBLE
        )

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [
            Field(self.get(self.probability_col), DataType.VECTOR),
            Field(self.get(self.prediction_col), DataType.DOUBLE),
        ]
