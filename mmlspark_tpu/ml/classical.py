"""Linear classifiers/regressors as zero-hidden-layer TPULearner networks."""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, DataType, Field
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasFeaturesCol,
    HasLabelCol,
    Param,
    TypeConverters,
    Wrappable,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.dnn.resnet import mlp
from mmlspark_tpu.models.tpu_learner import TPULearner
from mmlspark_tpu.models.tpu_model import TPUModel, extract_feature_matrix


class _LinearParams(HasFeaturesCol, HasLabelCol):
    max_iter = Param("max_iter", "Training epochs", TypeConverters.to_int)
    learning_rate = Param("learning_rate", "Step size", TypeConverters.to_float)
    reg_param = Param("reg_param", "L2 regularization (weight decay)", TypeConverters.to_float)
    batch_size = Param("batch_size", "Global batch size", TypeConverters.to_int)
    seed = Param("seed", "PRNG seed", TypeConverters.to_int)
    prediction_col = Param("prediction_col", "Prediction column", TypeConverters.to_string)

    def _set_linear_defaults(self) -> None:
        self._set_defaults(
            features_col="features", label_col="label", prediction_col="prediction",
            max_iter=50, learning_rate=0.1, reg_param=0.0, batch_size=64, seed=0,
        )

    def _learner(self, network, loss: str) -> TPULearner:
        return TPULearner(
            network,
            features_col=self.get(self.features_col),
            label_col=self.get(self.label_col),
            loss=loss,
            optimizer="adamw" if self.get(self.reg_param) > 0 else "adam",
            weight_decay=self.get(self.reg_param),
            learning_rate=self.get(self.learning_rate),
            epochs=self.get(self.max_iter),
            batch_size=self.get(self.batch_size),
            seed=self.get(self.seed),
        )


class LogisticRegression(Estimator, _LinearParams, Wrappable):
    """Multinomial logistic regression trained with the jit DP loop."""

    raw_prediction_col = Param("raw_prediction_col", "Raw margin column", TypeConverters.to_string)
    probability_col = Param("probability_col", "Probability column", TypeConverters.to_string)

    def __init__(self, **kwargs: Any):
        super().__init__()
        self._set_linear_defaults()
        self._set_defaults(raw_prediction_col="rawPrediction", probability_col="probability")
        self.set_params(**kwargs)

    def fit(self, df: DataFrame) -> "LogisticRegressionModel":
        fcol = df.column(self.get(self.features_col))
        d = fcol.values.shape[1] if fcol.values.ndim == 2 else 1
        y = df[self.get(self.label_col)]
        y_arr = np.asarray([float(v) for v in y])
        k = max(2, int(np.nanmax(y_arr)) + 1)
        inner = self._learner(mlp(d, [], k), "softmax_cross_entropy").fit(df)
        model = LogisticRegressionModel(inner)
        for p in ("features_col", "prediction_col", "raw_prediction_col", "probability_col"):
            model.set(p, self.get(p))
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [
            Field(self.get(self.raw_prediction_col), DataType.VECTOR),
            Field(self.get(self.probability_col), DataType.VECTOR),
            Field(self.get(self.prediction_col), DataType.DOUBLE),
        ]


class LogisticRegressionModel(Model, HasFeaturesCol, Wrappable):
    """Fitted LogisticRegression: raw margins, probabilities, predictions."""

    inner = ComplexParam("inner", "Fitted TPUModel")
    prediction_col = Param("prediction_col", "Prediction column", TypeConverters.to_string)
    raw_prediction_col = Param("raw_prediction_col", "Raw margin column", TypeConverters.to_string)
    probability_col = Param("probability_col", "Probability column", TypeConverters.to_string)

    def __init__(self, inner: Optional[TPUModel] = None):
        super().__init__()
        self._set_defaults(
            features_col="features", prediction_col="prediction",
            raw_prediction_col="rawPrediction", probability_col="probability",
        )
        if inner is not None:
            self.set(self.inner, inner)

    def transform(self, df: DataFrame) -> DataFrame:
        tpu_model: TPUModel = self.get(self.inner)
        tpu_model.set(tpu_model.input_col, self.get(self.features_col))
        scored = tpu_model.transform(df)
        raw = scored[tpu_model.get(tpu_model.output_col)]
        e = np.exp(raw - raw.max(axis=1, keepdims=True))
        prob = e / e.sum(axis=1, keepdims=True)
        pred = prob.argmax(axis=1).astype(np.float64)
        out = df
        out = out.with_column(self.get(self.raw_prediction_col), raw, DataType.VECTOR)
        out = out.with_column(self.get(self.probability_col), prob, DataType.VECTOR)
        return out.with_column(self.get(self.prediction_col), pred, DataType.DOUBLE)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [
            Field(self.get(self.raw_prediction_col), DataType.VECTOR),
            Field(self.get(self.probability_col), DataType.VECTOR),
            Field(self.get(self.prediction_col), DataType.DOUBLE),
        ]


class LinearRegression(Estimator, _LinearParams, Wrappable):
    """Linear regression trained with the jit DP loop (squared loss)."""

    def __init__(self, **kwargs: Any):
        super().__init__()
        self._set_linear_defaults()
        self.set_params(**kwargs)

    def fit(self, df: DataFrame) -> "LinearRegressionModel":
        fcol = df.column(self.get(self.features_col))
        d = fcol.values.shape[1] if fcol.values.ndim == 2 else 1
        inner = self._learner(mlp(d, [], 1), "mse").fit(df)
        model = LinearRegressionModel(inner)
        for p in ("features_col", "prediction_col"):
            model.set(p, self.get(p))
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.prediction_col), DataType.DOUBLE)]


class LinearRegressionModel(Model, HasFeaturesCol, Wrappable):
    """Fitted LinearRegression: predictions from the inner TPUModel."""

    inner = ComplexParam("inner", "Fitted TPUModel")
    prediction_col = Param("prediction_col", "Prediction column", TypeConverters.to_string)

    def __init__(self, inner: Optional[TPUModel] = None):
        super().__init__()
        self._set_defaults(features_col="features", prediction_col="prediction")
        if inner is not None:
            self.set(self.inner, inner)

    def transform(self, df: DataFrame) -> DataFrame:
        tpu_model: TPUModel = self.get(self.inner)
        tpu_model.set(tpu_model.input_col, self.get(self.features_col))
        scored = tpu_model.transform(df)
        raw = scored[tpu_model.get(tpu_model.output_col)]
        pred = raw[:, 0].astype(np.float64) if raw.ndim == 2 else raw.astype(np.float64)
        return df.with_column(self.get(self.prediction_col), pred, DataType.DOUBLE)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.prediction_col), DataType.DOUBLE)]
