"""ml — classical learners on the TPU training machinery.

The reference wraps SparkML's LogisticRegression / RandomForest / GBT etc.
inside TrainClassifier (TrainClassifier.scala:104-140). Here the classical
tier is built on the same jit/optax loop as TPULearner: a linear model is a
zero-hidden-layer Network, so LogisticRegression and LinearRegression get
the mesh/data-parallel path for free. Tree ensembles come from gbdt/.
"""

from mmlspark_tpu.ml.classical import (
    LinearRegression,
    LinearRegressionModel,
    LogisticRegression,
    LogisticRegressionModel,
)
from mmlspark_tpu.ml.bayes import NaiveBayes, NaiveBayesModel
from mmlspark_tpu.ml.forest import (
    DecisionTreeClassifier,
    DecisionTreeRegressor,
    RandomForestClassifier,
    RandomForestRegressor,
)

__all__ = [
    "DecisionTreeClassifier",
    "DecisionTreeRegressor",
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "NaiveBayes",
    "NaiveBayesModel",
    "RandomForestClassifier",
    "RandomForestRegressor",
]
