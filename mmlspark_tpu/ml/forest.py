"""RandomForest / DecisionTree learners over the GBDT tree machinery.

Reference parity: TrainClassifier / TuneHyperparameters wrap the SparkML
predictor zoo — RandomForestClassifier, DecisionTreeClassifier and their
regressors — with per-learner default search spaces
(tune-hyperparameters/src/main/scala/DefaultHyperparams.scala:17-95, quality
bar benchmarks_VerifyTrainClassifier.csv:6 "TrainClassifier + RandomForest").

TPU-first design: rather than a second tree implementation, these estimators
ride the fused-scan GBDT grower (gbdt/trainer.py) — a random forest is the
`rf` boosting mode (bagged trees fit to the initial gradients, averaged
output), a decision tree is a single unshrunk tree. SparkML-style params
(num_trees, max_depth, max_bins, subsampling_rate, ...) are translated onto
the LightGBM-style TrainConfig at fit time, so Tune can search either
vocabulary.
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import Param, TypeConverters
from mmlspark_tpu.gbdt.estimators import LightGBMClassifier, LightGBMRegressor


class _ForestParams:
    """SparkML-vocabulary params shared by the forest/tree estimators."""

    num_trees = Param("num_trees", "Number of trees in the forest", TypeConverters.to_int)
    max_bins = Param("max_bins", "Histogram bins per feature", TypeConverters.to_int)
    min_instances_per_node = Param(
        "min_instances_per_node", "Minimum rows per leaf", TypeConverters.to_int
    )
    min_info_gain = Param(
        "min_info_gain", "Minimum gain for a split", TypeConverters.to_float
    )
    subsampling_rate = Param(
        "subsampling_rate", "Row subsample fraction per tree", TypeConverters.to_float
    )
    feature_subset_strategy = Param(
        "feature_subset_strategy",
        "Features per split: all | sqrt | onethird | a float fraction",
        TypeConverters.to_string,
    )

    def _set_forest_defaults(self) -> None:
        self._set_defaults(
            num_trees=20,
            max_bins=32,
            min_instances_per_node=1,
            min_info_gain=0.0,
            subsampling_rate=0.632,
            feature_subset_strategy="sqrt",
            # depth-bounded growth (SparkML trees are depth-wise)
            max_depth=5,
            verbosity=0,
        )

    def _feature_fraction(self, n_features: int) -> float:
        strategy = self.get(self.feature_subset_strategy)
        if strategy == "all":
            return 1.0
        if strategy == "sqrt":
            return max(1.0 / n_features, math.sqrt(n_features) / n_features)
        if strategy == "onethird":
            return 1.0 / 3.0
        try:
            frac = float(strategy)
        except ValueError:
            raise ValueError(
                f"feature_subset_strategy {strategy!r}: use all | sqrt | "
                "onethird | a float fraction"
            ) from None
        if not 0.0 < frac <= 1.0:
            raise ValueError(f"feature fraction {frac} outside (0, 1]")
        return frac

    def _sync_tree_params(self, df: DataFrame, rf: bool) -> None:
        """Map the SparkML vocabulary onto the GBDT TrainConfig params.
        Runs at fit() so Tune-applied settings (which arrive via set())
        translate too."""
        fcol = df.column(self.get(self.features_col))
        n_features = fcol.values.shape[1] if fcol.values.ndim == 2 else 1
        depth = self.get(self.max_depth)
        self.set(self.max_bin, self.get(self.max_bins))
        self.set(self.min_data_in_leaf, self.get(self.min_instances_per_node))
        self.set(self.min_gain_to_split, self.get(self.min_info_gain))
        # Leaf budget = a full tree of this depth, so the depth limit is
        # what binds. Past depth 10 (or for max_depth<=0 = unlimited) the
        # budget caps at 1024 leaves — the fused grower's state is
        # O(num_leaves * F * B), so an unbounded budget would exhaust
        # device memory; warn because the tree may then be shallower than
        # strict SparkML semantics.
        if depth <= 0 or depth > 10:
            import warnings

            warnings.warn(
                f"max_depth={depth}: leaf budget capped at 1024 leaves "
                "(deeper growth bounded by device-side tree state)",
                RuntimeWarning,
            )
            self.set(self.num_leaves, 1024)
        else:
            self.set(self.num_leaves, max(2, 2 ** max(1, depth)))
        if rf:
            self.set(self.boosting_type, "rf")
            self.set(self.num_iterations, self.get(self.num_trees))
            self.set(self.bagging_fraction, self.get(self.subsampling_rate))
            self.set(self.bagging_freq, 1)
            self.set(self.feature_fraction, self._feature_fraction(n_features))
        else:
            self.set(self.boosting_type, "gbdt")
            self.set(self.num_iterations, 1)
            self.set(self.learning_rate, 1.0)  # single unshrunk tree


class RandomForestClassifier(LightGBMClassifier, _ForestParams):
    """Bagged-tree ensemble classifier (SparkML RandomForestClassifier
    surface; rf boosting mode underneath — averaged, unshrunk trees)."""

    def __init__(self, **kwargs: Any):
        super().__init__()
        self._set_forest_defaults()
        self.set_params(**kwargs)

    def fit(self, df: DataFrame):
        self._sync_tree_params(df, rf=True)
        return super().fit(df)


class RandomForestRegressor(LightGBMRegressor, _ForestParams):
    """Bagged-tree ensemble regressor (SparkML RandomForestRegressor)."""

    def __init__(self, **kwargs: Any):
        super().__init__()
        self._set_forest_defaults()
        self.set_params(**kwargs)

    def fit(self, df: DataFrame):
        self._sync_tree_params(df, rf=True)
        return super().fit(df)


class DecisionTreeClassifier(LightGBMClassifier, _ForestParams):
    """Single depth-bounded tree classifier (SparkML DecisionTreeClassifier
    surface; one unshrunk gradient tree underneath)."""

    def __init__(self, **kwargs: Any):
        super().__init__()
        self._set_forest_defaults()
        self._set_defaults(feature_subset_strategy="all", subsampling_rate=1.0)
        self.set_params(**kwargs)

    def fit(self, df: DataFrame):
        self._sync_tree_params(df, rf=False)
        return super().fit(df)


class DecisionTreeRegressor(LightGBMRegressor, _ForestParams):
    """Single depth-bounded tree regressor (SparkML DecisionTreeRegressor)."""

    def __init__(self, **kwargs: Any):
        super().__init__()
        self._set_forest_defaults()
        self._set_defaults(feature_subset_strategy="all", subsampling_rate=1.0)
        self.set_params(**kwargs)

    def fit(self, df: DataFrame):
        self._sync_tree_params(df, rf=False)
        return super().fit(df)
