"""Model builders: ResNet for CIFAR, small MLPs.

The flagship inference model — the role the CNTK ResNet zoo plays for the
reference's CIFAR10 notebook (SURVEY.md §7 phase 3; reference model zoo via
downloader ModelDownloader.scala:209-267). Specs are plain JSON so they
round-trip through Network.save_to_dir.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from mmlspark_tpu.dnn.network import Network


def _bn_relu_conv(filters: int, stride: int = 1, kernel: int = 3) -> List[dict]:
    return [
        {"kind": "conv", "filters": filters, "kernel": kernel, "stride": stride,
         "use_bias": False},
        {"kind": "batchnorm"},
        {"kind": "relu"},
    ]


def _basic_block(filters: int, stride: int = 1, project: bool = False) -> dict:
    body = [
        {"kind": "conv", "filters": filters, "kernel": 3, "stride": stride,
         "use_bias": False},
        {"kind": "batchnorm"},
        {"kind": "relu"},
        {"kind": "conv", "filters": filters, "kernel": 3, "stride": 1,
         "use_bias": False},
        {"kind": "batchnorm"},
    ]
    shortcut = None
    if project:
        shortcut = [
            {"kind": "conv", "filters": filters, "kernel": 1, "stride": stride,
             "use_bias": False},
            {"kind": "batchnorm"},
        ]
    block: dict = {"kind": "residual", "body": body}
    if shortcut:
        block["shortcut"] = shortcut
    return block


def resnet_cifar(
    depth: int = 20,
    num_classes: int = 10,
    input_shape: Sequence[int] = (32, 32, 3),
    compute_dtype: str = "float32",
) -> Network:
    """ResNet-(6n+2) for CIFAR (He et al. config): 3 stages of n basic blocks
    at 16/32/64 filters. depth=20 -> n=3."""
    if (depth - 2) % 6 != 0:
        raise ValueError("CIFAR ResNet depth must be 6n+2")
    n = (depth - 2) // 6
    spec: List[dict] = [
        {"kind": "conv", "name": "stem", "filters": 16, "kernel": 3, "use_bias": False},
        {"kind": "batchnorm", "name": "stem_bn"},
        {"kind": "relu", "name": "stem_relu"},
    ]
    for stage, filters in enumerate((16, 32, 64)):
        for block in range(n):
            stride = 2 if (stage > 0 and block == 0) else 1
            project = stage > 0 and block == 0
            cfg = _basic_block(filters, stride, project)
            cfg["name"] = f"stage{stage + 1}_block{block + 1}"
            spec.append(cfg)
            spec.append({"kind": "relu", "name": f"stage{stage + 1}_relu{block + 1}"})
    spec += [
        {"kind": "global_avg_pool", "name": "pool"},
        {"kind": "dense", "name": "logits", "units": num_classes},
    ]
    return Network(spec, input_shape, compute_dtype)


def resnet20_cifar(num_classes: int = 10, compute_dtype: str = "float32") -> Network:
    return resnet_cifar(20, num_classes, compute_dtype=compute_dtype)


def _bottleneck_block(filters: int, stride: int = 1, project: bool = False,
                      expansion: int = 4) -> dict:
    """1x1 reduce -> 3x3 -> 1x1 expand bottleneck (He et al. ResNet-50/101/152).
    The 3 matmul-shaped convs are exactly what the MXU wants: the 1x1 convs
    lower to plain (N*H*W, Cin) x (Cin, Cout) matmuls."""
    out = filters * expansion
    body = (
        _bn_relu_conv(filters, 1, kernel=1)
        + _bn_relu_conv(filters, stride, kernel=3)
        + [
            {"kind": "conv", "filters": out, "kernel": 1, "stride": 1,
             "use_bias": False},
            {"kind": "batchnorm"},
        ]
    )
    block: dict = {"kind": "residual", "body": body}
    if project:
        block["shortcut"] = [
            {"kind": "conv", "filters": out, "kernel": 1, "stride": stride,
             "use_bias": False},
            {"kind": "batchnorm"},
        ]
    return block


def resnet_imagenet(
    depth: int = 50,
    num_classes: int = 1000,
    input_shape: Sequence[int] = (224, 224, 3),
    compute_dtype: str = "float32",
) -> Network:
    """ImageNet-style ResNet: 7x7/2 stem + SAME 3x3/2 maxpool, 4 stages at
    64/128/256/512 base filters, global average pool, dense head. Depths
    18/34 use basic blocks; 50/101/152 use bottleneck blocks (x4 expansion).

    The flagship transfer-learning network family — the role the CNTK zoo
    plays for the reference (ModelDownloader.scala:209-267 downloadByName
    "ResNet50"; consumed by ImageFeaturizer.scala:129-177)."""
    basic = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3)}
    bottleneck = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}
    if depth not in basic and depth not in bottleneck:
        raise ValueError(
            f"ImageNet ResNet depth must be one of "
            f"{sorted(basic) + sorted(bottleneck)}"
        )
    use_bottleneck = depth in bottleneck
    stages = bottleneck.get(depth) or basic[depth]
    spec: List[dict] = [
        {"kind": "conv", "name": "stem", "filters": 64, "kernel": 7, "stride": 2,
         "use_bias": False},
        {"kind": "batchnorm", "name": "stem_bn"},
        {"kind": "relu", "name": "stem_relu"},
        {"kind": "max_pool", "name": "stem_pool", "size": 3, "stride": 2,
         "padding": "SAME"},
    ]
    for stage, (filters, n_blocks) in enumerate(
        zip((64, 128, 256, 512), stages)
    ):
        for block in range(n_blocks):
            stride = 2 if (stage > 0 and block == 0) else 1
            if use_bottleneck:
                cfg = _bottleneck_block(filters, stride, project=block == 0)
            else:
                # basic blocks: projection only where shape changes
                project = block == 0 and stage > 0
                cfg = _basic_block(filters, stride, project)
            cfg["name"] = f"stage{stage + 1}_block{block + 1}"
            spec.append(cfg)
            spec.append(
                {"kind": "relu", "name": f"stage{stage + 1}_relu{block + 1}"}
            )
    spec += [
        {"kind": "global_avg_pool", "name": "pool"},
        {"kind": "dense", "name": "logits", "units": num_classes},
    ]
    return Network(spec, input_shape, compute_dtype)


def resnet18(num_classes: int = 1000,
             input_shape: Sequence[int] = (224, 224, 3),
             compute_dtype: str = "float32") -> Network:
    return resnet_imagenet(18, num_classes, input_shape, compute_dtype)


def resnet34(num_classes: int = 1000,
             input_shape: Sequence[int] = (224, 224, 3),
             compute_dtype: str = "float32") -> Network:
    return resnet_imagenet(34, num_classes, input_shape, compute_dtype)


def resnet50(
    num_classes: int = 1000,
    input_shape: Sequence[int] = (224, 224, 3),
    compute_dtype: str = "float32",
) -> Network:
    return resnet_imagenet(50, num_classes, input_shape, compute_dtype)


def resnet_mini(num_classes: int = 10, input_shape: Sequence[int] = (8, 8, 3)) -> Network:
    """Tiny 2-block ResNet for fast CPU tests."""
    spec = [
        {"kind": "conv", "name": "stem", "filters": 8, "kernel": 3, "use_bias": False},
        {"kind": "batchnorm", "name": "stem_bn"},
        {"kind": "relu", "name": "stem_relu"},
        dict(_basic_block(8), name="block1"),
        {"kind": "relu", "name": "relu1"},
        {"kind": "global_avg_pool", "name": "pool"},
        {"kind": "dense", "name": "logits", "units": num_classes},
    ]
    return Network(spec, input_shape)


def mlp(
    input_dim: int,
    hidden: Sequence[int],
    num_outputs: int,
    activation: str = "relu",
    compute_dtype: str = "float32",
) -> Network:
    """Dense MLP over VECTOR features — the BrainScript one-liner equivalent
    (reference cntk-train's default model)."""
    spec: List[dict] = []
    for i, h in enumerate(hidden):
        spec.append({"kind": "dense", "name": f"dense_{i}", "units": int(h)})
        spec.append({"kind": activation, "name": f"{activation}_{i}"})
    spec.append({"kind": "dense", "name": "logits", "units": int(num_outputs)})
    return Network(spec, (input_dim,), compute_dtype)
