"""Network: a named-layer functional NN built from a JSON-serializable spec.

The spec is the model DSL — the role BrainScript plays in the reference
(BrainscriptBuilder.scala:16-151) — but declarative JSON that rebuilds the
same jax function anywhere. Named layers give the `layerNames` metadata the
reference's model zoo schema carries (downloader Schema.scala), so
ImageFeaturizer-style truncation works by name or by count.

Variables are split into two collections:
    {"params": {layer: {...trainable...}}, "state": {layer: {...running stats}}}
so trainers differentiate w.r.t. params only (BatchNorm running mean/var live
in state). All layer applies are pure; train-mode BatchNorm returns updated
state through `apply_and_state`.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

Spec = List[Dict[str, Any]]

LAYER_KINDS: Dict[str, "LayerDef"] = {}


class LayerDef:
    def __init__(self, kind: str, init: Callable, apply: Callable):
        self.kind = kind
        self.init = init      # (rng, cfg, in_shape) -> (params, state, out_shape)
        self.apply = apply    # (params, state, cfg, x, train, rng, w) -> (y, new_state)


def layer(kind: str):
    """Register a layer kind: decorated fn returns (init, apply)."""

    def wrap(fn):
        init, apply = fn()
        LAYER_KINDS[kind] = LayerDef(kind, init, apply)
        return fn

    return wrap


def _he_normal(rng, shape, fan_in, dtype):
    import jax

    std = np.sqrt(2.0 / max(1, fan_in))
    return (jax.random.normal(rng, shape) * std).astype(dtype)


# -- layer kinds ---------------------------------------------------------------


@layer("dense")
def _dense():
    def init(rng, cfg, in_shape):
        d_in = int(np.prod(in_shape))
        d_out = cfg["units"]
        params = {
            "kernel": _he_normal(rng, (d_in, d_out), d_in, np.float32),
            "bias": np.zeros((d_out,), np.float32),
        }
        return params, {}, (d_out,)

    def apply(params, state, cfg, x, train, rng, w=None):
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        if "kernel_scale" in params:
            # int8 weight-only variant (dnn/quant.py): codes stay int8 in
            # HBM, the Pallas kernel dequantizes in VMEM mid-matmul
            from mmlspark_tpu.dnn.quant import int8_matmul

            y = int8_matmul(x, params["kernel"], params["kernel_scale"])
            return y + params["bias"].astype(y.dtype), state
        return x @ params["kernel"] + params["bias"], state

    return init, apply


@layer("conv")
def _conv():
    def init(rng, cfg, in_shape):
        kh = kw = cfg.get("kernel", 3)
        if isinstance(kh, (list, tuple)):
            kh, kw = kh
        c_in = in_shape[-1]
        c_out = cfg["filters"]
        stride = cfg.get("stride", 1)
        params = {
            "kernel": _he_normal(rng, (kh, kw, c_in, c_out), kh * kw * c_in, np.float32),
        }
        if cfg.get("use_bias", True):
            params["bias"] = np.zeros((c_out,), np.float32)
        h, w = in_shape[0], in_shape[1]
        if cfg.get("padding", "SAME") == "SAME":
            oh, ow = -(-h // stride), -(-w // stride)
        else:
            oh, ow = (h - kh) // stride + 1, (w - kw) // stride + 1
        return params, {}, (oh, ow, c_out)

    def apply(params, state, cfg, x, train, rng, w=None):
        import jax
        import jax.numpy as jnp

        kernel = params["kernel"]
        if "kernel_scale" in params:
            # int8 storage-only conv (dnn/quant.py): codes are int8 at
            # rest; one whole-kernel dequantize feeds the f32 conv (XLA
            # has no mixed int8/f32 conv — the payload saving is in HBM
            # residency and the upload, not the MACs)
            kernel = kernel.astype(jnp.float32) * params["kernel_scale"]
        stride = cfg.get("stride", 1)
        y = jax.lax.conv_general_dilated(
            x,
            kernel.astype(x.dtype),
            window_strides=(stride, stride),
            padding=cfg.get("padding", "SAME"),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if "bias" in params:
            y = y + params["bias"].astype(y.dtype)
        return y, state

    return init, apply


@layer("batchnorm")
def _batchnorm():
    def init(rng, cfg, in_shape):
        c = in_shape[-1]
        params = {"scale": np.ones((c,), np.float32), "bias": np.zeros((c,), np.float32)}
        state = {"mean": np.zeros((c,), np.float32), "var": np.ones((c,), np.float32)}
        return params, state, in_shape

    def apply(params, state, cfg, x, train, rng, w=None):
        import jax.numpy as jnp

        eps = cfg.get("epsilon", 1e-5)
        momentum = cfg.get("momentum", 0.9)
        if train:
            axes = tuple(range(x.ndim - 1))
            xf = x.astype(jnp.float32)
            if w is not None:
                # Per-row sample weights (zero-weight = padding) must not
                # contaminate batch statistics: weighted mean/var over
                # (batch x spatial) positions.
                ww = w.astype(jnp.float32).reshape((-1,) + (1,) * (x.ndim - 1))
                spatial = float(np.prod(x.shape[1:-1])) if x.ndim > 2 else 1.0
                denom = jnp.maximum(jnp.sum(ww), 1e-9) * spatial
                mean = jnp.sum(xf * ww, axis=axes) / denom
                var = jnp.sum(((xf - mean) ** 2) * ww, axis=axes) / denom
            else:
                mean = jnp.mean(xf, axis=axes)
                var = jnp.var(xf, axis=axes)
            new_state = {
                "mean": momentum * state["mean"] + (1 - momentum) * mean,
                "var": momentum * state["var"] + (1 - momentum) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = (params["scale"] / jnp.sqrt(var + eps)).astype(x.dtype)
        y = (x - mean.astype(x.dtype)) * inv + params["bias"].astype(x.dtype)
        return y, new_state

    return init, apply


def _stateless(fn, shape_fn=None):
    def init(rng, cfg, in_shape):
        out = shape_fn(cfg, in_shape) if shape_fn else in_shape
        return {}, {}, out

    def apply(params, state, cfg, x, train, rng, w=None):
        return fn(cfg, x), state

    return init, apply


@layer("relu")
def _relu():
    import_fn = lambda cfg, x: __import__("jax.numpy", fromlist=["maximum"]).maximum(x, 0)
    return _stateless(import_fn)


@layer("gelu")
def _gelu():
    def fn(cfg, x):
        import jax

        return jax.nn.gelu(x)

    return _stateless(fn)


@layer("tanh")
def _tanh():
    def fn(cfg, x):
        import jax.numpy as jnp

        return jnp.tanh(x)

    return _stateless(fn)


@layer("sigmoid")
def _sigmoid():
    def fn(cfg, x):
        import jax

        return jax.nn.sigmoid(x)

    return _stateless(fn)


@layer("softmax")
def _softmax():
    def fn(cfg, x):
        import jax

        return jax.nn.softmax(x, axis=-1)

    return _stateless(fn)


@layer("log_softmax")
def _log_softmax():
    def fn(cfg, x):
        import jax

        return jax.nn.log_softmax(x, axis=-1)

    return _stateless(fn)


def _pool_shape(cfg, in_shape):
    k = cfg.get("size", 2)
    s = cfg.get("stride", k)
    h, w, c = in_shape
    if cfg.get("padding", "VALID") == "SAME":
        return (-(-h // s), -(-w // s), c)
    return ((h - k) // s + 1, (w - k) // s + 1, c)


@layer("max_pool")
def _max_pool():
    def fn(cfg, x):
        import jax

        k = cfg.get("size", 2)
        s = cfg.get("stride", k)
        return jax.lax.reduce_window(
            x, -np.inf, jax.lax.max, (1, k, k, 1), (1, s, s, 1),
            cfg.get("padding", "VALID"),
        )

    return _stateless(fn, _pool_shape)


@layer("avg_pool")
def _avg_pool():
    def fn(cfg, x):
        import jax
        import jax.numpy as jnp

        k = cfg.get("size", 2)
        s = cfg.get("stride", k)
        padding = cfg.get("padding", "VALID")
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, (1, k, k, 1), (1, s, s, 1), padding
        )
        if padding == "SAME":
            # edge windows overlap the zero pad: divide by the REAL element
            # count per window, not k*k (count_include_pad=False semantics)
            ones = jnp.ones((1,) + x.shape[1:3] + (1,), x.dtype)
            counts = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, (1, k, k, 1), (1, s, s, 1), padding
            )
            return summed / counts
        return summed / (k * k)

    return _stateless(fn, _pool_shape)


@layer("global_avg_pool")
def _global_avg_pool():
    def fn(cfg, x):
        import jax.numpy as jnp

        return jnp.mean(x, axis=(1, 2))

    return _stateless(fn, lambda cfg, s: (s[-1],))


@layer("flatten")
def _flatten():
    def fn(cfg, x):
        return x.reshape(x.shape[0], -1)

    return _stateless(fn, lambda cfg, s: (int(np.prod(s)),))


@layer("dropout")
def _dropout():
    def init(rng, cfg, in_shape):
        return {}, {}, in_shape

    def apply(params, state, cfg, x, train, rng, w=None):
        if not train or rng is None:
            return x, state
        import jax

        rate = cfg.get("rate", 0.5)
        keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
        return (x * keep) / (1.0 - rate), state

    return init, apply


@layer("residual")
def _residual():
    def init(rng, cfg, in_shape):
        import jax

        body = cfg["body"]
        shortcut = cfg.get("shortcut") or []
        r_body, r_short = jax.random.split(rng)
        bp, bs, out_shape = _init_spec(r_body, body, in_shape)
        sp, ss, s_shape = _init_spec(r_short, shortcut, in_shape)
        if s_shape != out_shape:
            raise ValueError(
                f"residual shapes differ: body {out_shape} vs shortcut {s_shape}"
            )
        return {"body": bp, "shortcut": sp}, {"body": bs, "shortcut": ss}, out_shape

    def apply(params, state, cfg, x, train, rng, w=None):
        # .get with {} fallbacks: empty subtrees (identity shortcut, no BN
        # state) are dropped by the flattened npz save and must not be required
        body = cfg["body"]
        shortcut = cfg.get("shortcut") or []
        y, new_bs, _ = _apply_spec(
            params.get("body", {}), state.get("body", {}), body, x, train, rng, None, w
        )
        s, new_ss, _ = _apply_spec(
            params.get("shortcut", {}), state.get("shortcut", {}), shortcut,
            x, train, rng, None, w,
        )
        return y + s, {"body": new_bs, "shortcut": new_ss}

    return init, apply


# -- FLOPs accounting (MFU reporting in bench.py) ------------------------------


def _spec_flops(spec: Spec, in_shape) -> Tuple[float, Tuple[int, ...]]:
    """(multiply-add FLOPs per example, output shape) for one spec walk.
    Counts the MXU work only (convs + dense, 2*MACs); elementwise/BN/pool
    FLOPs are noise next to the matmuls and XLA fuses them anyway."""
    flops = 0.0
    shape = tuple(in_shape)
    for cfg in spec:
        kind = cfg["kind"]
        if kind == "conv":
            k = cfg.get("kernel", 3)
            kh, kw = (k, k) if not isinstance(k, (list, tuple)) else k
            s = cfg.get("stride", 1)
            h, w, c_in = shape
            if cfg.get("padding", "SAME") == "SAME":
                oh, ow = -(-h // s), -(-w // s)
            else:
                oh, ow = (h - kh) // s + 1, (w - kw) // s + 1
            c_out = cfg["filters"]
            flops += 2.0 * kh * kw * c_in * c_out * oh * ow
            shape = (oh, ow, c_out)
        elif kind == "dense":
            d_in = int(np.prod(shape))
            d_out = cfg["units"]
            flops += 2.0 * d_in * d_out
            shape = (d_out,)
        elif kind in ("max_pool", "avg_pool"):
            shape = _pool_shape(cfg, shape)
        elif kind == "global_avg_pool":
            shape = (shape[-1],)
        elif kind == "flatten":
            shape = (int(np.prod(shape)),)
        elif kind == "residual":
            body_f, body_shape = _spec_flops(cfg["body"], shape)
            short_f, _ = _spec_flops(cfg.get("shortcut") or [], shape)
            flops += body_f + short_f
            shape = body_shape
        # batchnorm / activations / dropout: shape-preserving, ~0 MXU FLOPs
    return flops, shape


# -- spec walking --------------------------------------------------------------


def _named_spec(spec: Spec) -> Spec:
    """Assign unique names to unnamed layers (kind_index)."""
    out = []
    seen = set()
    for i, cfg in enumerate(spec):
        cfg = dict(cfg)
        name = cfg.get("name") or f"{cfg['kind']}_{i}"
        if name in seen:
            raise ValueError(f"duplicate layer name {name!r}")
        seen.add(name)
        cfg["name"] = name
        out.append(cfg)
    return out


def _init_spec(rng, spec: Spec, in_shape):
    import jax

    params, state = {}, {}
    shape = tuple(in_shape)
    spec = _named_spec(spec)
    rngs = jax.random.split(rng, max(1, len(spec)))
    for cfg, r in zip(spec, rngs):
        d = LAYER_KINDS[cfg["kind"]]
        p, s, shape = d.init(r, cfg, shape)
        if p:
            params[cfg["name"]] = p
        if s:
            state[cfg["name"]] = s
    return params, state, shape


def _apply_spec(params, state, spec: Spec, x, train, rng, capture: Optional[set], w=None):
    import jax

    new_state = {}
    acts = {}
    spec = _named_spec(spec)
    if rng is not None:
        rngs = jax.random.split(rng, max(1, len(spec)))
    else:
        rngs = [None] * len(spec)
    for cfg, r in zip(spec, rngs):
        d = LAYER_KINDS[cfg["kind"]]
        name = cfg["name"]
        x, s = d.apply(params.get(name, {}), state.get(name, {}), cfg, x, train, r, w)
        if s:
            new_state[name] = s
        if capture is not None and name in capture:
            acts[name] = x
    return x, new_state, acts


class Network:
    """A named-layer NN: JSON spec + (params, state) variables.

    Usage:
        net = Network(spec, input_shape=(32, 32, 3))
        variables = net.init(jax.random.PRNGKey(0))
        y = net.apply(variables, x)                     # inference
        y, new_state = net.apply_and_state(variables, x, train=True, rng=r)
    """

    def __init__(
        self,
        spec: Spec,
        input_shape: Sequence[int],
        compute_dtype: str = "float32",
    ):
        self.spec = _named_spec(spec)
        self.input_shape = tuple(int(d) for d in input_shape)
        self.compute_dtype = compute_dtype
        for cfg in self.spec:
            if cfg["kind"] not in LAYER_KINDS:
                raise ValueError(f"unknown layer kind {cfg['kind']!r}")

    # -- structure -------------------------------------------------------------

    @property
    def layer_names(self) -> List[str]:
        return [cfg["name"] for cfg in self.spec]

    def out_shape(self) -> Tuple[int, ...]:
        import jax

        _, _, shape = _init_spec(jax.random.PRNGKey(0), self.spec, self.input_shape)
        return shape

    def flops_per_example(self) -> float:
        """Forward-pass multiply-add FLOPs per example (MXU work only) —
        the numerator of bench.py's MFU lines."""
        flops, _ = _spec_flops(self.spec, self.input_shape)
        return flops

    def truncate(self, cut_output_layers: int) -> "Network":
        """Drop the last N layers — the reference's `cutOutputLayers`
        headless-featurization semantics (ImageFeaturizer.scala:129-177)."""
        if not 0 <= cut_output_layers < len(self.spec):
            raise ValueError(
                f"cut_output_layers={cut_output_layers} out of range for "
                f"{len(self.spec)} layers"
            )
        spec = self.spec[: len(self.spec) - cut_output_layers]
        return Network(spec, self.input_shape, self.compute_dtype)

    def truncate_at(self, layer_name: str) -> "Network":
        """Keep layers up to and including `layer_name`."""
        names = self.layer_names
        if layer_name not in names:
            raise ValueError(f"no layer {layer_name!r}; have {names}")
        idx = names.index(layer_name)
        return Network(self.spec[: idx + 1], self.input_shape, self.compute_dtype)

    # -- init / apply ----------------------------------------------------------

    def init(self, rng) -> Dict[str, Any]:
        params, state, _ = _init_spec(rng, self.spec, self.input_shape)
        return {"params": params, "state": state}

    def _cast_in(self, x):
        import jax.numpy as jnp

        if self.compute_dtype == "int8":
            # int8 is a WEIGHT storage dtype, not an activation dtype:
            # activations run float32 and only the resident kernels are
            # quantized (dnn/quant.py — weight-only scheme, no activation
            # calibration)
            return x.astype(jnp.float32)
        return x.astype(jnp.dtype(self.compute_dtype))

    def apply(self, variables, x, train: bool = False, rng=None):
        y, _, _ = _apply_spec(
            variables["params"], variables["state"], self.spec,
            self._cast_in(x), train, rng, None,
        )
        return y

    def apply_and_state(self, variables, x, train: bool = True, rng=None,
                        sample_weight=None):
        y, new_state, _ = _apply_spec(
            variables["params"], variables["state"], self.spec,
            self._cast_in(x), train, rng, None, sample_weight,
        )
        merged = dict(variables["state"])
        merged.update(new_state)
        return y, merged

    def apply_collect(self, variables, x, layer_names: Sequence[str]):
        """Forward pass capturing named intermediate activations."""
        y, _, acts = _apply_spec(
            variables["params"], variables["state"], self.spec,
            self._cast_in(x), False, None, set(layer_names),
        )
        return y, acts

    # -- persistence (serialize.py "custom" protocol) --------------------------

    def save_to_dir(self, path: str, variables: Optional[dict] = None) -> None:
        # Crash-consistent save: the whole directory is staged in a tmp
        # sibling and atomically swapped in (io/checkpoint.staged_dir), so
        # a kill mid-save can never destroy a previous good model dir or
        # leave a spec.json/variables.npz torn hybrid.
        import shutil

        from mmlspark_tpu.io.checkpoint import staged_dir

        with staged_dir(path) as tmp_dir:
            with open(os.path.join(tmp_dir, "spec.json"), "w") as f:
                json.dump(
                    {
                        "spec": self.spec,
                        "input_shape": list(self.input_shape),
                        "compute_dtype": self.compute_dtype,
                    },
                    f,
                    indent=1,
                )
            if variables is not None:
                flat = _flatten_tree(variables)
                np.savez(os.path.join(tmp_dir, "variables.npz"), **flat)
            else:
                # spec-only overwrite keeps its pre-ISSUE-8 merge
                # semantics: existing weights at `path` survive the
                # atomic swap by riding the staging dir
                old_vars = os.path.join(path, "variables.npz")
                if os.path.exists(old_vars):
                    shutil.copy2(
                        old_vars, os.path.join(tmp_dir, "variables.npz")
                    )

    @classmethod
    def load_from_dir(cls, path: str) -> "Network":
        with open(os.path.join(path, "spec.json")) as f:
            meta = json.load(f)
        return cls(meta["spec"], meta["input_shape"], meta["compute_dtype"])

    @staticmethod
    def load_variables(path: str) -> Optional[dict]:
        vpath = os.path.join(path, "variables.npz")
        if not os.path.exists(vpath):
            return None
        with np.load(vpath) as z:
            tree = _unflatten_tree({k: z[k] for k in z.files})
        tree.setdefault("params", {})
        tree.setdefault("state", {})
        return tree


def deterministic_variables(net: "Network", seed: int = 0) -> dict:
    """Platform-independent random init: jax.random values differ in ulps
    across backends (erfinv lowering), so builder-backed zoo entries
    (downloader/downloader.py materialize path) fill the init-shaped tree
    from a numpy rng instead — one draw sequence over sorted flattened keys,
    he-normal for kernels, identity for BN — giving a bit-identical
    variables.npz (and hence sha256) on CPU and TPU."""
    import jax

    # eval_shape: leaf shapes only, no actual random generation
    variables = jax.eval_shape(net.init, jax.random.PRNGKey(0))

    def walk_shapes(tree, prefix=""):
        for k, v in tree.items():
            key = f"{prefix}{_SEP}{k}" if prefix else str(k)
            if isinstance(v, dict):
                yield from walk_shapes(v, key)
            else:
                yield key, tuple(v.shape)

    flat = dict(walk_shapes(variables))
    rng = np.random.default_rng(seed)
    out = {}
    for key in sorted(flat):
        shape = flat[key]
        leaf = key.rsplit(_SEP, 1)[-1]
        if leaf == "kernel":
            fan_in = int(np.prod(shape[:-1]))
            out[key] = (
                rng.standard_normal(shape) * np.sqrt(2.0 / max(1, fan_in))
            ).astype(np.float32)
        elif leaf in ("scale", "var"):
            out[key] = np.ones(shape, np.float32)
        else:  # bias / mean
            out[key] = np.zeros(shape, np.float32)
    tree = _unflatten_tree(out)
    tree.setdefault("params", {})
    tree.setdefault("state", {})
    return tree


class NetworkBundle:
    """A Network together with its trained variables — the unit a model
    stage holds and persists (the reference's serialized CNTK model bytes,
    SerializableFunction.scala:88-115, reborn as spec JSON + weights npz)."""

    def __init__(self, network: Network, variables: dict):
        self.network = network
        self.variables = variables
        self._dev_vars = None

    def device_variables(self):
        """Weights as device-resident arrays, uploaded once per bundle — a
        ResNet-50's ~100MB of params re-crossing the host->HBM link on every
        transform call would dominate small-batch inference. The one upload
        is counted in profiling.dataplane_counters() and held in the
        device-memory ledger (model_weights) until the bundle is collected."""
        if self._dev_vars is None:
            import weakref

            import jax

            from mmlspark_tpu.obs.memory import device_label, memory_ledger
            from mmlspark_tpu.utils.profiling import dataplane_counters

            nbytes = sum(
                leaf.nbytes for leaf in jax.tree_util.tree_leaves(self.variables)
                if hasattr(leaf, "nbytes")
            )
            dataplane_counters().record_h2d(nbytes)
            self._dev_vars = jax.device_put(self.variables)
            led = memory_ledger()
            if led.enabled and nbytes > 0:
                leaves = jax.tree_util.tree_leaves(self._dev_vars)
                dev = device_label(leaves[0] if leaves else None)
                owner = f"bundle-{id(self)}"
                led.record_alloc(dev, "model_weights", nbytes, owner=owner)
                # the ledger entry lives exactly as long as the cached device
                # tree: collecting the bundle drops the arrays AND the bytes
                weakref.finalize(self, led.record_free, dev, "model_weights",
                                 nbytes, owner)
        return self._dev_vars

    def save_to_dir(self, path: str) -> None:
        self.network.save_to_dir(path, self.variables)

    @classmethod
    def load_from_dir(cls, path: str) -> "NetworkBundle":
        network = Network.load_from_dir(path)
        variables = Network.load_variables(path)
        if variables is None:
            raise FileNotFoundError(f"no variables.npz under {path}")
        variables.setdefault("params", {})
        variables.setdefault("state", {})
        return cls(network, variables)


_SEP = "/"


def _flatten_tree(tree: dict, prefix: str = "") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{_SEP}{k}" if prefix else str(k)
        if isinstance(v, dict):
            if not v:
                continue
            out.update(_flatten_tree(v, key))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten_tree(flat: Dict[str, np.ndarray]) -> dict:
    tree: dict = {}
    for key, v in flat.items():
        parts = key.split(_SEP)
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree
