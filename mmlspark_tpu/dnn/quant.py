"""Weight-only int8 quantization for zoo inference (docs/dataplane.md
"int8 inference variants").

Per-channel symmetric quantization: each OUTPUT channel c of a kernel
stores int8 codes q[..., c] = round(w[..., c] / scale[c]) with its own f32
scale[c] = max|w[..., c]| / 127 — 4x smaller weight payload than f32 in
HBM and on the wire, at ~0.4% worst-case relative weight error. Compute
stays float32: activations are NEVER quantized (a weight-only scheme needs
no calibration data and no activation-range tracking), and the matmul
dequantizes on the fly — ``(x @ q_f32) * scale``, exact in the scale step
because the per-column factor multiplies AFTER the accumulation.

The dense path runs ``int8_matmul`` below: one Pallas TPU kernel per row
block that converts the resident int8 codes to f32 **in VMEM** (HBM only
ever sees the int8 bytes — the 4x traffic saving is the point), runs the
f32 MXU dot, and scales columns in-register. Off-TPU the kernel body runs
in Pallas interpret mode — the same arithmetic as plain JAX ops — which is
how tier-1 CPU CI exercises it. Oversized operands fall back to the XLA
einsum contraction with the SAME ``(x @ q) * scale`` factorization, so the
two paths agree to f32 ulps (accumulation order is the only difference).
Conv kernels take the storage-only scheme: int8 in HBM, one whole-kernel
dequantize before ``conv_general_dilated`` (XLA has no mixed int8/f32
conv; the weight payload saving still applies).

Parity is gated, not assumed: ``INT8_LOGIT_MAE_TOL`` in zoo_builders plus
exact top-1 agreement, mirroring the bf16 gate.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import numpy as np

__all__ = [
    "quantize_per_channel",
    "dequantize",
    "int8_matmul",
    "quantize_variables",
]

#: row block per Pallas grid step (f32 sublane-tile friendly; large enough
#: to keep the MXU busy at zoo batch sizes)
_MM_BLK_M = 256
#: fall back to the XLA path when the dequantized weight block would not
#: comfortably fit VMEM beside the row block (elements of the padded
#: (K_pad, N_pad) operand; 4 MiB of f32 leaves headroom in ~16 MiB VMEM)
_MM_VMEM_ELEMS = 1 << 20


def quantize_per_channel(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-output-channel int8 codes for a kernel.

    The LAST axis is the output channel — true for both dense (d_in, d_out)
    and conv HWIO (kh, kw, c_in, c_out) kernels. Returns (q int8 same
    shape, scale f32 (c_out,)); all-zero channels get scale 1.0 so
    dequantization is exact for them too."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
    scale = (amax / 127.0).astype(np.float32)
    scale = np.where(scale > 0, scale, np.float32(1.0)).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """f32 weights back from per-channel codes (the reference arm of the
    parity tests; also the conv storage-only path)."""
    return np.asarray(q, np.float32) * np.asarray(scale, np.float32)


def _interpret_default() -> bool:
    import jax

    return jax.default_backend() != "tpu"


#: jitted int8_matmul impls keyed by the static interpret flag (jax stays
#: a lazy import — this module loads without initializing a backend)
_MM_JIT: Dict[bool, Any] = {}


def int8_matmul(x, q, scale, *, interpret=None):
    """``(x @ q) * scale`` with int8-resident weights: x (n, K) f32,
    q (K, N) int8, scale (N,) f32 -> (n, N) f32.

    Dispatches between the Pallas dequant-in-VMEM kernel and the XLA
    contraction fallback (same factorization) on operand size; both paths
    keep the weights int8 at rest and differ only in f32 accumulation
    order (documented ulp band, gated by the interpret parity tests)."""
    import jax

    if interpret is None:
        interpret = _interpret_default()
    key = bool(interpret)
    fn = _MM_JIT.get(key)
    if fn is None:
        fn = _MM_JIT[key] = jax.jit(
            functools.partial(_int8_matmul_impl, interpret=key)
        )
    return fn(x, q, scale)


def _int8_matmul_impl(x, q, scale, *, interpret: bool):
    import jax.numpy as jnp

    n, K = x.shape
    Kq, N = q.shape
    assert K == Kq, f"x K={K} != q K={Kq}"
    K_pad = -(-K // 128) * 128
    N_pad = -(-N // 128) * 128
    if K_pad * N_pad > _MM_VMEM_ELEMS:
        # einsum fallback: whole-operand contraction, scale after the dot
        return (
            x @ q.astype(jnp.float32)
        ) * scale.astype(jnp.float32)[None, :]
    return _int8_matmul_pallas(
        x, q, scale, n=n, K=K, N=N, K_pad=K_pad, N_pad=N_pad,
        interpret=bool(interpret),
    )


def _int8_matmul_pallas(x, q, scale, *, n, K, N, K_pad, N_pad, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    BLK = _MM_BLK_M
    n_pad = -(-n // BLK) * BLK
    xp = jnp.pad(x.astype(jnp.float32), ((0, n_pad - n), (0, K_pad - K)))
    qp = jnp.pad(q.astype(jnp.int8), ((0, K_pad - K), (0, N_pad - N)))
    sp = jnp.pad(scale.astype(jnp.float32), (0, N_pad - N))[None, :]

    def kernel(x_ref, q_ref, s_ref, o_ref):
        # int8 HBM bytes become f32 only here, in VMEM
        qf = q_ref[:].astype(jnp.float32)            # (K_pad, N_pad)
        acc = jax.lax.dot_general(
            x_ref[:], qf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                            # (BLK, N_pad)
        o_ref[:] = acc * s_ref[:]

    out = pl.pallas_call(
        kernel,
        grid=(n_pad // BLK,),
        in_specs=[
            pl.BlockSpec((BLK, K_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((K_pad, N_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((BLK, N_pad), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, N_pad), jnp.float32),
        interpret=interpret,
    )(xp, qp, sp)
    return out[:n, :N]


def quantize_variables(variables: Dict[str, Any]) -> Dict[str, Any]:
    """The int8 twin of a variables tree: every layer params dict holding a
    float ``kernel`` gets int8 codes plus a ``kernel_scale`` leaf; biases,
    BN leaves, and all state stay float32 (they are O(channels), not
    O(channels^2) — quantizing them saves nothing and costs accuracy).
    The presence of ``kernel_scale`` is what the layer apply fns dispatch
    on (dnn/network.py)."""

    def walk(tree):
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                out[k] = walk(v)
            else:
                out[k] = v
        if (
            "kernel" in out
            and not isinstance(out["kernel"], dict)
            and np.asarray(out["kernel"]).dtype.kind == "f"
        ):
            q, scale = quantize_per_channel(np.asarray(out["kernel"]))
            out["kernel"] = q
            out["kernel_scale"] = scale
        return out

    return {
        "params": walk(variables.get("params", {})),
        "state": variables.get("state", {}),
    }
