"""dnn — spec-driven functional neural networks for TPU.

Replaces the reference's CNTK backend (SURVEY.md §2.2 cntk-model/cntk-train):
the protobuf BrainScript graph becomes a JSON layer spec, the JNI eval
becomes a jit-compiled pure function, and `layerNames`-style truncation
(ImageFeaturizer.scala:129-177 `cutOutputLayers`) becomes `Network.truncate`.

Everything is MXU-shaped: NHWC convs via lax.conv_general_dilated, matmuls in
a configurable compute dtype (bfloat16 on TPU), static shapes throughout.
"""

from mmlspark_tpu.dnn.network import LAYER_KINDS, Network, layer
from mmlspark_tpu.dnn.resnet import (
    mlp,
    resnet18,
    resnet20_cifar,
    resnet34,
    resnet50,
    resnet_imagenet,
    resnet_mini,
)

__all__ = [
    "LAYER_KINDS",
    "Network",
    "layer",
    "mlp",
    "resnet18",
    "resnet20_cifar",
    "resnet34",
    "resnet50",
    "resnet_imagenet",
    "resnet_mini",
]
