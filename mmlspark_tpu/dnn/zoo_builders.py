"""Deterministic factories for builder-backed zoo entries.

Each factory returns a NetworkBundle built from a fixed numpy seed
(network.deterministic_variables), so the downloader can rebuild the exact
bytes — and verify the MANIFEST-pinned sha256 — on any backend. This stands
in for the reference's CDN-hosted CNTK checkpoints
(ModelDownloader.scala:209-267): zero-egress builds can't download, so the
zoo pins recipes instead of blobs.
"""

from __future__ import annotations

from typing import Sequence

from mmlspark_tpu.dnn.network import NetworkBundle, deterministic_variables
from mmlspark_tpu.dnn.resnet import resnet50


def resnet50_random(
    num_classes: int = 1000,
    input_shape: Sequence[int] = (224, 224, 3),
    seed: int = 0,
) -> NetworkBundle:
    """Randomly-initialized ResNet-50 (ImageNet geometry, ~25.5M params).

    Random weights are fine for the featurization/serving benches and the
    transfer-learning plumbing (random conv features are still a usable
    embedding); a trained checkpoint would drop in through the same entry.
    """
    net = resnet50(num_classes=num_classes, input_shape=tuple(input_shape))
    return NetworkBundle(net, deterministic_variables(net, seed))
