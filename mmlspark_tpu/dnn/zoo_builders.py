"""Deterministic factories for builder-backed zoo entries.

Each factory returns a NetworkBundle built from a fixed numpy seed
(network.deterministic_variables), so the downloader can rebuild the exact
bytes — and verify the MANIFEST-pinned sha256 — on any backend. This stands
in for the reference's CDN-hosted CNTK checkpoints
(ModelDownloader.scala:209-267): zero-egress builds can't download, so the
zoo pins recipes instead of blobs.

Inference dtype variants: every zoo model can be scored in bfloat16 — half
the MXU cycle cost per MAC on TPU — either per stage (`TPUModel(dtype=
"bfloat16")`, which shares the bundle's one weight upload and just compiles
a second program) or as a bundle-level twin (`bf16_variant`, for callers
that hold bundles, e.g. serving model registries). Weights stay float32 in
HBM either way; layers cast per-op (Network._cast_in / .astype(x.dtype)).
Parity is gated, not assumed: bf16 logits must match f32 within
`BF16_LOGIT_MAE_TOL` relative mean-absolute-error and agree on top-1 for
the smoke batch (tests/test_image_dataplane.py, bench.run_image_prep_smoke).
`dtype="float32"` remains the rollback default everywhere.
"""

from __future__ import annotations

from typing import Sequence

from mmlspark_tpu.dnn.network import (
    Network,
    NetworkBundle,
    deterministic_variables,
)
from mmlspark_tpu.dnn.resnet import resnet50

#: Documented bf16-vs-f32 parity tolerance: RELATIVE mean absolute logit
#: error — mean|f32 - bf16| / mean|f32| — on a smoke batch must stay under
#: this bound, and top-1 must match exactly. bf16 carries 8 mantissa bits
#: (~4e-3 relative rounding per op); 5e-2 bounds the drift compounded
#: across a ResNet-50's depth while still catching real numeric bugs (a
#: wrong accumulation dtype shows up orders of magnitude above this).
#: Relative, not absolute: logit SCALE is model-dependent (a random-init
#: zoo ResNet-50's un-adapted BN leaves logits at O(1e4)).
BF16_LOGIT_MAE_TOL = 5e-2

#: Documented int8-vs-f32 parity tolerance, same RELATIVE mean-absolute
#: logit error measure as the bf16 gate (and the same exact-top-1
#: requirement). Per-channel symmetric weight codes carry <= scale/2 =
#: max|w_c|/254 absolute error per weight (~0.4% of the channel's peak);
#: activations stay f32, so the only drift is quantization noise
#: compounded across depth. 1e-1 bounds that for a ResNet-50 while still
#: catching real bugs (a lost scale factor or a wrong channel axis throws
#: logits off by orders of magnitude, not percent).
INT8_LOGIT_MAE_TOL = 1e-1


def resnet50_random(
    num_classes: int = 1000,
    input_shape: Sequence[int] = (224, 224, 3),
    seed: int = 0,
    dtype: str = "float32",
) -> NetworkBundle:
    """Randomly-initialized ResNet-50 (ImageNet geometry, ~25.5M params).

    Random weights are fine for the featurization/serving benches and the
    transfer-learning plumbing (random conv features are still a usable
    embedding); a trained checkpoint would drop in through the same entry.

    `dtype="bfloat16"` returns the bf16 inference variant: identical
    variables (deterministic_variables depends only on leaf shapes, so the
    MANIFEST sha256 is dtype-independent), bf16 compute.
    """
    net = resnet50(num_classes=num_classes, input_shape=tuple(input_shape))
    if dtype == "int8":
        # quantized twin of the f32 recipe: same deterministic draw, then
        # per-channel weight codes (the MANIFEST pins the f32 recipe;
        # int8 is derived, like bf16 is)
        return int8_variant(
            NetworkBundle(net, deterministic_variables(net, seed))
        )
    if dtype != net.compute_dtype:
        net = Network(net.spec, net.input_shape, dtype)
    return NetworkBundle(net, deterministic_variables(net, seed))


def bf16_variant(bundle: NetworkBundle) -> NetworkBundle:
    """The bfloat16 inference twin of an existing bundle: shares the SAME
    variables dict (weights stay float32; layers cast activations per-op),
    swaps only the network's compute dtype. Note the twin is a distinct
    bundle, so it pays its own one-time weight upload — stages that should
    share the upload use `TPUModel(dtype="bfloat16")` on the original
    bundle instead."""
    net = bundle.network
    if net.compute_dtype == "bfloat16":
        return bundle
    return NetworkBundle(
        Network(net.spec, net.input_shape, "bfloat16"), bundle.variables
    )


def int8_variant(bundle: NetworkBundle) -> NetworkBundle:
    """The int8 weight-only inference twin of an existing bundle: every
    kernel leaf becomes per-channel int8 codes + a ``kernel_scale`` row
    (dnn/quant.py), compute stays float32 (activations are never
    quantized). Unlike `bf16_variant` the VARIABLES differ too, so the
    twin holds — and uploads — its own quantized tree (a quarter of the
    f32 kernel bytes). Parity vs the f32 parent is gated at
    `INT8_LOGIT_MAE_TOL` relative logit MAE with exact top-1 agreement,
    mirroring the bf16 gate; stages that only need cheaper MACs (not a
    smaller resident model) should prefer `bf16_variant`."""
    from mmlspark_tpu.dnn.quant import quantize_variables

    net = bundle.network
    if net.compute_dtype == "int8":
        return bundle
    return NetworkBundle(
        Network(net.spec, net.input_shape, "int8"),
        quantize_variables(bundle.variables),
    )
