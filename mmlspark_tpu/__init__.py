"""mmlspark_tpu — a TPU-native ML framework with the capabilities of MMLSpark.

A brand-new, TPU-first re-imagining of MMLSpark (Microsoft ML for Apache Spark):
the Estimator/Transformer pipeline surface, distributed LightGBM-style gradient
boosting, deep-network batch inference and featurization, image transforms,
auto-featurization / AutoML utilities, SAR recommendations, HTTP integration and
model serving — all built on JAX/XLA/Pallas/pjit instead of CNTK, LightGBM C++
and OpenCV native backends.

Reference layer map: /root/reference (see SURVEY.md). The compute path is
JAX on TPU (MXU matmuls in bfloat16, Pallas kernels for histogram ops, psum
over ICI for data-parallel reductions); the runtime around it is Python + a
C++ data-plane extension.
"""

__version__ = "0.2.0"

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.core.pipeline import (
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Transformer,
)
from mmlspark_tpu.core.params import Param, Params

__all__ = [
    "DataFrame",
    "DataType",
    "Estimator",
    "Model",
    "Param",
    "Params",
    "Pipeline",
    "PipelineModel",
    "PipelineStage",
    "Transformer",
]
