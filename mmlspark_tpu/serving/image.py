"""Image scoring over HTTP: the fused device prep path behind a staged
serving handler.

`ImageServingHandler` is the image-tier `PipelineServingHandler`: requests
carry either base64-encoded image bytes (``{"image": "<b64 jpeg/png/npy>"}``)
or a nested pixel array (``{"pixels": [[[...]]]}`` — HWC uint8-ranged, BGR
like every image column). The three stages split exactly along the PR 4
contract:

- **parse** (thread pool, no lock): base64 + image decode — inherently
  host work — then ragged decode shapes host-resize grouped by shape
  (ops.resize_groups: one resize_batch per distinct source shape, never a
  per-row loop) and the uniform uint8 batch goes through
  `device_ops.prep_image_batch`: ONE h2d upload, one fused XLA
  resize/unroll program, a device-backed "unrolled" column. Rows that fail
  to decode get a zero-image placeholder plus a MALFORMED_COL marker.
- **score** (model lock): TPUModel dispatch only — the input column is
  already device-resident, so the critical section moves zero bytes over
  the host link (the same transfer-guard discipline bench.run_serving_smoke
  gates).
- **reply** (thread pool): the one d2h sync + JSON serialization via
  make_reply.

``dtype="bfloat16"`` flips the inner TPUModel to the bf16 program (shared
weight upload, half MXU cycle cost; parity gated by the zoo bf16 tests).
"""

from __future__ import annotations

import base64
import binascii
from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.dnn.network import NetworkBundle
from mmlspark_tpu.models.tpu_model import TPUModel
from mmlspark_tpu.serving.server import (
    MALFORMED_COL,
    StagedServingHandler,
    make_reply,
)

UNROLLED_COL = "unrolled"


class ImageServingHandler(StagedServingHandler):
    """Serve a zoo/network bundle over image requests through the fused
    device prep path (one upload + one XLA prep program per batch).

    Parameters
    ----------
    bundle: the NetworkBundle to score; its `input_shape` (H, W, C) is the
        prep target every request is resized to.
    value_col / id_col: output column / request-id column names.
    output_layer: named layer to fetch (headless featurization), optional.
    mini_batch_size: rows per device dispatch of the inner TPUModel.
    dtype: "bfloat16" / "float32" override; None (default) inherits the
        bundle network's own compute dtype.
    """

    def __init__(
        self,
        bundle: NetworkBundle,
        value_col: str = "scored",
        id_col: str = "id",
        output_layer: Optional[str] = None,
        mini_batch_size: int = 64,
        dtype: Optional[str] = None,
    ):
        self.bundle = bundle
        self.value_col = value_col
        self.id_col = id_col
        self.in_shape = tuple(int(d) for d in bundle.network.input_shape)
        if len(self.in_shape) != 3:
            raise ValueError(
                f"ImageServingHandler needs an image network (H, W, C) "
                f"input, got {self.in_shape}"
            )
        self.model = TPUModel(
            bundle,
            input_col=UNROLLED_COL,
            output_col=value_col,
            mini_batch_size=mini_batch_size,
            dtype=dtype,
        )
        if output_layer:
            self.model.set_output_layer(output_layer)

    # -- per-row host decode (the one inherently-host step) -------------------

    def _decode_row(self, obj: Any) -> Any:
        """Request JSON object -> HWC uint8 ndarray, or an error string."""
        from mmlspark_tpu.io.image import DECODE_ERRORS, decode_image

        if not isinstance(obj, dict):
            return "request body must be a JSON object"
        if obj.get("image") is not None:
            try:
                raw = base64.b64decode(obj["image"], validate=True)
                img = np.asarray(decode_image(raw)["data"])
            except (binascii.Error, TypeError, *DECODE_ERRORS) as e:
                return f"field 'image': undecodable ({e})"
        elif obj.get("pixels") is not None:
            try:
                img = np.asarray(obj["pixels"], np.float64)
            except (TypeError, ValueError):
                return "field 'pixels': not a numeric array"
            if img.ndim == 2:
                img = img[:, :, None]
            if img.ndim != 3:
                return f"field 'pixels': expected HWC array, got ndim={img.ndim}"
            img = np.clip(np.rint(img), 0, 255).astype(np.uint8)
        else:
            return "need field 'image' (base64 bytes) or 'pixels' (HWC array)"
        if img.ndim == 2:
            img = img[:, :, None]
        h, w, c = self.in_shape
        if img.shape[2] != c:
            if img.shape[2] == 4 and c == 3:  # drop alpha
                img = img[:, :, :3]
            elif img.shape[2] == 1 and c == 3:  # gray -> 3-plane
                img = np.repeat(img, 3, axis=2)
            else:
                return (
                    f"image has {img.shape[2]} channels, model wants {c}"
                )
        return img

    # -- staged contract -------------------------------------------------------

    def parse(self, df: DataFrame) -> DataFrame:
        import json

        from mmlspark_tpu.images import device_ops

        requests = list(df.column("request").values)
        ids = df.column(self.id_col).values
        h, w, c = self.in_shape
        if not requests:
            out = DataFrame.from_dict({self.id_col: np.asarray(ids, object)})
            return out.with_column(
                UNROLLED_COL, np.zeros((0, h * w * c), np.float32),
                DataType.VECTOR,
            )
        errors: List[Optional[str]] = [None] * len(requests)
        imgs: List[Optional[np.ndarray]] = []
        for i, r in enumerate(requests):
            body = r.entity.string_content if r and r.entity else ""
            try:
                obj = json.loads(body) if body else {}
            except json.JSONDecodeError:
                obj = None
            decoded = self._decode_row(obj)
            if isinstance(decoded, str):
                errors[i] = decoded
                imgs.append(None)
            else:
                imgs.append(decoded)
        # malformed rows ride along as zero images (placeholder rows keep
        # the batch rectangular; make_reply turns their markers into 400s)
        filled = [
            im if im is not None else np.zeros((h, w, c), np.uint8)
            for im in imgs
        ]
        # shared uniform/ragged dispatch: one upload + the fused unroll
        # program, row count padded to a power-of-two bucket so the
        # coalescer's many distinct batch sizes reuse a handful of compiled
        # programs instead of tracing per exact N; cannot return None
        # because _decode_row pinned every row (and every placeholder) to
        # the model's channel count c
        dev, meta = device_ops.fused_unrolled_batch(
            filled, size=(h, w), pad_to_bucket=True
        )
        out = DataFrame.from_dict({self.id_col: np.asarray(ids, object)})
        out = out.with_column(UNROLLED_COL, dev, DataType.VECTOR, metadata=meta)
        if any(e is not None for e in errors):
            marker = np.empty(len(errors), object)
            marker[:] = errors
            out = out.with_column(MALFORMED_COL, marker)
        return out

    def score(self, df: DataFrame) -> DataFrame:
        return self.model.transform(df)

    def reply(self, df: DataFrame) -> DataFrame:
        return make_reply(df, self.value_col)
