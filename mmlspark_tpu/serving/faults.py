"""Fault injection for the serving fabric: kill/wedge/slow workers and
drop/delay gateway<->worker connections, deterministically.

The harness has two layers, matching where real faults happen:

- **Worker faults**: `kill_worker` closes the worker's listening socket
  (no drain, no goodbye — the moral equivalent of `kill -9` on a peer
  host) AND poisons the gateway transport for that slot so established
  keep-alive connections fail with ECONNREFUSED too — in-process workers'
  per-connection threads outlive `server_close()`, so the poison is what
  makes the kill behave like a dead remote host end to end. The worker
  object stays around so tests can assert its engine state and
  `DistributedServingServer.stop()` stays idempotent; a killed worker is
  not resurrected by `heal` — use `replace_worker`.
- **Transport faults** intercept the gateway's forward path
  (`DistributedServingServer` consults `FaultInjector.intercept` before
  each connection use): `wedge_worker` makes every forward block for the
  gateway's per-worker timeout then raise the same `socket.timeout` a real
  unresponsive peer produces; `slow_worker` delays forwards; `drop_
  connections` fails the next N forwards with `ConnectionError`. These are
  deterministic — no real socket needs to hang for the breaker/retry state
  machine to be exercised — and the raised exception types are exactly the
  ones the real transport produces, so the gateway code under test cannot
  tell the difference.

Used by tests/test_fabric_faults.py and bench.run_fault_smoke
(BENCH_pr06.json): the acceptance gate "kill 1 of 4 workers under load ->
error rate < 1%, recovery < 500 ms" runs through this harness.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, Optional, Tuple

from mmlspark_tpu.obs.logging import get_logger

log = get_logger("mmlspark_tpu.serving")


class FaultInjector:
    """Deterministic fault state consulted by the gateway per forward.

    One injector per DistributedServingServer (pass as `fault_injector=`
    or call `server.inject_faults()`). Thread-safe: gateway handler threads
    read the mode map under a lock; tests mutate it from outside."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # idx -> ("wedged", None) | ("slow", delay_s) | ("drop", n_left)
        self._modes: Dict[int, Tuple[str, Optional[float]]] = {}

    # -- worker faults ---------------------------------------------------------

    def kill_worker(self, server: "object", idx: int) -> None:
        """Kill worker idx: close its listening socket (new connections
        refuse) AND poison the transport so the gateway's ESTABLISHED
        keep-alive connections fail like a dead host's would. The second
        half matters: ThreadingHTTPServer's per-connection threads outlive
        server_close(), so without the transport poison a 'killed'
        in-process worker would keep answering over cached connections —
        masking the very failover path the kill is supposed to exercise.
        The worker's health() flips to unhealthy immediately."""
        worker = server.workers[idx]
        httpd = worker._httpd
        if httpd is not None:
            worker._httpd = None  # health() reports not-started IMMEDIATELY
            httpd.shutdown()
            httpd.server_close()
        with self._lock:
            self._modes[idx] = ("dead", None)
        log.info("fault_injected", fault="kill_worker", worker=idx,
                 port=worker.port)

    # -- transport faults ------------------------------------------------------

    def wedge_worker(self, idx: int) -> None:
        """Every forward to idx blocks for the gateway's worker timeout and
        then raises socket.timeout — an accepted-but-never-answered peer."""
        with self._lock:
            self._modes[idx] = ("wedged", None)

    def slow_worker(self, idx: int, delay_s: float) -> None:
        """Every forward to idx is delayed by delay_s, then proceeds."""
        with self._lock:
            self._modes[idx] = ("slow", float(delay_s))

    def drop_connections(self, idx: int, n: int = 1) -> None:
        """The next n forwards to idx fail with ConnectionError."""
        with self._lock:
            self._modes[idx] = ("drop", float(n))

    def heal(self, idx: Optional[int] = None) -> None:
        """Clear transport faults for one worker (or all)."""
        with self._lock:
            if idx is None:
                self._modes.clear()
            else:
                self._modes.pop(idx, None)

    def mode(self, idx: int) -> Optional[str]:
        with self._lock:
            entry = self._modes.get(idx)
            return entry[0] if entry else None

    # -- the gateway hook ------------------------------------------------------

    def intercept(self, idx: int, worker_timeout: float) -> None:
        """Called by the gateway before forwarding to worker idx. Raises
        the fault's exception (the same types the real transport produces)
        or returns after the configured delay."""
        with self._lock:
            entry = self._modes.get(idx)
            if entry is None:
                return
            kind, arg = entry
            if kind == "drop":
                left = (arg or 0) - 1
                if left <= 0:
                    self._modes.pop(idx, None)
                else:
                    self._modes[idx] = ("drop", left)
        if kind == "dead":
            raise ConnectionRefusedError(
                f"fault: worker {idx} is dead"
            )
        if kind == "drop":
            raise ConnectionError(f"fault: dropped connection to worker {idx}")
        if kind == "wedged":
            time.sleep(worker_timeout)
            raise socket.timeout(f"fault: worker {idx} wedged")
        if kind == "slow":
            time.sleep(arg or 0.0)
