"""Spark Serving, TPU-native: turn a fitted pipeline into a web service.

Reference: src/io/http Spark Serving — streaming sources/sinks that ARE web
servers (HTTPSource.scala:46,184; DistributedHTTPSource.scala:89-242;
continuous "1 ms" path HTTPSourceV2.scala:63-404) plus the
parseRequest/makeReply sugar (ServingImplicits.scala:90-109).

TPU-first redesign: the reference needs a streaming engine to shuttle
request batches from per-executor JVM web servers through the pipeline and
a sink to route replies back by (requestId, partitionId). In this runtime
one process owns the chip, so the whole apparatus collapses into a resident
server: requests enqueue into an exchange registry, an engine thread runs
the fitted (jit-compiled, device-resident) pipeline over micro-batches, and
replies complete the held exchanges. Continuous mode short-circuits the
queue — the handler thread scores synchronously against the resident model
for minimum latency. No offsets, no epochs, no port forwarding.
"""

from mmlspark_tpu.serving.server import (
    MALFORMED_COL,
    PipelineServingHandler,
    ServingServer,
    StagedServingHandler,
    as_staged_handler,
    make_reply,
    parse_request,
    serve_pipeline,
)
from mmlspark_tpu.serving.distributed import DistributedServingServer
from mmlspark_tpu.serving.fabric import (
    AdmissionController,
    CircuitBreaker,
    FabricConfig,
    RetryBudget,
    ServingFabric,
)
from mmlspark_tpu.serving.faults import FaultInjector
from mmlspark_tpu.serving.image import ImageServingHandler

__all__ = [
    "ImageServingHandler",
    "AdmissionController",
    "CircuitBreaker",
    "DistributedServingServer",
    "FabricConfig",
    "FaultInjector",
    "MALFORMED_COL",
    "RetryBudget",
    "ServingFabric",
    "PipelineServingHandler",
    "ServingServer",
    "StagedServingHandler",
    "as_staged_handler",
    "make_reply",
    "parse_request",
    "serve_pipeline",
]
