"""Fault-tolerant serving fabric: the gateway's routing/retry/admission brain.

Reference: the Spark Serving gateway survives executor churn because the
driver only routes to partitions that are alive (DistributedHTTPSource.scala
keeps a per-partition server registry; PortForwarding.scala fronts it) — a
dead executor simply stops being a routing target. Our worker pool needs the
same property without a driver: the gateway itself must observe worker
health and route around failures.

This module is the policy layer `DistributedServingServer` routes through
(serving/distributed.py). It is transport-agnostic — nothing here opens a
socket — so every policy is unit-testable with a fake clock and the
fault-injection harness (serving/faults.py) can exercise the whole state
machine deterministically. Four cooperating pieces:

- **HealthRouter** (inside `ServingFabric`): power-of-two-choices over the
  healthy worker set. Candidate one comes from a rotation counter (so an
  idle pool degenerates to exact round-robin — deterministic, and every
  worker stays warm), candidate two is sampled; the pick is the lower
  (in_flight, EWMA latency) score. Health is the AND of three signals: the
  worker's own PR 5 ``health()`` (dead engine threads, stopping), the
  circuit breaker (transport-level failures the in-process health can't
  see), and the drain flag.
- **CircuitBreaker**: per-worker closed -> open -> half-open. `failure_
  threshold` consecutive transport failures open it (no routes); after
  `open_secs` it admits ONE in-flight probe request at a time; `probe_
  successes` consecutive probe wins close it, any probe loss re-opens.
- **RetryBudget**: a token bucket funded by primary requests (`ratio`
  tokens per request, capped) and spent by retries/hedges — the classic
  guard against retry amplification: at most ~`ratio` of offered load can
  become retry load, so retries can never turn an overload into a storm.
- **AdmissionController**: an AIMD concurrency limit at the gateway edge.
  Admissions above the limit shed immediately (429 + Retry-After) instead
  of queueing toward the request timeout; completions grow the limit
  additively (~+1 per `limit` completions), overload signals (worker
  timeouts/503s, or latency above `latency_target_ms` when set) shrink it
  multiplicatively, at most once per `adjust_interval_s`.

Everything observable lands in the obs registry (docs/observability.md):
`serving_shed_requests_total{reason}`, `serving_fabric_retries_total{kind}`,
`serving_breaker_transitions_total{to}`, `serving_fabric_failures_total`,
and a scrape-time `serving_admission_limit{gateway}` gauge; `snapshot()`
is the router block `GET /healthz` serves.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from mmlspark_tpu.obs.logging import get_logger
from mmlspark_tpu.obs import registry as obs_registry
from mmlspark_tpu.obs.metrics import QuantileSketch

log = get_logger("mmlspark_tpu.serving")

#: per-process fabric sequence — the `gateway` metric label must be unique
#: per instance so two gateways in one process never merge their series
_FABRIC_SEQ = itertools.count()


@dataclass
class FabricConfig:
    """Tuning knobs for the serving fabric. Defaults are production-shaped:
    generous admission ceiling (tests and small deployments never shed),
    small failure threshold (a dead worker is ejected within a few
    requests), sub-second probe cadence (recovery is fast)."""

    # -- circuit breaker
    failure_threshold: int = 3        # consecutive failures -> open
    open_secs: float = 1.0            # open -> half-open delay
    probe_successes: int = 1          # half-open probe wins -> closed
    # -- retry / hedge
    max_retries: int = 3              # attempts beyond the first, per request
    retry_ratio: float = 0.1          # budget tokens funded per primary request
    retry_budget_cap: float = 32.0    # token bucket ceiling
    backoff_base_ms: float = 2.0      # full-jitter exponential base
    backoff_max_ms: float = 50.0
    hedge: bool = False               # tail hedging at p95
    hedge_min_ms: float = 20.0        # never hedge earlier than this
    # -- admission control (AIMD)
    admission_initial: float = 64.0
    admission_min: float = 2.0
    admission_max: float = 1024.0
    decrease_factor: float = 0.7      # multiplicative decrease on overload
    adjust_interval_s: float = 0.1    # at most one decrease per interval
    latency_target_ms: Optional[float] = None  # SLO; None = overload-only
    # -- health cache
    health_interval_s: float = 0.2    # min seconds between health() calls
    # -- EWMA latency
    ewma_alpha: float = 0.2
    # -- drain
    drain_timeout_s: float = 30.0
    # deterministic jitter/sampling (None -> nondeterministic seeding)
    seed: Optional[int] = 0


class CircuitBreaker:
    """closed -> open -> half-open per-worker breaker. Thread-safe; the
    clock is injectable so tests drive transitions without sleeping."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self,
        failure_threshold: int = 3,
        open_secs: float = 1.0,
        probe_successes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str], None]] = None,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.open_secs = open_secs
        self.probe_successes = probe_successes
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0          # consecutive, in closed state
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._probe_wins = 0

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _transition(self, to: str) -> None:
        if self._state != to:
            self._state = to
            if self._on_transition is not None:
                self._on_transition(to)

    def _maybe_half_open(self) -> None:
        # caller holds the lock
        if (
            self._state == self.OPEN
            and self._clock() - self._opened_at >= self.open_secs
        ):
            self._transition(self.HALF_OPEN)
            self._probe_in_flight = False
            self._probe_wins = 0

    def allows(self) -> bool:
        """True when a normal request may route here (closed state only —
        half-open traffic goes through `acquire_probe`)."""
        with self._lock:
            self._maybe_half_open()
            return self._state == self.CLOSED

    def acquire_probe(self) -> bool:
        """Claim the single half-open probe slot. The caller MUST follow
        with record_success/record_failure to release it."""
        with self._lock:
            self._maybe_half_open()
            if self._state != self.HALF_OPEN or self._probe_in_flight:
                return False
            self._probe_in_flight = True
            return True

    def record_success(self) -> None:
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._probe_in_flight = False
                self._probe_wins += 1
                if self._probe_wins >= self.probe_successes:
                    self._transition(self.CLOSED)
                    self._failures = 0
            else:
                self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self._maybe_half_open()
            if self._state == self.HALF_OPEN:
                self._probe_in_flight = False
                self._opened_at = self._clock()
                self._transition(self.OPEN)
            elif self._state == self.CLOSED:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    self._opened_at = self._clock()
                    self._transition(self.OPEN)

    def reset(self) -> None:
        with self._lock:
            self._transition(self.CLOSED)
            self._failures = 0
            self._probe_in_flight = False
            self._probe_wins = 0


class RetryBudget:
    """Token bucket capping retry amplification: primary requests fund
    `ratio` tokens each (up to `cap`), every retry/hedge spends one. Starts
    full so cold-start failovers aren't starved."""

    def __init__(self, ratio: float = 0.1, cap: float = 32.0):
        self.ratio = ratio
        self.cap = cap
        self._lock = threading.Lock()
        self._tokens = cap

    def fund(self) -> None:
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


class AdmissionController:
    """AIMD gateway concurrency limit. `try_acquire` admits or sheds;
    `release` feeds the control loop: overload signals (worker timeout/503,
    or latency above the target when one is set) shrink the limit
    multiplicatively — at most once per `adjust_interval_s`, so one slow
    BATCH doesn't collapse the window — and clean completions grow it by
    ~1 per `limit` completions (classic additive increase)."""

    def __init__(
        self,
        initial: float = 64.0,
        minimum: float = 2.0,
        maximum: float = 1024.0,
        decrease_factor: float = 0.7,
        adjust_interval_s: float = 0.1,
        latency_target_ms: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.minimum = minimum
        self.maximum = maximum
        self.decrease_factor = decrease_factor
        self.adjust_interval_s = adjust_interval_s
        self.latency_target_ms = latency_target_ms
        self._clock = clock
        self._lock = threading.Lock()
        self._limit = float(min(max(initial, minimum), maximum))
        self._in_flight = 0
        self._last_decrease = float("-inf")

    @property
    def limit(self) -> float:
        with self._lock:
            return self._limit

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    def try_acquire(self) -> bool:
        with self._lock:
            if self._in_flight >= int(self._limit):
                return False
            self._in_flight += 1
            return True

    def release(self, latency_ms: float, overloaded: bool = False) -> None:
        with self._lock:
            self._in_flight = max(0, self._in_flight - 1)
            slow = (
                self.latency_target_ms is not None
                and latency_ms > self.latency_target_ms
            )
            if overloaded or slow:
                now = self._clock()
                if now - self._last_decrease >= self.adjust_interval_s:
                    self._last_decrease = now
                    self._limit = max(
                        self.minimum, self._limit * self.decrease_factor
                    )
            else:
                self._limit = min(self.maximum, self._limit + 1.0 / self._limit)


class _WorkerState:
    """Router-side view of one worker slot: breaker, EWMA latency,
    gateway-tracked in-flight, drain flag, lazily cached health()."""

    __slots__ = (
        "idx", "breaker", "ewma_ms", "in_flight", "draining",
        "health_fn", "_health_ok", "_health_at", "failures_total",
        "unroutable_at",
    )

    def __init__(self, idx: int, breaker: CircuitBreaker,
                 health_fn: Optional[Callable[[], bool]]):
        self.idx = idx
        self.breaker = breaker
        self.ewma_ms: Optional[float] = None
        self.in_flight = 0
        self.draining = False
        self.health_fn = health_fn
        self._health_ok = True
        self._health_at = float("-inf")
        self.failures_total = 0
        # when the router FIRST observed this worker unroutable (health
        # flip or breaker open) — the "routing recovered in X ms" clock
        self.unroutable_at: Optional[float] = None


class ServingFabric:
    """Router + retry budget + admission control, shared by every gateway
    thread. All mutation happens under one small lock; the expensive bits
    (worker health() calls) are rate-limited by `health_interval_s`."""

    def __init__(
        self,
        n_workers: int,
        config: Optional[FabricConfig] = None,
        health_fns: Optional[Sequence[Optional[Callable[[], bool]]]] = None,
        clock: Callable[[], float] = time.monotonic,
        gateway_label: Optional[str] = None,
    ):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.config = config or FabricConfig()
        cfg = self.config
        self._clock = clock
        self._lock = threading.Lock()
        self._rng = random.Random(cfg.seed)
        self._rotation = itertools.count()
        # unique per instance (like ServingServer's engine label): two
        # gateways sharing an api_name must never merge their series
        self.gateway_label = (
            f"{gateway_label or 'gateway'}-{next(_FABRIC_SEQ)}"
        )
        reg = obs_registry()
        self._shed_total = reg.counter(
            "serving_shed_requests_total",
            "Requests shed at the gateway edge instead of queued",
            ("gateway", "reason"),
        )
        self._retries_total = reg.counter(
            "serving_fabric_retries_total",
            "Gateway retry/hedge attempts against a different worker",
            ("gateway", "kind"),
        )
        self._failures_total = reg.counter(
            "serving_fabric_failures_total",
            "Transport-level worker failures observed by the gateway",
            ("gateway", "kind"),
        )
        self._transitions = reg.counter(
            "serving_breaker_transitions_total",
            "Circuit-breaker state transitions across the worker pool",
            ("gateway", "to"),
        )
        self._limit_gauge = reg.gauge(
            "serving_admission_limit",
            "Current AIMD admission concurrency limit at the gateway",
            ("gateway",),
        )
        self._limit_gauge.labels(gateway=self.gateway_label).set_function(
            lambda: self.admission.limit
        )
        self.admission = AdmissionController(
            initial=cfg.admission_initial,
            minimum=cfg.admission_min,
            maximum=cfg.admission_max,
            decrease_factor=cfg.decrease_factor,
            adjust_interval_s=cfg.adjust_interval_s,
            latency_target_ms=cfg.latency_target_ms,
            clock=clock,
        )
        self.retry_budget = RetryBudget(cfg.retry_ratio, cfg.retry_budget_cap)
        self._lat_sketch = QuantileSketch()
        health_fns = health_fns or [None] * n_workers
        self._workers = [
            _WorkerState(i, self._make_breaker(), health_fns[i])
            for i in range(n_workers)
        ]

    def _make_breaker(self) -> CircuitBreaker:
        cfg = self.config
        return CircuitBreaker(
            cfg.failure_threshold, cfg.open_secs, cfg.probe_successes,
            clock=self._clock,
            on_transition=lambda to: self._transitions.labels(
                gateway=self.gateway_label, to=to
            ).inc(),
        )

    # -- health ----------------------------------------------------------------

    def _health_ok(self, w: _WorkerState) -> bool:
        """Cached worker health(), refreshed at most every
        health_interval_s. The in-process health signal catches dead engine
        threads and stopping servers; the breaker catches transport-level
        wedges the in-process view can't see."""
        if w.health_fn is None:
            return True
        now = self._clock()
        if now - w._health_at >= self.config.health_interval_s:
            w._health_at = now
            try:
                w._health_ok = bool(w.health_fn())
            except Exception as e:  # a dead health probe IS unhealthiness
                log.debug("health_probe_failed", worker=w.idx,
                          error=repr(e))
                w._health_ok = False
            if not w._health_ok and w.unroutable_at is None:
                w.unroutable_at = now
        return w._health_ok

    # -- routing ---------------------------------------------------------------

    @staticmethod
    def _better(cand: _WorkerState, base: _WorkerState) -> bool:
        """Is `cand` strictly the better pick? Fewer in-flight wins; on a
        tie, EWMA diverts only when decisively (2x) faster — a strict
        EWMA comparison would herd ALL idle traffic onto whichever worker
        happens to be microseconds ahead, starving the rest (and starving
        the breaker of the probe traffic it needs to observe recovery)."""
        if cand.in_flight != base.in_flight:
            return cand.in_flight < base.in_flight
        if cand.ewma_ms is not None and base.ewma_ms is not None:
            return cand.ewma_ms * 2.0 < base.ewma_ms
        return False

    def pick_and_acquire(
        self, exclude: Sequence[int] = (), probe_ok: bool = True
    ) -> Optional[Tuple[int, bool]]:
        """Choose a worker and reserve one in-flight slot on it atomically
        (so drain() never races an about-to-enter request). Returns
        (worker_idx, is_probe) or None when nothing is routable.

        Selection is power-of-two-choices over the healthy set: candidate
        one rotates deterministically (idle pool == round-robin, every
        worker exercised), candidate two is sampled; fewer in-flight wins,
        with EWMA diverting a tie only on a decisive (2x) latency gap,
        ties to the rotation candidate. A half-open breaker's single probe
        slot is claimed opportunistically so recovered workers rejoin
        without a side channel."""
        excluded = set(exclude)
        with self._lock:
            # opportunistic half-open probe (one in flight per breaker)
            if probe_ok:
                for w in self._workers:
                    if (
                        w.idx not in excluded
                        and not w.draining
                        and self._health_ok(w)
                        and w.breaker.acquire_probe()
                    ):
                        w.in_flight += 1
                        return w.idx, True
            healthy = [
                w for w in self._workers
                if w.idx not in excluded
                and not w.draining
                and w.breaker.allows()
                and self._health_ok(w)
            ]
            if not healthy:
                return None
            if len(healthy) == 1:
                chosen = healthy[0]
            else:
                c1 = healthy[next(self._rotation) % len(healthy)]
                c2 = self._rng.choice([w for w in healthy if w is not c1])
                chosen = c2 if self._better(c2, c1) else c1
            chosen.in_flight += 1
            return chosen.idx, False

    def release(self, idx: int) -> None:
        with self._lock:
            w = self._workers[idx]
            w.in_flight = max(0, w.in_flight - 1)

    def record_success(self, idx: int, latency_ms: float) -> None:
        """A completed forward: feeds the EWMA, the hedge-trigger sketch,
        and the breaker (which internally credits half-open probes)."""
        with self._lock:
            w = self._workers[idx]
            alpha = self.config.ewma_alpha
            w.ewma_ms = (
                latency_ms if w.ewma_ms is None
                else alpha * latency_ms + (1 - alpha) * w.ewma_ms
            )
            self._lat_sketch.add(latency_ms)
            w.breaker.record_success()
            if w.breaker.state == CircuitBreaker.CLOSED and w._health_ok:
                w.unroutable_at = None

    def record_failure(self, idx: int, kind: str = "transport",
                       breaker: bool = True) -> str:
        """A transport-level failure (connect refused, read timeout, worker
        503): counted per kind in `serving_fabric_failures_total`, and fed
        to the breaker so repeated failures eject the worker. `breaker=
        False` records a SOFT signal (counted, visible in /healthz) without
        breaker consequences — the stale-keep-alive rebuild uses it: a
        single stale blip whose same-worker retry succeeds must not eject a
        provably-serving worker, while a rebuild that fails too comes back
        through the hard path. Returns the breaker state AFTER the record,
        so the gateway can attach a breaker-transition span event to the
        request tree that caused it."""
        self._failures_total.labels(
            gateway=self.gateway_label, kind=kind
        ).inc()
        with self._lock:
            w = self._workers[idx]
            w.failures_total += 1
            if breaker:
                w.breaker.record_failure()
                if not w.breaker.allows() and w.unroutable_at is None:
                    w.unroutable_at = self._clock()
            return w.breaker.state

    def breaker_state(self, idx: int) -> str:
        """Worker `idx`'s breaker state (for span attrs on routed attempts)."""
        with self._lock:
            return self._workers[idx].breaker.state

    def unroutable_since(self, idx: int) -> Optional[float]:
        """Monotonic time at which the router first observed worker `idx`
        unroutable (health flip or breaker open); None while routable.
        (clock_kill -> unroutable_since) is the routing-recovery latency
        the fault smoke bench gates on — measured from the router's own
        observations, immune to measurement-thread scheduling."""
        with self._lock:
            return self._workers[idx].unroutable_at

    def routable_workers(self) -> List[int]:
        with self._lock:
            return [
                w.idx for w in self._workers
                if not w.draining and w.breaker.allows() and self._health_ok(w)
            ]

    # -- retry / hedge ---------------------------------------------------------

    def fund_retry_budget(self) -> None:
        self.retry_budget.fund()

    def try_retry(self, kind: str = "retry") -> bool:
        """Spend one retry-budget token; counts the attempt when granted."""
        if not self.retry_budget.try_spend():
            return False
        self._retries_total.labels(gateway=self.gateway_label, kind=kind).inc()
        return True

    def backoff_s(self, attempt: int) -> float:
        """Full-jitter exponential backoff for attempt N (1-based)."""
        cfg = self.config
        cap = min(cfg.backoff_max_ms, cfg.backoff_base_ms * (2 ** (attempt - 1)))
        return self._rng.uniform(0.0, cap) / 1e3

    def hedge_delay_s(self) -> float:
        """Observed p95 forward latency (floored at hedge_min_ms) — the
        tail-hedging trigger. Reads the sketch under the fabric lock:
        QuantileSketch itself is not thread-safe and record_success
        mutates it concurrently."""
        with self._lock:
            p95 = self._lat_sketch.quantile(0.95)
        if p95 != p95:  # NaN: no samples yet
            p95 = 0.0
        return max(self.config.hedge_min_ms, p95) / 1e3

    # -- shedding --------------------------------------------------------------

    def shed(self, reason: str) -> None:
        self._shed_total.labels(
            gateway=self.gateway_label, reason=reason
        ).inc()

    # -- drain / replace -------------------------------------------------------

    def set_draining(self, idx: int, draining: bool) -> None:
        with self._lock:
            self._workers[idx].draining = draining

    def worker_in_flight(self, idx: int) -> int:
        with self._lock:
            return self._workers[idx].in_flight

    def wait_drained(self, idx: int, timeout: Optional[float] = None) -> bool:
        """Block until the gateway has zero in-flight requests on worker
        `idx` (drain flag must already be set so no new ones enter).
        Deliberately wall-clock (time.monotonic, not the injectable test
        clock): it sleeps real time between polls, so pairing its deadline
        with a frozen fake clock would spin forever."""
        deadline = time.monotonic() + (
            timeout if timeout is not None else self.config.drain_timeout_s
        )
        while self.worker_in_flight(idx) > 0:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.002)
        return True

    def reset_worker(
        self, idx: int, health_fn: Optional[Callable[[], bool]] = None
    ) -> None:
        """Fresh state for a replaced worker slot: new breaker, no EWMA
        history, drain flag cleared."""
        with self._lock:
            w = self._workers[idx]
            w.breaker = self._make_breaker()
            w.ewma_ms = None
            w.draining = False
            w.failures_total = 0
            w.unroutable_at = None
            if health_fn is not None:
                w.health_fn = health_fn
            w._health_at = float("-inf")
            w._health_ok = True

    # -- observability ---------------------------------------------------------

    def set_worker_annotator(
        self, fn: Optional[Callable[[int], Dict[str, Any]]]
    ) -> None:
        """Install a per-worker snapshot annotator: `fn(idx)` returns extra
        fields merged into that worker's `snapshot()` entry. The
        distributed gateway uses this to surface federation-scrape
        staleness in the router block — `healthy` already folds staleness
        in through the health_fn, and the annotation says WHY a worker
        with a live socket went unroutable."""
        self._annotator = fn

    def _annotate(self, idx: int) -> Dict[str, Any]:
        fn = getattr(self, "_annotator", None)
        if fn is None:
            return {}
        try:
            extra = fn(idx)
        except Exception as e:  # a broken annotator must not break healthz
            log.debug("snapshot_annotator_failed", worker=idx, error=repr(e))
            return {}
        return dict(extra) if extra else {}

    def snapshot(self) -> Dict[str, Any]:
        """The router block `GET /healthz` serves (docs/observability.md)."""
        with self._lock:
            workers = [
                {
                    "idx": w.idx,
                    "breaker": w.breaker.state,
                    "draining": w.draining,
                    "healthy": (
                        not w.draining
                        and w.breaker.allows()
                        and self._health_ok(w)
                    ),
                    "in_flight": w.in_flight,
                    "ewma_ms": (
                        round(w.ewma_ms, 3) if w.ewma_ms is not None else None
                    ),
                    "failures_total": w.failures_total,
                    **self._annotate(w.idx),
                }
                for w in self._workers
            ]
        return {
            "workers": workers,
            "admission": {
                "limit": round(self.admission.limit, 2),
                "in_flight": self.admission.in_flight,
            },
            "retry_budget_tokens": round(self.retry_budget.tokens, 2),
        }

    def close(self) -> None:
        """Unhook scrape-time callbacks that close over this fabric — the
        process registry must not pin stopped gateways. Cumulative counter
        series (shed/retries/failures/transitions) stay, same policy as
        ServingServer's engine-labelled series: they hold plain floats,
        not object references, and Prometheus counters are supposed to
        survive their source."""
        self._limit_gauge.remove(gateway=self.gateway_label)
