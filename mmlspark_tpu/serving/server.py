"""The serving engine: HTTP front end + micro-batch/continuous scorer.

Semantics matched to the reference (see package docstring):
- input DataFrame schema is [id: {requestId, partitionId}, request:
  HTTPRequestData] (HTTPSourceV2.scala ID_SCHEMA/SCHEMA at :88-99)
- the sink routes each reply row's `reply` HTTPResponseData back to the
  exchange with that requestId (HTTPWriter, HTTPSourceV2.scala:421-476)
- unknown routes get 404; micro-batch requests that outlive
  `request_timeout` get 504; requests pending at shutdown get 503
- `parse_request` / `make_reply` mirror ServingImplicits.scala:90-109

Continuous mode is the reference's "1 ms latency" HTTPSourceProviderV2
path: no batch wait at all — the handler thread calls the pipeline
directly (batch of 1) under a model lock. Scoring runs inline, so
`request_timeout` does not bound a slow model there — it only bounds the
queue wait in micro-batch mode.
"""

from __future__ import annotations

import http.server
import json
import socket
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.config import get_logger
from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.io.http.schema import (
    EntityData,
    HeaderData,
    HTTPRequestData,
    HTTPResponseData,
    ProtocolVersionData,
    RequestLineData,
    StatusLineData,
)

log = get_logger("mmlspark_tpu.serving")


# -- parseRequest / makeReply sugar (ServingImplicits.scala:90-109) -----------


def parse_request(
    df: DataFrame,
    schema: Any = None,
    id_col: str = "id",
    request_col: str = "request",
) -> DataFrame:
    """Explode the JSON request entity into columns.

    schema=None: every key across the batch becomes a column (object dtype).
    schema=bytes: passthrough of the raw entity as a `bytes` column.
    schema={"col": DataType, ...}: select + cast those keys.
    """
    requests: List[Optional[HTTPRequestData]] = list(df.column(request_col).values)
    ids = df.column(id_col).values
    if schema is bytes:
        content = np.empty(len(requests), object)
        content[:] = [r.entity.content if r and r.entity else None for r in requests]
        return DataFrame.from_dict({id_col: ids}).with_column(
            "bytes", content, DataType.BINARY
        )
    parsed: List[dict] = []
    for r in requests:
        body = r.entity.string_content if r and r.entity else ""
        try:
            obj = json.loads(body) if body else {}
        except json.JSONDecodeError:
            obj = {}
        parsed.append(obj if isinstance(obj, dict) else {"value": obj})
    if schema is None:
        keys: List[str] = []
        for p in parsed:
            for k in p:
                if k not in keys:
                    keys.append(k)
        typed = {k: None for k in keys}
    else:
        typed = dict(schema)
    out = DataFrame.from_dict({id_col: np.asarray(ids, object)})
    for k, dtype in typed.items():
        vals = [p.get(k) for p in parsed]
        if dtype is not None and isinstance(dtype, DataType) and dtype.is_numeric:
            arr: Any = np.asarray(
                [np.nan if v is None else v for v in vals], np.float64
            )
            out = out.with_column(k, arr, DataType.DOUBLE)
        elif dtype == DataType.VECTOR:
            arr = np.asarray(vals, np.float64)
            out = out.with_column(k, arr, DataType.VECTOR)
        else:
            arr = np.empty(len(vals), object)
            arr[:] = vals
            out = out.with_column(k, arr)
    return out


def make_reply(df: DataFrame, reply_col: str, name: str = "reply") -> DataFrame:
    """Wrap a column as HTTPResponseData (ServingImplicits.makeReply):
    str -> text entity; bytes -> binary; anything else -> JSON."""
    values = df.column(reply_col).values
    replies = np.empty(len(values), object)
    out: List[HTTPResponseData] = []
    for v in values:
        if isinstance(v, str):
            out.append(HTTPResponseData.ok(v.encode("utf-8"), "text/plain"))
        elif isinstance(v, (bytes, bytearray)):
            out.append(HTTPResponseData.ok(bytes(v), "application/octet-stream"))
        else:
            out.append(
                HTTPResponseData.ok(json.dumps(_to_jsonable(v)).encode("utf-8"))
            )
    replies[:] = out
    return df.with_column(name, replies, DataType.STRUCT)


def _to_jsonable(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, dict):
        return {k: _to_jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_jsonable(x) for x in v]
    return v


# -- the server ---------------------------------------------------------------


class _Exchange:
    """One held HTTP exchange awaiting its reply (the reference keeps the
    com.sun HttpExchange open in MultiChannelMap / the partition reader)."""

    __slots__ = ("request", "event", "response")

    def __init__(self, request: HTTPRequestData):
        self.request = request
        self.event = threading.Event()
        self.response: Optional[HTTPResponseData] = None

    def respond(self, response: HTTPResponseData) -> None:
        self.response = response
        self.event.set()


class ServingServer:
    """Serve `handler(df) -> df` over HTTP.

    handler receives the [id, request] DataFrame and must return a frame
    containing `id` and a reply column of HTTPResponseData (usually built
    with parse_request/make_reply around a fitted PipelineModel).

    mode="continuous": score per-request in the handler thread (lowest
    latency — the reference's HTTPSourceProviderV2 path).
    mode="micro_batch": queue up to max_batch_size requests (waiting at most
    max_wait_ms) and score them in one pipeline call (DistributedHTTPSource
    batch path) — higher throughput per chip, a little more latency.
    """

    def __init__(
        self,
        handler: Callable[[DataFrame], DataFrame],
        host: str = "127.0.0.1",
        port: int = 0,
        api_name: str = "serving",
        mode: str = "continuous",
        max_batch_size: int = 64,
        max_wait_ms: float = 5.0,
        reply_col: str = "reply",
        request_timeout: float = 30.0,
    ):
        if mode not in ("continuous", "micro_batch"):
            raise ValueError("mode must be 'continuous' or 'micro_batch'")
        self.handler = handler
        self.host = host
        self.api_name = api_name
        self.mode = mode
        self.max_batch_size = max_batch_size
        self.max_wait_ms = max_wait_ms
        self.reply_col = reply_col
        self.request_timeout = request_timeout
        self._queue: List[tuple] = []
        self._queue_lock = threading.Condition()
        self._model_lock = threading.Lock()
        # per-request stage decomposition of the micro-batch path (round-5
        # verdict item 8: explain the p99 tail with data, don't guess):
        # queue_wait | lock_wait | handler, bounded ring
        self.stage_timings: List[Dict[str, float]] = []
        self._stage_cap = 4096
        self._stage_pos = 0
        self._stopping = threading.Event()
        self._engine_thread: Optional[threading.Thread] = None
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._port = port

    # - wiring ---------------------------------------------------------------

    @property
    def port(self) -> int:
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self._port}/{self.api_name}"

    def start(self) -> "ServingServer":
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # small header+body writes otherwise hit Nagle + delayed-ACK
            # (~40 ms per exchange) — fatal for the 1 ms latency target
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # route into our logger
                log.debug("%s " + fmt, self.address_string(), *args)

            def _read_request(self) -> HTTPRequestData:
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                ct = self.headers.get("Content-Type")
                return HTTPRequestData(
                    RequestLineData(self.command, self.path),
                    [HeaderData(k, v) for k, v in self.headers.items()],
                    EntityData(
                        content=body,
                        content_length=len(body),
                        content_type=HeaderData("Content-Type", ct) if ct else None,
                    ),
                )

            def _send(self, resp: HTTPResponseData) -> None:
                body = resp.entity.content if resp.entity else b""
                self.send_response(
                    resp.status_line.status_code, resp.status_line.reason_phrase
                )
                ct = None
                if resp.entity and resp.entity.content_type:
                    ct = resp.entity.content_type.value
                self.send_header("Content-Type", ct or "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                route = self.path.split("?", 1)[0].rstrip("/")
                if route != f"/{outer.api_name}":
                    self._send(_status(404, "Not Found"))
                    return
                exchange = _Exchange(self._read_request())
                if outer.mode == "continuous":
                    outer._score_now(exchange)
                else:
                    with outer._queue_lock:
                        outer._queue.append(
                            (str(uuid.uuid4()), exchange, time.monotonic())
                        )
                        outer._queue_lock.notify()
                if not exchange.event.wait(outer.request_timeout):
                    self._send(_status(504, "Gateway Timeout"))
                    return
                self._send(exchange.response)

            do_GET = do_POST
            do_PUT = do_POST

        self._httpd = http.server.ThreadingHTTPServer(
            (self.host, self._port), Handler
        )
        self._httpd.daemon_threads = True
        self._port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        if self.mode == "micro_batch":
            self._engine_thread = threading.Thread(target=self._engine_loop, daemon=True)
            self._engine_thread.start()
        log.info("serving %s (%s mode)", self.url, self.mode)
        return self

    def stop(self) -> None:
        self._stopping.set()
        with self._queue_lock:
            pending = self._queue
            self._queue = []
            self._queue_lock.notify_all()
        for _, ex, _t in pending:
            ex.respond(_status(503, "Service Unavailable"))
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # - scoring --------------------------------------------------------------

    def _run_batch(self, ids: List[str], exchanges: List[_Exchange]) -> None:
        id_vals = np.empty(len(ids), object)
        id_vals[:] = [{"requestId": rid, "partitionId": 0} for rid in ids]
        reqs = np.empty(len(exchanges), object)
        reqs[:] = [ex.request for ex in exchanges]
        df = DataFrame.from_dict(
            {"id": id_vals, "request": reqs},
            types={"id": DataType.STRUCT, "request": DataType.STRUCT},
        )
        by_id = dict(zip(ids, exchanges))
        try:
            out = self.handler(df)
            out_ids = out.column("id").values
            replies = out.column(self.reply_col).values
            for row_id, reply in zip(out_ids, replies):
                rid = row_id["requestId"] if isinstance(row_id, dict) else str(row_id)
                ex = by_id.pop(rid, None)
                if ex is not None:
                    ex.respond(reply if reply is not None else _status(500, "No reply"))
        except Exception as e:  # surface pipeline errors as 500s, keep serving
            log.exception("handler failed")
            for ex in by_id.values():
                ex.respond(
                    _status(500, "Internal Server Error", repr(e).encode("utf-8"))
                )
            return
        for ex in by_id.values():  # rows the handler dropped
            ex.respond(_status(500, "No reply produced"))

    def stage_summary(self) -> Dict[str, float]:
        """p50/p99 decomposition of the recorded micro-batch stage timings
        (queue wait vs lock wait vs handler run) — the evidence base for
        attributing tail latency (BASELINE.md serving section). Also carries
        mean host<->device transfer counts per scored batch (the dataplane
        hot-path metric: a device-resident handler pipeline should show
        exactly one h2d for the request features and one d2h for the reply
        sync — anything more is a stage boundary leaking through host).
        The counters are process-wide, so per-batch attribution is exact
        only while this server is the sole device user; under concurrent
        engines treat these as an upper bound."""
        if not self.stage_timings:
            return {}
        out: Dict[str, float] = {}
        for key in ("queue_wait_ms", "lock_wait_ms", "handler_ms"):
            vals = sorted(t[key] for t in self.stage_timings)
            out[f"{key}_p50"] = round(vals[len(vals) // 2], 3)
            out[f"{key}_p99"] = round(vals[int(len(vals) * 0.99)], 3)
        out["mean_batch_size"] = round(
            float(np.mean([t["batch_size"] for t in self.stage_timings])), 2
        )
        for key in ("h2d_transfers", "d2h_transfers"):
            per_batch = [t[key] for t in self.stage_timings if key in t]
            if per_batch:
                out[f"mean_{key}_per_batch"] = round(float(np.mean(per_batch)), 2)
        out["n_sampled"] = float(len(self.stage_timings))
        return out

    def _score_now(self, exchange: _Exchange) -> None:
        with self._model_lock:
            self._run_batch([str(uuid.uuid4())], [exchange])

    def _engine_loop(self) -> None:
        while not self._stopping.is_set():
            with self._queue_lock:
                if not self._queue:
                    self._queue_lock.wait(0.05)
                    continue
                deadline = time.monotonic() + self.max_wait_ms / 1000.0
                while (
                    len(self._queue) < self.max_batch_size
                    and time.monotonic() < deadline
                    and not self._stopping.is_set()
                ):
                    self._queue_lock.wait(max(0.0, deadline - time.monotonic()))
                # Requests whose client already got a 504 are dead — scoring
                # them would burn batch slots and model-lock time on replies
                # nobody reads.
                cutoff = time.monotonic() - self.request_timeout
                self._queue = [e for e in self._queue if e[2] > cutoff]
                batch = self._queue[: self.max_batch_size]
                self._queue = self._queue[self.max_batch_size:]
            if batch:
                from mmlspark_tpu.utils.profiling import dataplane_counters

                counters = dataplane_counters()
                ids = [rid for rid, _, _t in batch]
                exchanges = [ex for _, ex, _t in batch]
                t_assembled = time.monotonic()
                with self._model_lock:
                    t_locked = time.monotonic()
                    dp_before = counters.snapshot()
                    self._run_batch(ids, exchanges)
                    dp = counters.delta(dp_before)
                t_done = time.monotonic()
                for _rid, _ex, t_enq in batch:
                    entry = {
                        "queue_wait_ms": (t_assembled - t_enq) * 1e3,
                        "lock_wait_ms": (t_locked - t_assembled) * 1e3,
                        "handler_ms": (t_done - t_locked) * 1e3,
                        "batch_size": float(len(batch)),
                        "h2d_transfers": float(dp["h2d_transfers"]),
                        "d2h_transfers": float(dp["d2h_transfers"]),
                    }
                    # true ring: overwrite oldest so the summary tracks
                    # CURRENT traffic, not startup-era compiles
                    if len(self.stage_timings) < self._stage_cap:
                        self.stage_timings.append(entry)
                    else:
                        self.stage_timings[self._stage_pos] = entry
                    self._stage_pos = (self._stage_pos + 1) % self._stage_cap


def _status(code: int, reason: str, body: bytes = b"") -> HTTPResponseData:
    return HTTPResponseData(
        headers=[],
        entity=EntityData(content=body, content_length=len(body)) if body else None,
        status_line=StatusLineData(ProtocolVersionData(), code, reason),
    )


def serve_pipeline(
    model,
    input_schema: Any = None,
    host: str = "127.0.0.1",
    port: int = 0,
    api_name: str = "serving",
    reply_col: str = "scored",
    mode: str = "continuous",
    **kwargs: Any,
) -> ServingServer:
    """One-liner: JSON request -> parse_request -> model.transform ->
    make_reply(reply_col). `reply_col` must exist after the transform."""

    def handler(df: DataFrame) -> DataFrame:
        parsed = parse_request(df, input_schema)
        scored = model.transform(parsed)
        return make_reply(scored, reply_col)

    return ServingServer(
        handler, host=host, port=port, api_name=api_name, mode=mode, **kwargs
    )
