"""The serving engine: HTTP front end + pipelined micro-batch/continuous scorer.

Semantics matched to the reference (see package docstring):
- input DataFrame schema is [id: {requestId, partitionId}, request:
  HTTPRequestData] (HTTPSourceV2.scala ID_SCHEMA/SCHEMA at :88-99)
- the sink routes each reply row's `reply` HTTPResponseData back to the
  exchange with that requestId (HTTPWriter, HTTPSourceV2.scala:421-476)
- unknown routes get 404; micro-batch requests that outlive
  `request_timeout` get 504; requests pending at shutdown get 503
- `parse_request` / `make_reply` mirror ServingImplicits.scala:90-109

Continuous mode is the reference's "1 ms latency" HTTPSourceProviderV2
path: no batch wait at all — the handler thread calls the pipeline
directly (batch of 1) under a model lock. Scoring runs inline, so
`request_timeout` does not bound a slow model there — it only bounds the
queue wait in micro-batch mode.

Micro-batch mode runs a three-stage PIPELINED engine (the Clipper
adaptive-batching / Orca keep-the-accelerator-saturated shape):

1. **parse** (thread pool): raw exchanges -> request frame ->
   `StagedServingHandler.parse` — JSON decode and host->device feature
   uploads happen here, OUTSIDE any lock, overlapped with earlier batches'
   device compute.
2. **score** (single thread, the model lock): `StagedServingHandler.score`
   — device dispatch only. JAX async dispatch returns as soon as the work
   is queued on the device, so batch N+1 is submitted while batch N's
   computation is still in flight, bounded by `in_flight_depth` so HBM
   stays O(depth * batch) rather than O(traffic).
3. **reply** (thread pool): `StagedServingHandler.reply` — the
   device->host result sync and JSON serialization, again outside the
   lock, so slow reply encoding never blocks the device queue.

Coalescing is adaptive (stages/batching.py AdaptiveBatchPolicy): a batch
dispatches IMMEDIATELY while the pipeline is empty (an idle device earns
nothing by waiting) and stretches toward max_wait_ms / max_batch_size only
while earlier batches are in flight. Plain-callable handlers keep working:
they run whole inside the score stage (the pre-pipeline contract);
`engine="sync"` restores the fully synchronous engine (the rollback lever
and the bench.py --smoke baseline).

Observability (docs/observability.md): every request gets a root "http"
span whose id follows it through parse -> score -> reply (and, via span
context, into PipelineModel per-stage spans); request latency lands in the
`serving_request_latency_ms` histogram. Two built-in routes serve the
whole observability layer over HTTP on every server:

- ``GET /metrics`` — the process metrics registry in Prometheus text
  format (dataplane transfer/compile counters, per-stage occupancy,
  latency quantiles);
- ``GET /healthz`` — engine liveness JSON (threads alive, queue depth,
  in-flight batches, last-dispatch age); 200 while healthy, 503 while
  stopping or with a dead engine thread.

`slow_request_ms` logs the full span path of any request slower than the
threshold, so tail outliers arrive pre-attributed.
"""

from __future__ import annotations

import contextlib
import http.server
import itertools
import json
import queue
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame, DataType
from mmlspark_tpu.obs.logging import get_logger
from mmlspark_tpu.io.http.schema import (
    EntityData,
    HeaderData,
    HTTPRequestData,
    HTTPResponseData,
    ProtocolVersionData,
    RequestLineData,
    StatusLineData,
)
from mmlspark_tpu.obs import registry as obs_registry
from mmlspark_tpu.obs import tracer as obs_tracer
from mmlspark_tpu.obs.slo import slo_monitor
from mmlspark_tpu.obs.tracing import extract_context
from mmlspark_tpu.utils.profiling import (
    ServingPipelineCounters,
    dataplane_counters,
)

log = get_logger("mmlspark_tpu.serving")

#: per-process server sequence — the `engine` metric label must be unique
#: per ServingServer instance so two servers never merge their series
_SERVER_SEQ = itertools.count()

class _GatewayHTTPServer(http.server.ThreadingHTTPServer):
    """ThreadingHTTPServer with a deep accept backlog: the socketserver
    default of 5 overflows the SYN queue the moment a burst of clients
    connects together, and the kernel's retransmit billing (~1s) lands on
    their first request's latency. Shared by ServingServer and the
    distributed gateway."""

    daemon_threads = True
    request_queue_size = 128

    def handle_error(self, request, client_address):
        """A peer vanishing mid-exchange (gateway failover dropped the
        connection, client timed out and hung up) is normal under fault
        tolerance — log it instead of spraying tracebacks on stderr."""
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, TimeoutError)):
            log.debug("connection_dropped", client=str(client_address),
                      error=repr(exc))
            return
        super().handle_error(request, client_address)


#: Object column parse_request adds when some rows fail schema conversion:
#: None for clean rows, an error string for malformed ones. make_reply turns
#: the marker into a per-row 400 so one bad request can't fail its batch.
MALFORMED_COL = "__malformed__"


# -- parseRequest / makeReply sugar (ServingImplicits.scala:90-109) -----------


def parse_request(
    df: DataFrame,
    schema: Any = None,
    id_col: str = "id",
    request_col: str = "request",
) -> DataFrame:
    """Explode the JSON request entity into columns.

    schema=None: every key across the batch becomes a column (object dtype).
    schema=bytes: passthrough of the raw entity as a `bytes` column.
    schema={"col": DataType, ...}: select + cast those keys. A VECTOR entry
    may declare its dimension as ``(DataType.VECTOR, dim)`` so wrong-length
    requests are rejected per row instead of reaching the model.

    Rows whose values can't satisfy a VECTOR schema entry (missing key,
    null, ragged length vs the declared/batch dimension, non-numeric) do
    NOT fail the batch: they get a zero-vector placeholder plus an error
    string in the MALFORMED_COL marker column, which make_reply converts to
    a per-row 400. Without a declared dimension, the expected length is the
    most common convertible row length in the batch (ties break to the
    earliest seen) — declare the dimension for deterministic validation
    independent of batch composition.
    """
    requests: List[Optional[HTTPRequestData]] = list(df.column(request_col).values)
    ids = df.column(id_col).values
    if schema is bytes:
        content = np.empty(len(requests), object)
        content[:] = [r.entity.content if r and r.entity else None for r in requests]
        return DataFrame.from_dict({id_col: ids}).with_column(
            "bytes", content, DataType.BINARY
        )
    parsed: List[dict] = []
    for r in requests:
        body = r.entity.string_content if r and r.entity else ""
        try:
            obj = json.loads(body) if body else {}
        except json.JSONDecodeError:
            obj = {}
        parsed.append(obj if isinstance(obj, dict) else {"value": obj})
    if schema is None:
        keys: List[str] = []
        for p in parsed:
            for k in p:
                if k not in keys:
                    keys.append(k)
        typed = {k: None for k in keys}
    else:
        typed = dict(schema)
    errors: List[Optional[str]] = [None] * len(parsed)
    out = DataFrame.from_dict({id_col: np.asarray(ids, object)})
    for k, dtype in typed.items():
        vals = [p.get(k) for p in parsed]
        declared_dim: Optional[int] = None
        if (
            isinstance(dtype, tuple)
            and len(dtype) == 2
            and dtype[0] == DataType.VECTOR
        ):
            declared_dim = int(dtype[1])
            dtype = DataType.VECTOR
        if dtype is not None and isinstance(dtype, DataType) and dtype.is_numeric:
            arr: Any = np.asarray(
                [np.nan if v is None else v for v in vals], np.float64
            )
            out = out.with_column(k, arr, DataType.DOUBLE)
        elif dtype == DataType.VECTOR:
            rows: List[Optional[np.ndarray]] = []
            for i, v in enumerate(vals):
                row: Optional[np.ndarray] = None
                if v is not None:
                    try:
                        cand = np.asarray(v, np.float64)
                        if cand.ndim == 1:
                            row = cand
                    except (TypeError, ValueError):
                        row = None
                if row is None and errors[i] is None:
                    errors[i] = (
                        f"field {k!r}: missing or not a numeric vector"
                    )
                rows.append(row)
            if declared_dim is not None:
                dim = declared_dim
            else:
                # modal length (ties -> earliest seen): one bad row batched
                # ahead of good ones must not redefine the batch's dim and
                # 400 valid clients
                lens = [r.shape[0] for r in rows if r is not None]
                if lens:
                    counts: Dict[int, int] = {}
                    for n in lens:
                        counts[n] = counts.get(n, 0) + 1
                    best = max(counts.values())
                    dim = next(n for n in lens if counts[n] == best)
                else:
                    dim = 1
            arr = np.zeros((len(rows), dim), np.float64)
            for i, row in enumerate(rows):
                if row is None:
                    continue
                if row.shape[0] != dim:
                    if errors[i] is None:
                        errors[i] = (
                            f"field {k!r}: vector length {row.shape[0]} != "
                            f"expected {dim}"
                        )
                    continue
                arr[i] = row
            out = out.with_column(k, arr, DataType.VECTOR)
        else:
            arr = np.empty(len(vals), object)
            arr[:] = vals
            out = out.with_column(k, arr)
    if any(e is not None for e in errors):
        marker = np.empty(len(errors), object)
        marker[:] = errors
        out = out.with_column(MALFORMED_COL, marker)
    return out


def make_reply(df: DataFrame, reply_col: str, name: str = "reply") -> DataFrame:
    """Wrap a column as HTTPResponseData (ServingImplicits.makeReply):
    str -> text entity; bytes -> binary; anything else -> JSON. Rows flagged
    in MALFORMED_COL (see parse_request) become 400s instead of replies."""
    values = df.column(reply_col).values
    markers = (
        df.column(MALFORMED_COL).values if MALFORMED_COL in df.columns else None
    )
    replies = np.empty(len(values), object)
    out: List[HTTPResponseData] = []
    for i, v in enumerate(values):
        if markers is not None and markers[i] is not None:
            body = json.dumps({"error": str(markers[i])}).encode("utf-8")
            out.append(_status(400, "Bad Request", body))
        elif isinstance(v, str):
            out.append(HTTPResponseData.ok(v.encode("utf-8"), "text/plain"))
        elif isinstance(v, (bytes, bytearray)):
            out.append(HTTPResponseData.ok(bytes(v), "application/octet-stream"))
        else:
            out.append(
                HTTPResponseData.ok(json.dumps(_to_jsonable(v)).encode("utf-8"))
            )
    replies[:] = out
    return df.with_column(name, replies, DataType.STRUCT)


def _to_jsonable(v: Any) -> Any:
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, dict):
        return {k: _to_jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_jsonable(x) for x in v]
    return v


# -- staged handlers -----------------------------------------------------------


class StagedServingHandler:
    """Three-stage handler contract for the pipelined micro-batch engine.

    parse: [id, request] frame -> device-staged feature frame (JSON decode +
    h2d uploads; runs in the parse pool, outside any lock).
    score: feature frame -> scored frame (device dispatch ONLY; runs under
    the model lock — no JSON, no syncs).
    reply: scored frame -> frame with the reply column of HTTPResponseData
    (d2h sync + serialization; runs in the reply pool, outside the lock).

    Calling the handler directly chains the three stages — continuous mode
    and the sync engine use that path, so one handler serves every mode.
    """

    def parse(self, df: DataFrame) -> DataFrame:
        return df

    def score(self, df: DataFrame) -> DataFrame:
        return df

    def reply(self, df: DataFrame) -> DataFrame:
        return df

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.reply(self.score(self.parse(df)))


class _CallableStages(StagedServingHandler):
    """A plain handler callable, adapted: all its work (JSON + dispatch +
    serialization) runs in the score stage — the pre-pipeline contract."""

    def __init__(self, fn: Callable[[DataFrame], DataFrame]):
        self._fn = fn

    def score(self, df: DataFrame) -> DataFrame:
        return self._fn(df)


def as_staged_handler(handler: Any) -> StagedServingHandler:
    """Adapt any supported handler shape to the staged contract."""
    if isinstance(handler, StagedServingHandler):
        return handler
    return _CallableStages(handler)


class PipelineServingHandler(StagedServingHandler):
    """The canonical staged handler: parse_request -> model.transform ->
    make_reply, with feature uploads pinned to the parse stage.

    `use_mesh=True` shards parse-stage uploads along the default data mesh
    (parallel/mesh.shard_frame), so a multi-device deployment distributes
    request batches without any handler code changes."""

    def __init__(
        self,
        model: Any,
        input_schema: Any = None,
        value_col: str = "scored",
        id_col: str = "id",
        use_mesh: bool = False,
    ):
        self.model = model
        self.input_schema = input_schema
        self.value_col = value_col
        self.id_col = id_col
        self.use_mesh = use_mesh
        self._mesh = None

    def _get_mesh(self):
        if self.use_mesh and self._mesh is None:
            from mmlspark_tpu.parallel.mesh import data_parallel_mesh

            self._mesh = data_parallel_mesh()
        return self._mesh

    def parse(self, df: DataFrame) -> DataFrame:
        parsed = parse_request(df, self.input_schema, id_col=self.id_col)
        vec_cols = [
            n
            for n in parsed.columns
            if n != self.id_col
            and parsed.column(n).dtype == DataType.VECTOR
            and parsed.column(n).values.dtype != object  # ragged: host-only
        ]
        mesh = self._get_mesh()
        if mesh is not None:
            from mmlspark_tpu.parallel.mesh import shard_frame

            return shard_frame(mesh, parsed, vec_cols)
        for n in vec_cols:
            parsed.column(n).device_values()  # upload into the storage cell
        return parsed

    def score(self, df: DataFrame) -> DataFrame:
        return self.model.transform(df)

    def reply(self, df: DataFrame) -> DataFrame:
        return make_reply(df, self.value_col)


# -- the server ---------------------------------------------------------------


class _Exchange:
    """One held HTTP exchange awaiting its reply (the reference keeps the
    com.sun HttpExchange open in MultiChannelMap / the partition reader).
    `deadline` (micro-batch only) is when the waiting client gives up and
    sends its own 504 — replies after it are counted, not routed. `rid` is
    the request id and `span` the root "http" trace span that follows the
    request through every stage (obs/tracing.py)."""

    __slots__ = ("request", "event", "response", "deadline", "rid", "span")

    def __init__(self, request: HTTPRequestData, deadline: Optional[float] = None):
        self.request = request
        self.event = threading.Event()
        self.response: Optional[HTTPResponseData] = None
        self.deadline = deadline
        self.rid: Optional[str] = None
        self.span: Any = None

    def respond(self, response: HTTPResponseData) -> None:
        self.response = response
        self.event.set()


def _request_frame(ids: List[str], exchanges: List[_Exchange]) -> DataFrame:
    id_vals = np.empty(len(ids), object)
    id_vals[:] = [{"requestId": rid, "partitionId": 0} for rid in ids]
    reqs = np.empty(len(exchanges), object)
    reqs[:] = [ex.request for ex in exchanges]
    return DataFrame.from_dict(
        {"id": id_vals, "request": reqs},
        types={"id": DataType.STRUCT, "request": DataType.STRUCT},
    )


class ServingServer:
    """Serve `handler(df) -> df` over HTTP.

    handler receives the [id, request] DataFrame and must return a frame
    containing `id` and a reply column of HTTPResponseData (usually built
    with parse_request/make_reply around a fitted PipelineModel). A
    StagedServingHandler additionally splits parse/score/reply so the
    pipelined engine can overlap host work with device compute.

    mode="continuous": score per-request in the handler thread (lowest
    latency — the reference's HTTPSourceProviderV2 path).
    mode="micro_batch": coalesce up to max_batch_size requests and score
    them in one pipeline call (DistributedHTTPSource batch path).
    engine="pipelined" (default) overlaps parse/score/reply across batches
    with adaptive coalescing; engine="sync" is the serial legacy engine.
    """

    def __init__(
        self,
        handler: Callable[[DataFrame], DataFrame],
        host: str = "127.0.0.1",
        port: int = 0,
        api_name: str = "serving",
        mode: str = "continuous",
        max_batch_size: int = 64,
        max_wait_ms: float = 5.0,
        reply_col: str = "reply",
        request_timeout: float = 30.0,
        engine: str = "pipelined",
        in_flight_depth: int = 2,
        parse_workers: int = 2,
        reply_workers: int = 2,
        guard_score: bool = False,
        slow_request_ms: Optional[float] = None,
    ):
        if mode not in ("continuous", "micro_batch"):
            raise ValueError("mode must be 'continuous' or 'micro_batch'")
        if engine not in ("pipelined", "sync"):
            raise ValueError("engine must be 'pipelined' or 'sync'")
        if in_flight_depth < 1:
            raise ValueError("in_flight_depth must be >= 1")
        self.handler = handler
        self.host = host
        self.api_name = api_name
        self.mode = mode
        self.engine = engine
        self.max_batch_size = max_batch_size
        self.max_wait_ms = max_wait_ms
        self.reply_col = reply_col
        self.request_timeout = request_timeout
        self.in_flight_depth = in_flight_depth
        self.parse_workers = parse_workers
        self.reply_workers = reply_workers
        # verification aid (tests/bench): run the score critical section
        # under jax.transfer_guard("disallow_explicit") — proof that parse-stage
        # uploads and reply-stage syncs keep it transfer-free. On the sync
        # engine and in continuous mode the whole handler IS the critical
        # section, so the guard wraps it all (and truthfully fails handlers
        # that transfer under the lock).
        self.guard_score = guard_score
        self._queue: List[tuple] = []
        self._queue_lock = threading.Condition()
        self._model_lock = threading.Lock()
        # per-request stage decomposition (round-5 verdict item 8: explain
        # the p99 tail with data, don't guess): queue_wait | parse | lock
        # wait | handler | reply, bounded ring
        self.stage_timings: List[Dict[str, float]] = []
        self._stage_cap = 4096
        self._stage_pos = 0
        # ring writers are concurrent now (reply-pool workers, per-request
        # continuous handler threads), unlike the old single engine thread
        self._stage_lock = threading.Lock()
        # observability wiring: a stable per-instance label keys every
        # registry series; the latency histogram and queue-depth gauge are
        # the scrape-side view of what stage_summary() reports in-process
        self.slow_request_ms = slow_request_ms
        self._obs_label = f"{api_name}-{next(_SERVER_SEQ)}"
        self._tracer = obs_tracer()
        reg = obs_registry()
        self._lat_hist = reg.histogram(
            "serving_request_latency_ms",
            "End-to-end request latency at the HTTP edge",
            ("engine", "code"),
        )
        self._queue_gauge = reg.gauge(
            "serving_queue_depth",
            "Requests queued awaiting batch dispatch",
            ("engine",),
        )
        self._queue_gauge.labels(engine=self._obs_label).set_function(
            lambda: float(len(self._queue))
        )
        self._pipe_counters = ServingPipelineCounters(
            engine_label=self._obs_label
        )
        self._last_dispatch: Optional[float] = None
        self._t_started: Optional[float] = None
        # batches dispatched but not yet THROUGH the score stage — the
        # adaptive coalescer's "in flight" signal: while this is > 0 the
        # score stage has work coming, so waiting to fatten the next batch
        # costs nothing; once it drains, waiting just idles the device
        self._score_feed = 0
        self._stopping = threading.Event()
        self._engine_thread: Optional[threading.Thread] = None
        self._dispatch_thread: Optional[threading.Thread] = None
        self._score_thread: Optional[threading.Thread] = None
        self._parse_pool: Optional[ThreadPoolExecutor] = None
        self._reply_pool: Optional[ThreadPoolExecutor] = None
        self._score_q: "queue.Queue" = queue.Queue()
        self._inflight_sem = threading.BoundedSemaphore(in_flight_depth)
        self._staged: Optional[StagedServingHandler] = None
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._port = port

    # - wiring ---------------------------------------------------------------

    @property
    def port(self) -> int:
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self._port}/{self.api_name}"

    @property
    def expired_in_flight(self) -> int:
        """Requests whose deadline passed while their batch was being
        scored — the client already received its 504, so the engine skipped
        routing the reply (and, when EVERY request in a pipelined batch had
        expired, the reply stage's d2h sync + serialization entirely;
        partially-expired batches still serialize for the live rows)."""
        return int(self._pipe_counters.expired_in_flight)

    def start(self) -> "ServingServer":
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # small header+body writes otherwise hit Nagle + delayed-ACK
            # (~40 ms per exchange) — fatal for the 1 ms latency target
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # route into our logger
                log.debug("http_access", client=self.address_string(),
                          line=(fmt % args) if args else fmt)

            def _read_request(self) -> HTTPRequestData:
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                ct = self.headers.get("Content-Type")
                return HTTPRequestData(
                    RequestLineData(self.command, self.path),
                    [HeaderData(k, v) for k, v in self.headers.items()],
                    EntityData(
                        content=body,
                        content_length=len(body),
                        content_type=HeaderData("Content-Type", ct) if ct else None,
                    ),
                )

            def _send(self, resp: HTTPResponseData) -> None:
                body = resp.entity.content if resp.entity else b""
                self.send_response(
                    resp.status_line.status_code, resp.status_line.reason_phrase
                )
                ct = None
                if resp.entity and resp.entity.content_type:
                    ct = resp.entity.content_type.value
                self.send_header("Content-Type", ct or "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _drain_body(self) -> None:
                """Consume any request body before replying: HTTP/1.1
                keep-alive means unread body bytes would be parsed as the
                NEXT request line, corrupting the connection."""
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    self.rfile.read(length)

            def do_POST(self):
                route = self.path.split("?", 1)[0].rstrip("/")
                # observability surfaces answer on every server (verb
                # agnostic so `curl` and scrapers both just work)
                if route == "/metrics":
                    self._drain_body()
                    parts = self.path.split("?", 1)
                    query = parts[1] if len(parts) > 1 else ""
                    if "sketches=1" in query:
                        # federation scrape: identity + exposition +
                        # mergeable histogram state in one exchange
                        # (obs/federation.py scrape_payload)
                        from mmlspark_tpu.obs.federation import (
                            scrape_payload,
                        )

                        body = json.dumps(
                            scrape_payload(
                                obs_registry(),
                                probe="probe=1" in query,
                            ),
                            sort_keys=True,
                        ).encode("utf-8")
                        self._send(HTTPResponseData.ok(
                            body, "application/json"))
                        return
                    body, ctype = obs_registry().render_scrape(query)
                    self._send(HTTPResponseData.ok(body, ctype))
                    return
                if route == "/healthz":
                    self._drain_body()
                    ok, info = outer.health()
                    body = json.dumps(info, sort_keys=True).encode("utf-8")
                    self._send(
                        HTTPResponseData.ok(body)
                        if ok
                        else _status(503, "Service Unavailable", body)
                    )
                    return
                # flight-recorder surfaces (docs/observability.md "Flight
                # recorder"): recent per-dispatch records as JSON, and the
                # tracer ring as Chrome trace_event JSON — a live pause is
                # diagnosable without redeploying
                if route == "/debug/flight":
                    self._drain_body()
                    from mmlspark_tpu.obs.profiler import device_profiler

                    body = json.dumps(
                        device_profiler().flight(), sort_keys=True
                    ).encode("utf-8")
                    self._send(HTTPResponseData.ok(body))
                    return
                if route == "/debug/memory":
                    self._drain_body()
                    body = json.dumps(
                        _memory_payload(self.path), sort_keys=True
                    ).encode("utf-8")
                    self._send(HTTPResponseData.ok(body))
                    return
                if route == "/debug/trace":
                    self._drain_body()
                    # ?trace_id= serves the assembled cross-hop TREE for
                    # one trace; no query keeps the Chrome-trace dump of
                    # the whole ring (docs/observability.md)
                    body = json.dumps(
                        _trace_payload(self.path)
                    ).encode("utf-8")
                    self._send(HTTPResponseData.ok(body))
                    return
                if route != f"/{outer.api_name}":
                    self._send(_status(404, "Not Found"))
                    return
                if outer._stopping.is_set():
                    self._send(_status(503, "Service Unavailable"))
                    return
                t_http = time.monotonic()
                rid = str(uuid.uuid4())
                # cross-process propagation: a gateway-routed request
                # carries traceparent, so this http span parents under the
                # gateway's attempt span and the whole hop chain shares
                # one trace id (absent/malformed headers -> fresh root)
                ctx = extract_context(self.headers)
                if outer.mode == "continuous":
                    exchange = _Exchange(self._read_request())
                    exchange.rid = rid
                    exchange.span = outer._tracer.start_span(
                        "http", context=ctx,
                        attrs={"request_id": rid, "path": self.path,
                               "method": self.command, "mode": outer.mode},
                    )
                    outer._score_now(exchange)
                else:
                    t_enq = time.monotonic()
                    exchange = _Exchange(
                        self._read_request(),
                        deadline=t_enq + outer.request_timeout,
                    )
                    exchange.rid = rid
                    exchange.span = outer._tracer.start_span(
                        "http", context=ctx,
                        attrs={"request_id": rid, "path": self.path,
                               "method": self.command, "mode": outer.mode},
                    )
                    with outer._queue_lock:
                        # authoritative stop check: stop() sets _stopping
                        # BEFORE draining under this lock, so an enqueue
                        # racing the drain either lands in it or sees the
                        # flag here — never strands in a dead queue
                        stopped = outer._stopping.is_set()
                        if not stopped:
                            outer._queue.append((rid, exchange, t_enq))
                            outer._queue_lock.notify_all()
                    if stopped:
                        resp = _status(503, "Service Unavailable")
                        outer._finish_http(exchange, resp, t_http)
                        self._send(resp)
                        return
                if not exchange.event.wait(outer.request_timeout):
                    resp = _status(504, "Gateway Timeout")
                else:
                    # a reply skipped as expired sets the event with no
                    # response; if this thread's own timer hasn't quite
                    # lapsed (clock skew vs the engine's deadline), 504 is
                    # still the truthful answer
                    resp = exchange.response or _status(504, "Gateway Timeout")
                outer._finish_http(exchange, resp, t_http)
                self._send(resp)

            do_GET = do_POST
            do_PUT = do_POST

        self._httpd = _GatewayHTTPServer((self.host, self._port), Handler)
        self._port = self._httpd.server_address[1]
        self._t_started = time.monotonic()
        httpd = self._httpd
        # short poll interval: shutdown() (stop, kill, hot-swap teardown)
        # returns in ~50ms instead of the 500ms socketserver default
        threading.Thread(
            target=lambda: httpd.serve_forever(poll_interval=0.05),
            daemon=True,
        ).start()
        if self.mode == "micro_batch":
            if self.engine == "pipelined":
                self._start_pipeline()
            else:
                self._engine_thread = threading.Thread(
                    target=self._engine_loop,
                    daemon=True,
                    name=f"serve-sync-{self._port}",
                )
                self._engine_thread.start()
        log.info("serving_started", url=self.url, mode=self.mode,
                 engine=self.engine)
        return self

    def _start_pipeline(self) -> None:
        self._staged = as_staged_handler(self.handler)
        self._parse_pool = ThreadPoolExecutor(
            self.parse_workers, thread_name_prefix=f"serve-parse-{self._port}"
        )
        self._reply_pool = ThreadPoolExecutor(
            self.reply_workers, thread_name_prefix=f"serve-reply-{self._port}"
        )
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop,
            daemon=True,
            name=f"serve-dispatch-{self._port}",
        )
        self._score_thread = threading.Thread(
            target=self._score_loop, daemon=True, name=f"serve-score-{self._port}"
        )
        self._dispatch_thread.start()
        self._score_thread.start()

    def stop(self) -> None:
        """Drain and shut down: queued-but-undispatched requests get 503;
        batches already in parse/score/reply complete with real replies;
        every engine thread is joined (with timeouts) so no worker outlives
        the server."""
        self._stopping.set()
        with self._queue_lock:
            pending = self._queue
            self._queue = []
            self._queue_lock.notify_all()
        for _rid, ex, _t in pending:
            ex.respond(_status(503, "Service Unavailable"))
        if self._dispatch_thread is not None:
            self._dispatch_thread.join(timeout=10.0)
            self._dispatch_thread = None
        if self._parse_pool is not None:
            self._parse_pool.shutdown(wait=True)  # in-parse batches finish
            self._parse_pool = None
        if self._score_thread is not None:
            self._score_q.put(None)  # sentinel AFTER the parse pool drained
            self._score_thread.join(timeout=30.0)
            self._score_thread = None
        if self._reply_pool is not None:
            self._reply_pool.shutdown(wait=True)  # in-flight replies complete
            self._reply_pool = None
        if self._engine_thread is not None:
            self._engine_thread.join(timeout=10.0)
            self._engine_thread = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        # unhook scrape-time callbacks that close over this server — the
        # process registry must not pin stopped servers (or report stale
        # liveness for them); cumulative counter series stay
        self._queue_gauge.remove(engine=self._obs_label)
        self._pipe_counters.close()

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # - scoring --------------------------------------------------------------

    def _respond_engine(
        self,
        ex: _Exchange,
        response: HTTPResponseData,
        enforce_deadline: bool = True,
    ) -> None:
        """Route one reply to its exchange. A request whose deadline passed
        while its batch was in flight already cost its client a 504 — late
        replies are counted (expired_in_flight), not routed."""
        if ex.event.is_set():
            return
        if (
            enforce_deadline
            and ex.deadline is not None
            and time.monotonic() > ex.deadline
        ):
            self._pipe_counters.record_expired()
            ex.event.set()  # hygiene: never leave a waiter unhooked
            return
        ex.respond(response)

    def _route_replies(
        self, out: DataFrame, by_id: Dict[str, _Exchange], enforce_deadline: bool
    ) -> None:
        out_ids = out.column("id").values
        replies = out.column(self.reply_col).values
        for row_id, reply in zip(out_ids, replies):
            rid = row_id["requestId"] if isinstance(row_id, dict) else str(row_id)
            ex = by_id.pop(rid, None)
            if ex is not None:
                self._respond_engine(
                    ex,
                    reply if reply is not None else _status(500, "No reply"),
                    enforce_deadline,
                )
        for ex in by_id.values():  # rows the handler dropped
            self._respond_engine(ex, _status(500, "No reply produced"), enforce_deadline)

    def _run_batch(
        self,
        ids: List[str],
        exchanges: List[_Exchange],
        enforce_deadline: bool = False,
    ) -> None:
        df = _request_frame(ids, exchanges)
        by_id = dict(zip(ids, exchanges))
        try:
            # guard_score applies here too (sync engine / continuous mode):
            # the whole handler IS the critical section on these paths, so
            # the guard truthfully reports any transfer made under the lock
            with self._stage_span("score", exchanges, batch_size=len(ids)):
                with self._score_guard():
                    out = self.handler(df)
            self._route_replies(out, by_id, enforce_deadline)
        except Exception as e:  # surface pipeline errors as 500s, keep serving
            log.exception("handler_failed")
            for ex in by_id.values():
                self._respond_engine(
                    ex,
                    _status(500, "Internal Server Error", repr(e).encode("utf-8")),
                    enforce_deadline=False,
                )

    def _record_timing(self, entry: Dict[str, float]) -> None:
        # true ring: overwrite oldest so the summary tracks CURRENT
        # traffic, not startup-era compiles
        with self._stage_lock:
            if len(self.stage_timings) < self._stage_cap:
                self.stage_timings.append(entry)
            else:
                self.stage_timings[self._stage_pos] = entry
            self._stage_pos = (self._stage_pos + 1) % self._stage_cap

    def stage_summary(self) -> Dict[str, float]:
        """p50/p99 decomposition of the recorded stage timings (queue wait |
        parse | lock wait | handler/score | reply) — the evidence base for
        attributing tail latency (BASELINE.md serving section). Also carries
        mean host<->device transfer counts per scored batch (the dataplane
        hot-path metric: a device-resident handler pipeline should show
        exactly one h2d for the request features and one d2h for the reply
        sync — anything more is a stage boundary leaking through host).
        The counters are process-wide, so per-batch attribution is exact
        only while this server is the sole device user; under concurrent
        engines treat these as an upper bound. Continuous mode records the
        same entries with queue_wait pinned to zero (scoring is inline);
        sync-engine entries omit parse/reply (that work runs un-staged
        inside the handler)."""
        if not self.stage_timings:
            return {}
        out: Dict[str, float] = {}
        for key in (
            "queue_wait_ms",
            "parse_ms",
            "lock_wait_ms",
            "handler_ms",
            "reply_ms",
        ):
            vals = sorted(t[key] for t in self.stage_timings if key in t)
            if not vals:
                continue
            out[f"{key}_p50"] = round(vals[len(vals) // 2], 3)
            out[f"{key}_p99"] = round(vals[int(len(vals) * 0.99)], 3)
        out["mean_batch_size"] = round(
            float(np.mean([t["batch_size"] for t in self.stage_timings])), 2
        )
        for key in ("h2d_transfers", "d2h_transfers"):
            per_batch = [t[key] for t in self.stage_timings if key in t]
            if per_batch:
                out[f"mean_{key}_per_batch"] = round(float(np.mean(per_batch)), 2)
        out["n_sampled"] = float(len(self.stage_timings))
        return out

    def pipeline_summary(self) -> Dict[str, float]:
        """Occupancy/backpressure summary of the pipelined engine: per-stage
        busy fractions, in-flight depth peak, immediate vs coalesced
        dispatch decisions, and expired-in-flight count
        (utils/profiling.ServingPipelineCounters)."""
        return self._pipe_counters.summary()

    # - observability ---------------------------------------------------------

    def health(self) -> Tuple[bool, Dict[str, Any]]:
        """Engine liveness: (healthy, info). Healthy means the server is
        accepting work AND every engine thread it needs is alive; the info
        dict is what ``GET /healthz`` returns (200 when healthy, 503
        otherwise)."""
        now = time.monotonic()
        with self._queue_lock:
            depth = len(self._queue)
        threads: Dict[str, bool] = {}
        if self.mode == "micro_batch":
            if self.engine == "pipelined":
                threads["dispatch"] = (
                    self._dispatch_thread is not None
                    and self._dispatch_thread.is_alive()
                )
                threads["score"] = (
                    self._score_thread is not None
                    and self._score_thread.is_alive()
                )
            else:
                threads["engine"] = (
                    self._engine_thread is not None
                    and self._engine_thread.is_alive()
                )
        stopping = self._stopping.is_set()
        started = self._httpd is not None
        ok = started and not stopping and all(threads.values())
        # SLO health rides along: a page-severity burn alert on a spec
        # covering this engine degrades the REPORTED status without
        # flipping liveness (a burning-but-alive server must not be
        # ejected by the gateway's health routing — it is still the best
        # place for the traffic it can serve)
        slos = slo_monitor().status(engine=self._obs_label)
        slo_degraded = slo_monitor().page_burn_active(
            engine=self._obs_label
        )
        status = "ok" if ok else ("stopping" if stopping else "degraded")
        if ok and slo_degraded:
            status = "degraded"
        info: Dict[str, Any] = {
            "status": status,
            "slos": slos,
            "mode": self.mode,
            "engine": self.engine,
            "engine_label": self._obs_label,
            "threads": threads,
            "queue_depth": depth,
            "in_flight": self._pipe_counters.in_flight,
            "last_dispatch_age_s": (
                round(now - self._last_dispatch, 3)
                if self._last_dispatch is not None
                else None
            ),
            "uptime_s": (
                round(now - self._t_started, 3)
                if self._t_started is not None
                else None
            ),
        }
        return ok, info

    def _finish_http(self, ex: _Exchange, resp: HTTPResponseData,
                     t0: float) -> None:
        """Close out a request at the HTTP edge: end its root span, record
        end-to-end latency, and log the span path when it crossed
        `slow_request_ms`."""
        code = resp.status_line.status_code
        dt_ms = (time.monotonic() - t0) * 1e3
        span = ex.span
        traced = span is not None and span.recording
        if traced:
            span.set_attribute("status_code", code)
            self._tracer.end_span(span)
        # the explicit trace_id rides as the histogram's OpenMetrics
        # exemplar (the span has left the contextvar by now), so a p99
        # spike on the scrape links straight to this request's trace
        self._lat_hist.labels(engine=self._obs_label, code=str(code)).observe(
            dt_ms,
            trace_id=span.trace_id if traced else None,
            span_id=span.span_id if traced else None,
        )
        # the SLO engine sees the same stream the latency family records:
        # availability/latency objectives selecting this engine label
        # evaluate over exactly these observations
        slo_monitor().observe(
            self._obs_label, code, dt_ms,
            trace_id=span.trace_id if traced else None,
        )
        if self.slow_request_ms is not None and dt_ms >= self.slow_request_ms:
            path = (
                self._tracer.trace_summary(span.trace_id) if traced else "untraced"
            )
            log.warning(
                "slow_request", request_id=ex.rid,
                latency_ms=round(dt_ms, 1),
                threshold_ms=self.slow_request_ms, span_path=path,
                trace_id=span.trace_id if traced else None,
            )

    @contextlib.contextmanager
    def _stage_span(self, name: str, exchanges: List[_Exchange], **attrs):
        """Trace one batch stage: a LIVE child span under the first traced
        request (activated, so nested spans and transfer events attach to
        it), plus a retroactive copy under every other request in the batch
        — each request's trace ends up with its full http -> parse -> score
        -> reply path."""
        tr = self._tracer
        traced = [
            ex.span for ex in exchanges
            if ex.span is not None and ex.span.recording
        ]
        if not traced:
            yield None
            return
        lead, rest = traced[0], traced[1:]
        span = tr.start_span(name, parent=lead, attrs=attrs)
        try:
            with tr.activate(span):
                yield span
        finally:
            tr.end_span(span)
            for parent in rest:
                tr.add_span(name, parent, span.t_start, span.t_end,
                            attrs=dict(span.attrs))

    def _score_now(self, exchange: _Exchange) -> None:
        counters = dataplane_counters()
        t0 = time.monotonic()
        with self._model_lock:
            t_locked = time.monotonic()
            self._last_dispatch = t_locked
            dp_before = counters.snapshot()
            self._run_batch([exchange.rid or str(uuid.uuid4())], [exchange])
            dp = counters.delta(dp_before)
        t_done = time.monotonic()
        # continuous mode records the same decomposition as micro-batch so
        # stage_summary() works in both modes; queue_wait is structurally
        # zero (the handler thread scores inline, no batcher queue)
        self._record_timing(
            {
                "queue_wait_ms": 0.0,
                "lock_wait_ms": (t_locked - t0) * 1e3,
                "handler_ms": (t_done - t_locked) * 1e3,
                "batch_size": 1.0,
                "h2d_transfers": float(dp["h2d_transfers"]),
                "d2h_transfers": float(dp["d2h_transfers"]),
            }
        )

    # - sync engine (engine="sync": the serial rollback path) -----------------

    def _engine_loop(self) -> None:
        while not self._stopping.is_set():
            with self._queue_lock:
                if not self._queue:
                    self._queue_lock.wait(0.05)
                    continue
                deadline = time.monotonic() + self.max_wait_ms / 1000.0
                while (
                    len(self._queue) < self.max_batch_size
                    and time.monotonic() < deadline
                    and not self._stopping.is_set()
                ):
                    self._queue_lock.wait(max(0.0, deadline - time.monotonic()))
                # Requests whose client already got a 504 are dead — scoring
                # them would burn batch slots and model-lock time on replies
                # nobody reads.
                cutoff = time.monotonic() - self.request_timeout
                self._queue = [e for e in self._queue if e[2] > cutoff]
                batch = self._queue[: self.max_batch_size]
                self._queue = self._queue[self.max_batch_size:]
            if batch:
                counters = dataplane_counters()
                ids = [rid for rid, _, _t in batch]
                exchanges = [ex for _, ex, _t in batch]
                t_assembled = time.monotonic()
                self._last_dispatch = t_assembled
                with self._model_lock:
                    t_locked = time.monotonic()
                    dp_before = counters.snapshot()
                    # enforce_deadline: a request can expire WHILE its batch
                    # is being scored, not just in the queue — skip + count
                    self._run_batch(ids, exchanges, enforce_deadline=True)
                    dp = counters.delta(dp_before)
                t_done = time.monotonic()
                for _rid, _ex, t_enq in batch:
                    self._record_timing(
                        {
                            "queue_wait_ms": (t_assembled - t_enq) * 1e3,
                            "lock_wait_ms": (t_locked - t_assembled) * 1e3,
                            "handler_ms": (t_done - t_locked) * 1e3,
                            "batch_size": float(len(batch)),
                            "h2d_transfers": float(dp["h2d_transfers"]),
                            "d2h_transfers": float(dp["d2h_transfers"]),
                        }
                    )

    # - pipelined engine ------------------------------------------------------

    def _dispatch_loop(self) -> None:
        from mmlspark_tpu.stages.batching import AdaptiveBatchPolicy

        policy = AdaptiveBatchPolicy(self.max_batch_size, self.max_wait_ms)
        while not self._stopping.is_set():
            with self._queue_lock:
                if not self._queue:
                    self._queue_lock.wait(0.05)
                    continue
                immediate = True
                while not self._stopping.is_set() and self._queue:
                    oldest_ms = (time.monotonic() - self._queue[0][2]) * 1e3
                    if policy.should_dispatch(
                        len(self._queue), oldest_ms, self._score_feed
                    ):
                        break
                    immediate = False
                    self._queue_lock.wait(
                        min(max(policy.wait_budget_s(oldest_ms), 1e-4), 0.05)
                    )
                if self._stopping.is_set() or not self._queue:
                    continue
                cutoff = time.monotonic() - self.request_timeout
                self._queue = [e for e in self._queue if e[2] > cutoff]
                batch = self._queue[: self.max_batch_size]
                self._queue = self._queue[self.max_batch_size:]
                if batch:
                    self._score_feed += 1
            if not batch:
                continue
            # acquire the in-flight slot HERE, before any parse-stage device
            # upload, so at most in_flight_depth batches of features exist
            # between dispatch and reply-done — the documented O(depth *
            # batch) HBM bound. Under overload the dispatcher blocks (queue
            # grows host-side) instead of flooding HBM.
            acquired = False
            while not acquired and not self._stopping.is_set():
                acquired = self._inflight_sem.acquire(timeout=0.05)
            if not acquired:  # stopping while saturated
                for _rid, ex, _t in batch:
                    self._respond_engine(
                        ex, _status(503, "Service Unavailable"), enforce_deadline=False
                    )
                self._score_feed_done()
                continue
            self._pipe_counters.enter_in_flight()
            self._pipe_counters.record_dispatch(immediate)
            t_dispatch = time.monotonic()
            self._last_dispatch = t_dispatch
            try:
                self._parse_pool.submit(self._parse_batch, batch, t_dispatch)
            except RuntimeError:  # pool torn down mid-stop
                for _rid, ex, _t in batch:
                    self._respond_engine(
                        ex, _status(503, "Service Unavailable"), enforce_deadline=False
                    )
                self._score_feed_done()
                self._inflight_sem.release()
                self._pipe_counters.exit_in_flight()

    def _score_feed_done(self) -> None:
        with self._queue_lock:
            self._score_feed -= 1
            # wake a stretching dispatcher: the score stage may now be hungry
            self._queue_lock.notify_all()

    def _parse_batch(self, batch: List[tuple], t_dispatch: float) -> None:
        ids = [rid for rid, _ex, _t in batch]
        exchanges = [ex for _rid, ex, _t in batch]
        counters = dataplane_counters()
        try:
            t0 = time.monotonic()
            with self._pipe_counters.stage("parse", rows=len(batch)):
                with self._stage_span(
                    "parse", exchanges, batch_size=len(batch)
                ) as pspan:
                    dp_before = counters.snapshot()
                    parsed = self._staged.parse(_request_frame(ids, exchanges))
                    h2d = counters.delta(dp_before)["h2d_transfers"]
                    if pspan is not None:
                        pspan.set_attribute("h2d_transfers", h2d)
            self._score_q.put(
                {
                    "batch": batch,
                    "ids": ids,
                    "exchanges": exchanges,
                    "parsed": parsed,
                    "t_dispatch": t_dispatch,
                    "parse_ms": (time.monotonic() - t0) * 1e3,
                    "h2d": h2d,
                }
            )
        except Exception as e:
            log.exception("parse_stage_failed")
            for ex in exchanges:
                self._respond_engine(
                    ex,
                    _status(500, "Internal Server Error", repr(e).encode("utf-8")),
                    enforce_deadline=False,
                )
            self._score_feed_done()
            self._inflight_sem.release()  # slot was taken at dispatch
            self._pipe_counters.exit_in_flight()

    def _score_guard(self):
        if not self.guard_score:
            return contextlib.nullcontext()
        import jax

        # disallow_explicit: jax.device_put / device_get are "explicit"
        # transfers that plain "disallow" waves through — and the parse
        # stage's uploads are exactly device_puts, so the stricter level is
        # the one that actually proves the critical section transfer-free
        return jax.transfer_guard("disallow_explicit")

    def _score_loop(self) -> None:
        while True:
            work = self._score_q.get()
            if work is None:  # shutdown sentinel (stop(), after parse drain)
                return
            # the in-flight slot was acquired at dispatch (before the parse
            # stage's uploads) and frees when the reply stage finishes the
            # d2h sync — HBM stays O(depth * batch) end to end
            t_wait = time.monotonic()
            err: Optional[HTTPResponseData] = None
            scored: Optional[DataFrame] = None
            with self._model_lock:
                t_locked = time.monotonic()
                try:
                    with self._pipe_counters.stage("score"):
                        with self._stage_span(
                            "score", work["exchanges"],
                            batch_size=len(work["batch"]),
                        ):
                            with self._score_guard():
                                # JAX async dispatch: returns once the batch
                                # is QUEUED on the device, so the next
                                # batch's parse and this one's compute
                                # overlap
                                scored = self._staged.score(work["parsed"])
                except Exception as e:
                    log.exception("score_stage_failed")
                    err = _status(
                        500, "Internal Server Error", repr(e).encode("utf-8")
                    )
            # past the score stage: the coalescer may stop stretching
            self._score_feed_done()
            work["lock_wait_ms"] = (t_locked - t_wait) * 1e3
            work["score_ms"] = (time.monotonic() - t_locked) * 1e3
            if err is not None:
                for ex in work["exchanges"]:
                    self._respond_engine(ex, err, enforce_deadline=False)
                self._finish_batch(work)
                continue
            work["scored"] = scored
            try:
                self._reply_pool.submit(self._reply_batch, work)
            except RuntimeError:  # pool torn down mid-stop
                for ex in work["exchanges"]:
                    self._respond_engine(
                        ex, _status(503, "Service Unavailable"), enforce_deadline=False
                    )
                self._finish_batch(work)

    def _reply_batch(self, work: Dict[str, Any]) -> None:
        counters = dataplane_counters()
        t0 = time.monotonic()
        try:
            now = time.monotonic()
            if all(
                ex.deadline is not None and now > ex.deadline
                for ex in work["exchanges"]
            ):
                # every client already got its 504 — shed the whole reply
                # stage (d2h sync + serialization), just count and unhook
                for ex in work["exchanges"]:
                    self._respond_engine(ex, _status(504, "Gateway Timeout"))
                return
            with self._pipe_counters.stage("reply"):
                # the reply span closes BEFORE replies are routed: routing
                # wakes the HTTP threads, which log the slow-request span
                # path — every stage span must already be in the ring
                with self._stage_span(
                    "reply", work["exchanges"], batch_size=len(work["batch"])
                ) as rspan:
                    dp_before = counters.snapshot()
                    out = self._staged.reply(work["scored"])
                    work["d2h"] = counters.delta(dp_before)["d2h_transfers"]
                    if rspan is not None:
                        rspan.set_attribute("d2h_transfers", work["d2h"])
                self._route_replies(
                    out,
                    dict(zip(work["ids"], work["exchanges"])),
                    enforce_deadline=True,
                )
        except Exception as e:
            log.exception("reply_stage_failed")
            for ex in work["exchanges"]:
                self._respond_engine(
                    ex,
                    _status(500, "Internal Server Error", repr(e).encode("utf-8")),
                    enforce_deadline=False,
                )
        finally:
            work["reply_ms"] = (time.monotonic() - t0) * 1e3
            self._finish_batch(work)

    def _finish_batch(self, work: Dict[str, Any]) -> None:
        self._inflight_sem.release()
        self._pipe_counters.exit_in_flight()
        n = float(len(work["batch"]))
        for _rid, _ex, t_enq in work["batch"]:
            self._record_timing(
                {
                    "queue_wait_ms": (work["t_dispatch"] - t_enq) * 1e3,
                    "parse_ms": work.get("parse_ms", 0.0),
                    "lock_wait_ms": work.get("lock_wait_ms", 0.0),
                    "handler_ms": work.get("score_ms", 0.0),
                    "reply_ms": work.get("reply_ms", 0.0),
                    "batch_size": n,
                    "h2d_transfers": float(work.get("h2d", 0)),
                    "d2h_transfers": float(work.get("d2h", 0)),
                }
            )


def _trace_payload(path: str) -> Dict[str, Any]:
    """The GET /debug/trace body: the assembled tree for ?trace_id=, the
    whole ring as Chrome trace_event JSON otherwise. Shared by
    ServingServer and the distributed gateway (same process tracer)."""
    import urllib.parse

    from mmlspark_tpu.obs.federation import proc_identity

    query = path.split("?", 1)[1] if "?" in path else ""
    opts = urllib.parse.parse_qs(query)
    tid = opts.get("trace_id", [""])[-1]
    payload = (
        obs_tracer().trace_tree(tid) if tid else obs_tracer().chrome_trace()
    )
    payload["proc_identity"] = proc_identity()
    return payload


def _memory_payload(path: str) -> Dict[str, Any]:
    """The GET /debug/memory body: the device-memory ledger's per-device
    snapshot, watermarks, pressure, last truth-check and top-N owners
    (obs/memory.py). `?top_n=` widens the owner list; `?reconcile=always`
    forces a fresh jax.live_arrays() truth-check on this request (the
    default re-checks lazily when the last one is stale). Shared by
    ServingServer and the distributed gateway (same process ledger)."""
    import urllib.parse

    from mmlspark_tpu.obs.memory import memory_ledger

    query = path.split("?", 1)[1] if "?" in path else ""
    opts = urllib.parse.parse_qs(query)
    try:
        top_n = int(opts.get("top_n", ["10"])[-1])
    except ValueError:
        top_n = 10
    mode = opts.get("reconcile", ["auto"])[-1]
    if mode not in ("auto", "always", "never"):
        mode = "auto"
    return memory_ledger().debug_payload(top_n=top_n, reconcile=mode)


def _status(code: int, reason: str, body: bytes = b"") -> HTTPResponseData:
    return HTTPResponseData(
        headers=[],
        entity=EntityData(content=body, content_length=len(body)) if body else None,
        status_line=StatusLineData(ProtocolVersionData(), code, reason),
    )


def serve_pipeline(
    model,
    input_schema: Any = None,
    host: str = "127.0.0.1",
    port: int = 0,
    api_name: str = "serving",
    reply_col: str = "scored",
    mode: str = "continuous",
    use_mesh: bool = False,
    **kwargs: Any,
) -> ServingServer:
    """One-liner: JSON request -> parse_request -> model.transform ->
    make_reply(reply_col). `reply_col` must exist after the transform.
    Built on PipelineServingHandler, so micro-batch mode gets the pipelined
    engine's parse/score/reply overlap (and `use_mesh=True` shards
    parse-stage uploads over the data mesh) with no extra code."""
    handler = PipelineServingHandler(
        model, input_schema, value_col=reply_col, use_mesh=use_mesh
    )
    return ServingServer(
        handler, host=host, port=port, api_name=api_name, mode=mode, **kwargs
    )
