"""Distributed serving: a worker pool behind a fault-tolerant routing gateway.

Reference: io/http/src/main/scala/DistributedHTTPSource.scala:89-242 — one
JVMSharedServer per executor, each binding its own port and scoring its own
partition, with a driver-side gateway (PortForwarding.scala:12) fronting the
pool — and HTTPSourceV2.scala:167-404's continuous per-partition commit (no
cross-partition lock). The reference survives executor churn because the
driver only routes to partitions that are alive; this gateway recreates
that property without a driver through the serving fabric
(serving/fabric.py):

- **health-driven routing**: power-of-two-choices among workers that are
  (a) green on their own PR 5 ``health()`` signal, (b) closed on their
  circuit breaker, and (c) not draining; EWMA latency + in-flight counts
  break the choice. A worker that fails at the transport level (connect
  refused, read timeout) accumulates breaker failures and is ejected;
  after ``open_secs`` single probe requests test it back in.
- **retry + hedge**: a failed forward retries against a *different* worker
  with full-jitter backoff, capped by a retry-budget token bucket so
  retries can never amplify an overload; optional tail hedging duplicates
  a request to a second worker once it outlives the observed p95.
- **admission control + load shedding**: an AIMD concurrency limit at the
  gateway edge; excess load fast-fails with 429 + Retry-After instead of
  queueing toward the request timeout (`serving_shed_requests_total`).
- **graceful drain / hot restart**: ``drain(idx)`` stops routing to a
  worker and flushes its in-flight; ``replace_worker(idx)`` starts a
  replacement first, drains, atomically swaps the slot, then tears the old
  worker down — zero-downtime model refresh.

TPU re-design: the partition==executor mapping becomes worker==replica.
Each worker owns a PRIVATE handler instance (its own compiled model, its
own model lock), so continuous-mode scoring never serializes across
workers. Workers are in-process threads sharing the chip; multi-host scale
uses the same topology with workers on peer hosts and this gateway as the
cross-host router — which is exactly why the fabric treats workers as
opaque HTTP peers that can die, wedge, or lag.
"""

from __future__ import annotations

import http.client
import http.server
import json
import socket
import threading
import time
from typing import Callable, List, Optional, Tuple

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.obs.logging import get_logger
from mmlspark_tpu.obs import registry as obs_registry
from mmlspark_tpu.serving.fabric import FabricConfig, ServingFabric
from mmlspark_tpu.serving.faults import FaultInjector
from mmlspark_tpu.serving.server import ServingServer

log = get_logger("mmlspark_tpu.serving")

#: (status, reason, content-type, payload) of one forwarded exchange
_Result = Tuple[int, str, Optional[str], bytes]


class DistributedServingServer:
    """N ServingServer workers + a fault-tolerant routing gateway on one
    public port.

    handler_factory() is called once PER WORKER so each worker holds its own
    handler state (compiled model replica, locks). Pass a plain handler only
    if it is stateless/thread-safe.

    `fabric` tunes routing/retry/admission (serving/fabric.py FabricConfig);
    `worker_timeout` bounds every gateway->worker exchange (connect AND
    read) so a wedged worker costs one bounded timeout, not an OS-default
    TCP stall; `fault_injector` wires in the deterministic fault harness
    (serving/faults.py) for tests and the fault smoke bench.
    """

    def __init__(
        self,
        handler_factory: Callable[[], Callable[[DataFrame], DataFrame]],
        n_workers: int = 2,
        host: str = "127.0.0.1",
        port: int = 0,
        api_name: str = "serving",
        mode: str = "continuous",
        max_batch_size: int = 64,
        max_wait_ms: float = 5.0,
        request_timeout: float = 30.0,
        engine: str = "pipelined",
        in_flight_depth: int = 2,
        fabric: Optional[FabricConfig] = None,
        worker_timeout: Optional[float] = None,
        fault_injector: Optional[FaultInjector] = None,
    ):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.host = host
        self.api_name = api_name
        self._port = port
        self.handler_factory = handler_factory
        self.worker_timeout = (
            worker_timeout if worker_timeout is not None
            else request_timeout + 5.0
        )
        self._worker_kwargs = dict(
            host=host,
            port=0,
            api_name=api_name,
            mode=mode,
            max_batch_size=max_batch_size,
            max_wait_ms=max_wait_ms,
            request_timeout=request_timeout,
            engine=engine,
            in_flight_depth=in_flight_depth,
        )
        self.workers: List[ServingServer] = [
            self._make_worker() for _ in range(n_workers)
        ]
        self.fabric = ServingFabric(
            n_workers,
            config=fabric,
            health_fns=[self._health_fn(w) for w in self.workers],
            gateway_label=f"{api_name}-gw",
        )
        self._faults = fault_injector
        # keep-alive connections to workers, one per (gateway thread, worker);
        # the generation counter invalidates every thread's cached connection
        # to a slot when replace_worker swaps it
        self._local = threading.local()
        self._conn_gen: List[int] = [0] * n_workers
        self._hedge_pool = None
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._stopping = threading.Event()
        self._replace_lock = threading.Lock()

    def _make_worker(
        self, factory: Optional[Callable] = None
    ) -> ServingServer:
        return ServingServer(
            (factory or self.handler_factory)(), **self._worker_kwargs
        )

    @staticmethod
    def _health_fn(worker: ServingServer) -> Callable[[], bool]:
        return lambda: worker.health()[0]

    @property
    def port(self) -> int:
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self._port}/{self.api_name}"

    def inject_faults(self, injector: FaultInjector) -> FaultInjector:
        self._faults = injector
        return injector

    # -- gateway -> worker transport -------------------------------------------

    def _worker_conn(self, idx: int) -> http.client.HTTPConnection:
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        gen = self._conn_gen[idx]
        entry = conns.get(idx)
        if entry is not None:
            if entry[0] == gen:
                return entry[1]
            entry[1].close()  # slot was replaced: stale connection
        conn = http.client.HTTPConnection(
            self.workers[idx].host, self.workers[idx].port,
            timeout=self.worker_timeout,
        )
        conn.connect()
        # small writes both ways: Nagle + delayed ACK would add ~40 ms
        # per forwarded exchange (same fix as ServingServer's handler)
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conns[idx] = (gen, conn)
        return conn

    def _drop_conn(self, idx: int) -> None:
        conns = getattr(self._local, "conns", None)
        if conns:
            entry = conns.pop(idx, None)
            if entry is not None:
                entry[1].close()

    def _attempt(self, idx: int, method: str, path: str, body: bytes,
                 content_type: Optional[str]) -> _Result:
        """One forward to worker idx over the cached keep-alive connection.

        A stale keep-alive (the worker closed an idle connection) rebuilds
        and retries ONCE against the same worker — but, unlike the old
        gateway, the staleness is reported to the router as a failure
        signal first, so a worker that keeps dropping connections
        accumulates breaker failures instead of being silently retried
        forever. Timeouts are NOT retried here: a wedged worker won't
        answer a fresh connection either — surface to the failover policy.
        """
        if self._faults is not None:
            self._faults.intercept(idx, self.worker_timeout)
        headers = {"Content-Type": content_type or "application/json"}
        try:
            conn = self._worker_conn(idx)
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
        except (http.client.HTTPException, ConnectionError, OSError) as e:
            self._drop_conn(idx)
            if isinstance(e, socket.timeout):
                raise
            # soft signal: counted and visible, but only the hard path
            # (the rebuild failing too) feeds the breaker — a single stale
            # blip whose retry succeeds must not eject the worker
            self.fabric.record_failure(idx, kind="stale_conn", breaker=False)
            try:
                conn = self._worker_conn(idx)
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
            except (http.client.HTTPException, ConnectionError, OSError):
                # the rebuild failed too (worker dying mid-exchange):
                # don't leave the broken conn cached for this thread, or
                # every later forward pays a spurious stale_conn signal
                # plus a dead round-trip before rebuilding
                self._drop_conn(idx)
                raise
        return resp.status, resp.reason, resp.getheader("Content-Type"), payload

    # -- routing policy --------------------------------------------------------

    def _route_once(self, method: str, path: str, body: bytes,
                    content_type: Optional[str],
                    exclude: Tuple[int, ...]) -> Tuple[Optional[_Result], Optional[int]]:
        """One routed attempt: pick a worker, forward, feed the router.
        Returns (result, worker_idx); result is None on transport failure
        (the failure is already recorded), worker_idx is None when nothing
        was routable."""
        picked = self.fabric.pick_and_acquire(exclude)
        if picked is None and exclude:
            # every routable worker already failed this request; retrying
            # one beats an instant 502 (it may have been a stale conn blip)
            picked = self.fabric.pick_and_acquire(())
        if picked is None:
            return None, None
        idx, _probe = picked
        t0 = time.monotonic()
        try:
            result = self._attempt(idx, method, path, body, content_type)
        except (http.client.HTTPException, ConnectionError, OSError) as e:
            self.fabric.release(idx)
            self.fabric.record_failure(idx)
            log.warning("worker_failed", worker=idx, error=repr(e))
            return None, idx
        self.fabric.release(idx)
        latency_ms = (time.monotonic() - t0) * 1e3
        if result[0] == 503:
            # the worker itself is shedding/stopping: a failure signal for
            # the router AND grounds to fail over, same as a transport error
            self.fabric.record_failure(idx, kind="worker_503")
            return result, idx
        self.fabric.record_success(idx, latency_ms)
        return result, idx

    def _route_and_forward(self, method: str, path: str, body: bytes,
                           content_type: Optional[str]) -> _Result:
        """Forward with failover: budgeted retries against different
        workers with full-jitter backoff. Exhausted budget/attempts surface
        the last worker answer (a 503) or a 502."""
        cfg = self.fabric.config
        exclude: List[int] = []
        last_result: Optional[_Result] = None
        attempt = 0
        while True:
            result, idx = self._route_once(
                method, path, body, content_type, tuple(exclude)
            )
            if idx is None:
                self.fabric.shed("no_healthy_workers")
                return (
                    503, "Service Unavailable", "application/json",
                    b'{"error": "no healthy workers"}',
                )
            if result is not None and result[0] != 503:
                return result
            last_result = result or last_result
            exclude.append(idx)
            attempt += 1
            if attempt > cfg.max_retries or not self.fabric.try_retry():
                break
            time.sleep(self.fabric.backoff_s(attempt))
        if last_result is not None:
            return last_result
        return (
            502, "Bad Gateway", "application/json",
            b'{"error": "bad gateway: worker unreachable"}',
        )

    def _forward_api(self, method: str, path: str, body: bytes,
                     content_type: Optional[str]) -> _Result:
        """The api-route entry: plain failover, or tail-hedged failover
        when the fabric config enables hedging."""
        if self._hedge_pool is None:
            return self._route_and_forward(method, path, body, content_type)
        import concurrent.futures as cf

        primary = self._hedge_pool.submit(
            self._route_and_forward, method, path, body, content_type
        )
        done, _ = cf.wait([primary], timeout=self.fabric.hedge_delay_s())
        if done or not self.fabric.try_retry(kind="hedge"):
            return primary.result()
        hedge = self._hedge_pool.submit(
            self._route_and_forward, method, path, body, content_type
        )
        for fut in cf.as_completed([primary, hedge]):
            result = fut.result()
            if result[0] < 500:
                return result
        return result  # both 5xx: surface the last

    # -- drain / hot restart ---------------------------------------------------

    def drain(self, worker_idx: int, timeout: Optional[float] = None) -> bool:
        """Stop routing new work to worker_idx and wait for its in-flight
        (as seen by the gateway) to flush. Returns True when fully drained.
        The slot stays unroutable until `undrain`/`replace_worker`."""
        self.fabric.set_draining(worker_idx, True)
        return self.fabric.wait_drained(worker_idx, timeout)

    def undrain(self, worker_idx: int) -> None:
        self.fabric.set_draining(worker_idx, False)

    def replace_worker(
        self,
        worker_idx: int,
        handler_factory: Optional[Callable] = None,
        timeout: Optional[float] = None,
    ) -> ServingServer:
        """Zero-downtime hot swap of one worker slot: start the
        replacement FIRST (compile warm-up happens off the serving path),
        drain the incumbent, atomically install the replacement (fresh
        breaker/EWMA state, every thread's cached connection invalidated),
        then tear the incumbent down. Other workers carry the load during
        the drain window, so with n_workers >= 2 no request ever fails."""
        with self._replace_lock:
            replacement = self._make_worker(handler_factory)
            replacement.start()
            self.fabric.set_draining(worker_idx, True)
            drained = self.fabric.wait_drained(worker_idx, timeout)
            if not drained:
                log.warning("worker_drain_timeout", worker=worker_idx,
                            action="swapping anyway")
            old = self.workers[worker_idx]
            self.workers[worker_idx] = replacement
            self._conn_gen[worker_idx] += 1
            if self._faults is not None:
                # injected faults are keyed by slot; the replacement must
                # not inherit the incumbent's kill/wedge poison (this is
                # how a killed worker comes back: replace, not heal)
                self._faults.heal(worker_idx)
            self.fabric.reset_worker(
                worker_idx, health_fn=self._health_fn(replacement)
            )
            old.stop()
            log.info(
                "worker_hot_swapped", worker=worker_idx,
                old_port=old.port, new_port=replacement.port,
            )
            return replacement

    # -- the gateway server ----------------------------------------------------

    def start(self) -> "DistributedServingServer":
        for w in self.workers:
            w.start()
        if self.fabric.config.hedge:
            from concurrent.futures import ThreadPoolExecutor

            # sized to the admission ceiling, not the worker count: every
            # hedged request holds a pool thread for its primary (the pool
            # is what races primary vs hedge — an inline primary would pin
            # the handler thread for a wedged worker's full timeout even
            # after the hedge answered), so a small pool would cap gateway
            # concurrency below the admission limit and queue primaries.
            # Threads spawn on demand; real concurrency is bounded by
            # admission control, not this ceiling.
            self._hedge_pool = ThreadPoolExecutor(
                max_workers=2 * int(self.fabric.config.admission_max),
                thread_name_prefix=f"gw-hedge-{self.api_name}",
            )
        outer = self

        class Gateway(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):
                log.debug("gateway_http_access",
                          client=self.address_string(),
                          line=(fmt % args) if args else fmt)

            def _send_body(self, code: int, reason: str, payload: bytes,
                           content_type: str,
                           extra_headers: Tuple[Tuple[str, str], ...] = ()) -> None:
                self.send_response(code, reason)
                self.send_header("Content-Type", content_type)
                for name, value in extra_headers:
                    self.send_header(name, value)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                route = self.path.split("?", 1)[0].rstrip("/")
                # drain the body FIRST, on every route: on a keep-alive
                # connection unread bytes would be parsed as the next
                # request line, corrupting the connection (this includes
                # the 404 and error reply paths, which used to skip it)
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                # observability surfaces: workers share this process, so
                # the gateway serves the shared registry directly and
                # aggregates per-worker liveness (docs/observability.md)
                if route == "/metrics":
                    parts = self.path.split("?", 1)
                    body, ctype = obs_registry().render_scrape(
                        parts[1] if len(parts) > 1 else ""
                    )
                    self._send_body(200, "OK", body, ctype)
                    return
                if route == "/healthz":
                    code, payload = outer._healthz()
                    self._send_body(
                        code, "OK" if code == 200 else "Service Unavailable",
                        payload, "application/json",
                    )
                    return
                # flight-recorder surfaces: workers share this process, so
                # the gateway serves the shared profiler ring and tracer
                # directly, like it does /metrics (docs/observability.md)
                if route == "/debug/flight":
                    from mmlspark_tpu.obs.profiler import device_profiler

                    self._send_body(
                        200, "OK",
                        json.dumps(device_profiler().flight(),
                                   sort_keys=True).encode("utf-8"),
                        "application/json",
                    )
                    return
                if route == "/debug/trace":
                    from mmlspark_tpu.obs import tracer as obs_tracer

                    self._send_body(
                        200, "OK",
                        json.dumps(obs_tracer().chrome_trace()
                                   ).encode("utf-8"),
                        "application/json",
                    )
                    return
                if route != f"/{outer.api_name}":
                    self.send_response(404, "Not Found")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if outer._stopping.is_set():
                    self._send_body(
                        503, "Service Unavailable",
                        b'{"error": "gateway stopping"}', "application/json",
                    )
                    return
                # admission control: shed NOW rather than queue to death.
                # admission.in_flight doubles as the gateway's in-flight
                # meter (stop() waits on it).
                if not outer.fabric.admission.try_acquire():
                    outer.fabric.shed("admission")
                    self._send_body(
                        429, "Too Many Requests",
                        b'{"error": "overloaded, retry later"}',
                        "application/json",
                        extra_headers=(("Retry-After", "1"),),
                    )
                    return
                outer.fabric.fund_retry_budget()
                t0 = time.monotonic()
                try:
                    status, reason, ct, payload = outer._forward_api(
                        self.command, self.path, body,
                        self.headers.get("Content-Type"),
                    )
                except Exception as e:  # defensive: policy must not 500 the gateway
                    log.exception("gateway_forward_failed")
                    status, reason = 502, "Bad Gateway"
                    ct = "application/json"
                    payload = json.dumps(
                        {"error": f"bad gateway: {e!r}"}
                    ).encode("utf-8")
                latency_ms = (time.monotonic() - t0) * 1e3
                outer.fabric.admission.release(
                    latency_ms, overloaded=status in (502, 503)
                )
                self._send_body(status, reason, payload,
                                ct or "application/json")

            do_GET = do_POST
            do_PUT = do_POST

        from mmlspark_tpu.serving.server import _GatewayHTTPServer

        self._httpd = _GatewayHTTPServer((self.host, self._port), Gateway)
        self._port = self._httpd.server_address[1]
        httpd = self._httpd
        threading.Thread(
            target=lambda: httpd.serve_forever(poll_interval=0.05),
            daemon=True,
        ).start()
        log.info(
            "distributed_serving_started", url=self.url,
            workers=len(self.workers),
            ports=[w.port for w in self.workers],
        )
        return self

    def _healthz(self) -> Tuple[int, bytes]:
        """Gateway liveness: 200 while at least one worker is routable (the
        gateway can still serve — that is the whole point of the fabric),
        503 when none are or the gateway is stopping. `status` grades it:
        ok (everything green) / degraded (serving around failures) /
        stopping / unavailable."""
        healths = [w.health() for w in self.workers]
        router = self.fabric.snapshot()
        routable = [w for w in router["workers"] if w["healthy"]]
        stopping = self._stopping.is_set()
        if stopping:
            status, code = "stopping", 503
        elif not routable:
            status, code = "unavailable", 503
        elif len(routable) < len(self.workers) or not all(
            h[0] for h in healths
        ):
            status, code = "degraded", 200
        else:
            status, code = "ok", 200
        body = json.dumps({
            "status": status,
            "workers": [h[1] for h in healths],
            "router": router,
        }, sort_keys=True).encode("utf-8")
        return code, body

    def stop(self, drain_timeout: float = 10.0) -> None:
        """Graceful stop: refuse new work (503), wait for in-flight gateway
        requests to complete (bounded by drain_timeout), then tear down the
        gateway, the workers, and the fabric's registry hooks."""
        self._stopping.set()
        deadline = time.monotonic() + drain_timeout
        while (
            self.fabric.admission.in_flight > 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._hedge_pool is not None:
            self._hedge_pool.shutdown(wait=False)
            self._hedge_pool = None
        for w in self.workers:
            w.stop()
        self.fabric.close()

    def __enter__(self) -> "DistributedServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
