"""Distributed serving: a worker pool behind a fault-tolerant routing gateway.

Reference: io/http/src/main/scala/DistributedHTTPSource.scala:89-242 — one
JVMSharedServer per executor, each binding its own port and scoring its own
partition, with a driver-side gateway (PortForwarding.scala:12) fronting the
pool — and HTTPSourceV2.scala:167-404's continuous per-partition commit (no
cross-partition lock). The reference survives executor churn because the
driver only routes to partitions that are alive; this gateway recreates
that property without a driver through the serving fabric
(serving/fabric.py):

- **health-driven routing**: power-of-two-choices among workers that are
  (a) green on their own PR 5 ``health()`` signal, (b) closed on their
  circuit breaker, and (c) not draining; EWMA latency + in-flight counts
  break the choice. A worker that fails at the transport level (connect
  refused, read timeout) accumulates breaker failures and is ejected;
  after ``open_secs`` single probe requests test it back in.
- **retry + hedge**: a failed forward retries against a *different* worker
  with full-jitter backoff, capped by a retry-budget token bucket so
  retries can never amplify an overload; optional tail hedging duplicates
  a request to a second worker once it outlives the observed p95.
- **admission control + load shedding**: an AIMD concurrency limit at the
  gateway edge; excess load fast-fails with 429 + Retry-After instead of
  queueing toward the request timeout (`serving_shed_requests_total`).
- **graceful drain / hot restart**: ``drain(idx)`` stops routing to a
  worker and flushes its in-flight; ``replace_worker(idx)`` starts a
  replacement first, drains, atomically swaps the slot, then tears the old
  worker down — zero-downtime model refresh.

TPU re-design: the partition==executor mapping becomes worker==replica.
Each worker owns a PRIVATE handler instance (its own compiled model, its
own model lock), so continuous-mode scoring never serializes across
workers. Workers are in-process threads sharing the chip; multi-host scale
uses the same topology with workers on peer hosts and this gateway as the
cross-host router — which is exactly why the fabric treats workers as
opaque HTTP peers that can die, wedge, or lag.
"""

from __future__ import annotations

import http.client
import http.server
import json
import socket
import threading
import time
import urllib.parse
from typing import Any, Callable, Dict, List, Optional, Tuple

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.obs.logging import get_logger
from mmlspark_tpu.obs import registry as obs_registry
from mmlspark_tpu.obs import tracer as obs_tracer
from mmlspark_tpu.obs.federation import FederationConfig, Federator
from mmlspark_tpu.obs.slo import slo_monitor
from mmlspark_tpu.obs.tracing import (
    Span,
    extract_context,
    inject_context,
    stitch_trace_trees,
)
from mmlspark_tpu.serving.fabric import (
    CircuitBreaker,
    FabricConfig,
    ServingFabric,
)
from mmlspark_tpu.serving.faults import FaultInjector
from mmlspark_tpu.serving.server import (
    ServingServer,
    _memory_payload,
    _trace_payload,
)

log = get_logger("mmlspark_tpu.serving")

#: (status, reason, content-type, payload) of one forwarded exchange
_Result = Tuple[int, str, Optional[str], bytes]


class DistributedServingServer:
    """N ServingServer workers + a fault-tolerant routing gateway on one
    public port.

    handler_factory() is called once PER WORKER so each worker holds its own
    handler state (compiled model replica, locks). Pass a plain handler only
    if it is stateless/thread-safe.

    `fabric` tunes routing/retry/admission (serving/fabric.py FabricConfig);
    `worker_timeout` bounds every gateway->worker exchange (connect AND
    read) so a wedged worker costs one bounded timeout, not an OS-default
    TCP stall; `fault_injector` wires in the deterministic fault harness
    (serving/faults.py) for tests and the fault smoke bench.
    """

    def __init__(
        self,
        handler_factory: Callable[[], Callable[[DataFrame], DataFrame]],
        n_workers: int = 2,
        host: str = "127.0.0.1",
        port: int = 0,
        api_name: str = "serving",
        mode: str = "continuous",
        max_batch_size: int = 64,
        max_wait_ms: float = 5.0,
        request_timeout: float = 30.0,
        engine: str = "pipelined",
        in_flight_depth: int = 2,
        fabric: Optional[FabricConfig] = None,
        worker_timeout: Optional[float] = None,
        fault_injector: Optional[FaultInjector] = None,
        slow_request_ms: Optional[float] = None,
        federation: Optional[FederationConfig] = None,
    ):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.host = host
        self.api_name = api_name
        self._port = port
        self.handler_factory = handler_factory
        self.worker_timeout = (
            worker_timeout if worker_timeout is not None
            else request_timeout + 5.0
        )
        self._worker_kwargs = dict(
            host=host,
            port=0,
            api_name=api_name,
            mode=mode,
            max_batch_size=max_batch_size,
            max_wait_ms=max_wait_ms,
            request_timeout=request_timeout,
            engine=engine,
            in_flight_depth=in_flight_depth,
            # workers share the gateway's slow threshold: a gateway-routed
            # slow request then logs BOTH sides under one propagated
            # trace id (gateway line: worker/attempts/queue-wait; worker
            # line: stage decomposition)
            slow_request_ms=slow_request_ms,
        )
        self.workers: List[ServingServer] = [
            self._make_worker() for _ in range(n_workers)
        ]
        self.fabric = ServingFabric(
            n_workers,
            config=fabric,
            health_fns=[self._health_fn(w) for w in self.workers],
            gateway_label=f"{api_name}-gw",
        )
        self._faults = fault_injector
        # gateway-edge observability: the gateway is an HTTP edge like any
        # ServingServer, so it reports into the SAME latency family (its
        # engine label is the fabric's gateway label) and the SLO monitor
        # sees gateway-visible outcomes (shed 429s, forwarded 5xx) that
        # never reach a worker's histogram; slow_request_ms logs actionable
        # slow lines (worker, attempts, queue wait) without opening traces
        self.slow_request_ms = slow_request_ms
        self._tracer = obs_tracer()
        self._lat_hist = obs_registry().histogram(
            "serving_request_latency_ms",
            "End-to-end request latency at the HTTP edge",
            ("engine", "code"),
        )
        # keep-alive connections to workers, one per (gateway thread, worker);
        # the generation counter invalidates every thread's cached connection
        # to a slot when replace_worker swaps it
        self._local = threading.local()
        self._conn_gen: List[int] = [0] * n_workers
        self._hedge_pool = None
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        self._stopping = threading.Event()
        self._replace_lock = threading.Lock()
        # cross-process observability federation (obs/federation.py): the
        # gateway scrapes each worker's /metrics, re-exports the union
        # under proc labels, fans /debug/* out with ?scope=cluster, and
        # feeds worker request outcomes to the SLO monitor under the
        # cluster engine label — `cluster_engine` is what an SLOSpec
        # targets to burn on CLUSTER-wide outcomes, not just this edge
        self.federation_config = federation or FederationConfig()
        self.federator: Optional[Federator] = None
        self.cluster_engine: Optional[str] = None
        if self.federation_config.enabled:
            self.cluster_engine = (
                self.federation_config.slo_engine
                or f"{self.fabric.gateway_label}-cluster"
            )
            self.federator = Federator(
                obs_registry(),
                self.federation_config,
                slo_engine=self.cluster_engine,
                slo_exclude_engines=(self.fabric.gateway_label,),
                gateway_label=self.fabric.gateway_label,
            )

    def _make_worker(
        self, factory: Optional[Callable] = None
    ) -> ServingServer:
        return ServingServer(
            (factory or self.handler_factory)(), **self._worker_kwargs
        )

    def _health_fn(self, worker: ServingServer) -> Callable[[], bool]:
        """Router health for one worker: its own health() signal AND
        federation-scrape freshness — a worker whose metrics have been
        unscrapeable for `stale_after_intervals` scrape intervals is
        suspect even if its socket still accepts connections. Resolved
        lazily so hot-swapped replacements and late federator wiring both
        see current state."""
        def check() -> bool:
            if not worker.health()[0]:
                return False
            fed = self.federator
            if fed is None or self._httpd is None:
                return True
            try:
                idx = self.workers.index(worker)
            except ValueError:  # replaced mid-check: not routable anyway
                return True
            return not fed.is_stale(f"worker-{idx}")
        return check

    @property
    def port(self) -> int:
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self._port}/{self.api_name}"

    def inject_faults(self, injector: FaultInjector) -> FaultInjector:
        self._faults = injector
        return injector

    # -- gateway -> worker transport -------------------------------------------

    def _worker_conn(self, idx: int) -> http.client.HTTPConnection:
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        gen = self._conn_gen[idx]
        entry = conns.get(idx)
        if entry is not None:
            if entry[0] == gen:
                return entry[1]
            entry[1].close()  # slot was replaced: stale connection
        conn = http.client.HTTPConnection(
            self.workers[idx].host, self.workers[idx].port,
            timeout=self.worker_timeout,
        )
        conn.connect()
        # small writes both ways: Nagle + delayed ACK would add ~40 ms
        # per forwarded exchange (same fix as ServingServer's handler)
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conns[idx] = (gen, conn)
        return conn

    def _drop_conn(self, idx: int) -> None:
        conns = getattr(self._local, "conns", None)
        if conns:
            entry = conns.pop(idx, None)
            if entry is not None:
                entry[1].close()

    # -- federation transport --------------------------------------------------

    def _fed_fetch(self, idx: int) -> Callable[[str], Tuple[int, bytes]]:
        """Federation fetcher for worker slot `idx`, over the same cached
        keep-alive transport as API forwards (the scrape loop runs on its
        own thread, so it owns its own thread-local connections). The
        scrape timeout replaces the forward timeout for the exchange and
        is restored after — handler threads share connections between
        ``?scope=cluster`` fan-outs and API forwards. Injected worker
        faults are honored read-only: a killed/wedged slot fails the
        scrape with the same exception a dead/hung peer produces, WITHOUT
        consuming one-shot transport faults armed for API traffic."""
        def fetch(path: str) -> Tuple[int, bytes]:
            if self._faults is not None:
                mode = self._faults.mode(idx)
                if mode in ("dead", "drop"):
                    raise ConnectionRefusedError(
                        f"worker {idx} transport poisoned ({mode})"
                    )
                if mode == "wedged":
                    raise socket.timeout(f"worker {idx} wedged")
            timeout = self.federation_config.scrape_timeout_s
            conn = self._worker_conn(idx)
            try:
                conn.sock.settimeout(timeout)
                conn.request("GET", path, headers=inject_context(None, {}))
                resp = conn.getresponse()
                body = resp.read()
                conn.sock.settimeout(self.worker_timeout)
            except (http.client.HTTPException, ConnectionError, OSError):
                self._drop_conn(idx)
                raise
            return resp.status, body
        return fetch

    def _extra_fetch(
        self, host: str, port: int
    ) -> Callable[[str], Tuple[int, bytes]]:
        """Fetcher for a federation-only extra target (FederationConfig.
        extra_targets): a peer the gateway observes but never routes API
        traffic to, e.g. a worker in another process. One short-lived
        connection per fetch — these are off the routing hot path and a
        cached socket to a foreign process would outlive its restarts."""
        def fetch(path: str) -> Tuple[int, bytes]:
            conn = http.client.HTTPConnection(
                host, port, timeout=self.federation_config.scrape_timeout_s
            )
            try:
                conn.request("GET", path, headers=inject_context(None, {}))
                resp = conn.getresponse()
                return resp.status, resp.read()
            finally:
                conn.close()
        return fetch

    def _attempt(self, idx: int, method: str, path: str, body: bytes,
                 content_type: Optional[str],
                 span: Optional[Span] = None) -> _Result:
        """One forward to worker idx over the cached keep-alive connection.

        `span` is this attempt's span: its W3C traceparent is injected into
        the forwarded headers so the worker's http span parents under it —
        the cross-process link graftcheck's untraced-cross-process-call
        rule pins in place. A stale keep-alive (the worker closed an idle
        connection) rebuilds and retries ONCE against the same worker —
        but, unlike the old gateway, the staleness is reported to the
        router as a failure signal first (and attached as a span event), so
        a worker that keeps dropping connections accumulates breaker
        failures instead of being silently retried forever. Timeouts are
        NOT retried here: a wedged worker won't answer a fresh connection
        either — surface to the failover policy.
        """
        if self._faults is not None:
            self._faults.intercept(idx, self.worker_timeout)
        headers = inject_context(
            span, {"Content-Type": content_type or "application/json"}
        )
        try:
            conn = self._worker_conn(idx)
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
        except (http.client.HTTPException, ConnectionError, OSError) as e:
            self._drop_conn(idx)
            if isinstance(e, socket.timeout):
                raise
            # soft signal: counted and visible, but only the hard path
            # (the rebuild failing too) feeds the breaker — a single stale
            # blip whose retry succeeds must not eject the worker
            self.fabric.record_failure(idx, kind="stale_conn", breaker=False)
            if span is not None and span.recording:
                span.add_event("stale_conn_rebuild", worker=idx,
                               error=repr(e))
            try:
                conn = self._worker_conn(idx)
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
            except (http.client.HTTPException, ConnectionError, OSError):
                # the rebuild failed too (worker dying mid-exchange):
                # don't leave the broken conn cached for this thread, or
                # every later forward pays a spurious stale_conn signal
                # plus a dead round-trip before rebuilding
                self._drop_conn(idx)
                raise
        return resp.status, resp.reason, resp.getheader("Content-Type"), payload

    # -- routing policy --------------------------------------------------------

    def _route_once(self, method: str, path: str, body: bytes,
                    content_type: Optional[str],
                    exclude: Tuple[int, ...],
                    parent_span: Optional[Span] = None,
                    attempt_no: int = 1,
                    kind: str = "primary") -> Tuple[Optional[_Result], Optional[int]]:
        """One routed attempt: pick a worker, forward, feed the router.
        Every attempt — primary, retry, hedge, half-open probe — is a
        distinct child span under the gateway's request span, tagged with
        worker index, attempt number and breaker state; breaker
        transitions it causes attach as span events. Returns (result,
        worker_idx); result is None on transport failure (the failure is
        already recorded), worker_idx is None when nothing was routable."""
        picked = self.fabric.pick_and_acquire(exclude)
        if picked is None and exclude:
            # every routable worker already failed this request; retrying
            # one beats an instant 502 (it may have been a stale conn blip)
            picked = self.fabric.pick_and_acquire(())
        if picked is None:
            return None, None
        idx, probe = picked
        tr = self._tracer
        span = tr.start_span(
            "attempt", parent=parent_span,
            attrs={"worker": idx, "attempt": attempt_no, "kind": kind,
                   "probe": probe,
                   "breaker": self.fabric.breaker_state(idx)},
        )
        t0 = time.monotonic()
        try:
            result = self._attempt(idx, method, path, body, content_type,
                                   span=span)
        except (http.client.HTTPException, ConnectionError, OSError) as e:
            self.fabric.release(idx)
            state = self.fabric.record_failure(idx)
            if span.recording:
                span.set_attribute("error", repr(e))
                if state != CircuitBreaker.CLOSED:
                    span.add_event("breaker_transition", worker=idx,
                                   to=state)
            tr.end_span(span)
            log.warning("worker_failed", worker=idx, error=repr(e),
                        trace_id=span.trace_id if span.recording else None)
            return None, idx
        self.fabric.release(idx)
        latency_ms = (time.monotonic() - t0) * 1e3
        if span.recording:
            span.set_attribute("status_code", result[0])
        if result[0] == 503:
            # the worker itself is shedding/stopping: a failure signal for
            # the router AND grounds to fail over, same as a transport error
            state = self.fabric.record_failure(idx, kind="worker_503")
            if span.recording:
                span.set_attribute("error", "worker 503")
                if state != CircuitBreaker.CLOSED:
                    span.add_event("breaker_transition", worker=idx,
                                   to=state)
            tr.end_span(span)
            return result, idx
        self.fabric.record_success(idx, latency_ms)
        tr.end_span(span)
        return result, idx

    def _route_and_forward(self, method: str, path: str, body: bytes,
                           content_type: Optional[str],
                           parent_span: Optional[Span] = None,
                           info: Optional[Dict[str, Any]] = None,
                           first_kind: str = "primary") -> _Result:
        """Forward with failover: budgeted retries against different
        workers with full-jitter backoff. Exhausted budget/attempts surface
        the last worker answer (a 503) or a 502. `info` accumulates the
        routing story (attempts, workers tried, total backoff wait) for the
        gateway's slow_request log line; retries mark the trace interesting
        so tail retention pins the whole tree. `first_kind` tags the first
        attempt's span ("primary", or "hedge" on the hedged branch) so the
        assembled tree distinguishes the hedge from the request it races."""
        cfg = self.fabric.config
        exclude: List[int] = []
        last_result: Optional[_Result] = None
        attempt = 0
        info = info if info is not None else {}
        tr = self._tracer
        while True:
            result, idx = self._route_once(
                method, path, body, content_type, tuple(exclude),
                parent_span=parent_span, attempt_no=attempt + 1,
                kind="retry" if attempt else first_kind,
            )
            if idx is None:
                self.fabric.shed("no_healthy_workers")
                if parent_span is not None and parent_span.recording:
                    parent_span.add_event("shed",
                                          reason="no_healthy_workers")
                    tr.mark_trace(parent_span.trace_id, "shed")
                return (
                    503, "Service Unavailable", "application/json",
                    b'{"error": "no healthy workers"}',
                )
            info["attempts"] = info.get("attempts", 0) + 1
            info.setdefault("workers", []).append(idx)
            if result is not None and result[0] != 503:
                info["worker"] = idx
                return result
            last_result = result or last_result
            exclude.append(idx)
            attempt += 1
            if attempt > cfg.max_retries or not self.fabric.try_retry():
                break
            backoff_s = self.fabric.backoff_s(attempt)
            if parent_span is not None and parent_span.recording:
                parent_span.add_event(
                    "retry", attempt=attempt, failed_worker=idx,
                    backoff_ms=round(backoff_s * 1e3, 2),
                )
                tr.mark_trace(parent_span.trace_id, "retry")
            info["backoff_ms"] = info.get("backoff_ms", 0.0) + backoff_s * 1e3
            time.sleep(backoff_s)
        info["worker"] = exclude[-1] if exclude else None
        if last_result is not None:
            return last_result
        return (
            502, "Bad Gateway", "application/json",
            b'{"error": "bad gateway: worker unreachable"}',
        )

    def _forward_api(self, method: str, path: str, body: bytes,
                     content_type: Optional[str],
                     parent_span: Optional[Span] = None,
                     info: Optional[Dict[str, Any]] = None) -> _Result:
        """The api-route entry: plain failover, or tail-hedged failover
        when the fabric config enables hedging. Hedge launch and win/loss
        attach as span events on the request tree."""
        info = info if info is not None else {}
        if self._hedge_pool is None:
            return self._route_and_forward(method, path, body, content_type,
                                           parent_span, info)
        import concurrent.futures as cf

        p_info: Dict[str, Any] = {}
        primary = self._hedge_pool.submit(
            self._route_and_forward, method, path, body, content_type,
            parent_span, p_info,
        )
        delay_s = self.fabric.hedge_delay_s()
        done, _ = cf.wait([primary], timeout=delay_s)
        if done or not self.fabric.try_retry(kind="hedge"):
            result = primary.result()
            info.update(p_info)
            return result
        tr = self._tracer
        if parent_span is not None and parent_span.recording:
            parent_span.add_event("hedge_launched",
                                  delay_ms=round(delay_s * 1e3, 2))
            tr.mark_trace(parent_span.trace_id, "hedge")
        h_info: Dict[str, Any] = {}
        hedge = self._hedge_pool.submit(
            self._route_and_forward, method, path, body, content_type,
            parent_span, h_info, first_kind="hedge",
        )
        info["hedged"] = True
        for fut in cf.as_completed([primary, hedge]):
            result = fut.result()
            if result[0] < 500:
                winner = "primary" if fut is primary else "hedge"
                if parent_span is not None and parent_span.recording:
                    parent_span.add_event("hedge_result", winner=winner,
                                          status=result[0])
                # best-effort merge: the loser may still be mutating its
                # own info dict — never read it for anything load-bearing
                info.update(p_info if fut is primary else h_info)
                return result
        if parent_span is not None and parent_span.recording:
            parent_span.add_event("hedge_result", winner="none",
                                  status=result[0])
        info.update(p_info)
        return result  # both 5xx: surface the last

    # -- drain / hot restart ---------------------------------------------------

    def drain(self, worker_idx: int, timeout: Optional[float] = None) -> bool:
        """Stop routing new work to worker_idx and wait for its in-flight
        (as seen by the gateway) to flush. Returns True when fully drained.
        The slot stays unroutable until `undrain`/`replace_worker`."""
        self.fabric.set_draining(worker_idx, True)
        return self.fabric.wait_drained(worker_idx, timeout)

    def undrain(self, worker_idx: int) -> None:
        self.fabric.set_draining(worker_idx, False)

    def replace_worker(
        self,
        worker_idx: int,
        handler_factory: Optional[Callable] = None,
        timeout: Optional[float] = None,
    ) -> ServingServer:
        """Zero-downtime hot swap of one worker slot: start the
        replacement FIRST (compile warm-up happens off the serving path),
        drain the incumbent, atomically install the replacement (fresh
        breaker/EWMA state, every thread's cached connection invalidated),
        then tear the incumbent down. Other workers carry the load during
        the drain window, so with n_workers >= 2 no request ever fails."""
        with self._replace_lock:
            replacement = self._make_worker(handler_factory)
            replacement.start()
            self.fabric.set_draining(worker_idx, True)
            drained = self.fabric.wait_drained(worker_idx, timeout)
            if not drained:
                log.warning("worker_drain_timeout", worker=worker_idx,
                            action="swapping anyway")
            old = self.workers[worker_idx]
            self.workers[worker_idx] = replacement
            self._conn_gen[worker_idx] += 1
            if self._faults is not None:
                # injected faults are keyed by slot; the replacement must
                # not inherit the incumbent's kill/wedge poison (this is
                # how a killed worker comes back: replace, not heal)
                self._faults.heal(worker_idx)
            self.fabric.reset_worker(
                worker_idx, health_fn=self._health_fn(replacement)
            )
            old.stop()
            log.info(
                "worker_hot_swapped", worker=worker_idx,
                old_port=old.port, new_port=replacement.port,
            )
            return replacement

    # -- the gateway server ----------------------------------------------------

    def start(self) -> "DistributedServingServer":
        for w in self.workers:
            w.start()
        if self.federator is not None:
            targets: Dict[str, Callable[[str], Tuple[int, bytes]]] = {
                f"worker-{i}": self._fed_fetch(i)
                for i in range(len(self.workers))
            }
            for j, (ehost, eport) in enumerate(
                self.federation_config.extra_targets
            ):
                targets[f"extra-{j}"] = self._extra_fetch(ehost, int(eport))
            self.federator.set_targets(targets)
            fed = self.federator

            def _annotate(idx: int) -> Dict[str, Any]:
                name = f"worker-{idx}"
                return {
                    "scrape_staleness_s": round(fed.staleness_s(name), 3),
                    "scrape_stale": fed.is_stale(name),
                }

            self.fabric.set_worker_annotator(_annotate)
            self.federator.start()
        if self.fabric.config.hedge:
            from concurrent.futures import ThreadPoolExecutor

            # sized to the admission ceiling, not the worker count: every
            # hedged request holds a pool thread for its primary (the pool
            # is what races primary vs hedge — an inline primary would pin
            # the handler thread for a wedged worker's full timeout even
            # after the hedge answered), so a small pool would cap gateway
            # concurrency below the admission limit and queue primaries.
            # Threads spawn on demand; real concurrency is bounded by
            # admission control, not this ceiling.
            self._hedge_pool = ThreadPoolExecutor(
                max_workers=2 * int(self.fabric.config.admission_max),
                thread_name_prefix=f"gw-hedge-{self.api_name}",
            )
        outer = self

        class Gateway(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):
                log.debug("gateway_http_access",
                          client=self.address_string(),
                          line=(fmt % args) if args else fmt)

            def _send_body(self, code: int, reason: str, payload: bytes,
                           content_type: str,
                           extra_headers: Tuple[Tuple[str, str], ...] = ()) -> None:
                self.send_response(code, reason)
                self.send_header("Content-Type", content_type)
                for name, value in extra_headers:
                    self.send_header(name, value)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                route = self.path.split("?", 1)[0].rstrip("/")
                # drain the body FIRST, on every route: on a keep-alive
                # connection unread bytes would be parsed as the next
                # request line, corrupting the connection (this includes
                # the 404 and error reply paths, which used to skip it)
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                # observability surfaces: the gateway serves the FEDERATED
                # view — the union of its local registry and every scraped
                # worker, per-proc series plus cluster aggregates
                # (docs/observability.md "Federation")
                if route == "/metrics":
                    parts = self.path.split("?", 1)
                    query = parts[1] if len(parts) > 1 else ""
                    if outer.federator is not None:
                        body, ctype = outer.federator.render_scrape(query)
                    else:
                        body, ctype = obs_registry().render_scrape(query)
                    self._send_body(200, "OK", body, ctype)
                    return
                if route == "/healthz":
                    code, payload = outer._healthz()
                    self._send_body(
                        code, "OK" if code == 200 else "Service Unavailable",
                        payload, "application/json",
                    )
                    return
                # flight-recorder surfaces: local payload by default;
                # ?scope=cluster fans out to every federation target with
                # per-worker timeout + partial-result semantics (a dead
                # worker is an explicit errors[] entry, never a hang) and
                # merges keyed by process identity (docs/observability.md)
                if route == "/debug/flight":
                    from mmlspark_tpu.obs.profiler import device_profiler

                    payload: Any = device_profiler().flight()
                    if outer._cluster_scope(self.path):
                        payload = outer.federator.fanout_debug(
                            outer._strip_scope(self.path), payload
                        )
                    self._send_body(
                        200, "OK",
                        json.dumps(payload, sort_keys=True).encode("utf-8"),
                        "application/json",
                    )
                    return
                if route == "/debug/memory":
                    payload = _memory_payload(self.path)
                    if outer._cluster_scope(self.path):
                        payload = outer.federator.fanout_debug(
                            outer._strip_scope(self.path), payload
                        )
                    self._send_body(
                        200, "OK",
                        json.dumps(payload, sort_keys=True).encode("utf-8"),
                        "application/json",
                    )
                    return
                if route == "/debug/trace":
                    # ?trace_id= serves the assembled cross-hop tree
                    # (gateway root -> attempts -> worker stages); no
                    # query keeps the whole-ring Chrome-trace dump. With
                    # scope=cluster a trace_id lookup fans out and returns
                    # ONE stitched tree spanning every process that held
                    # spans of the trace (traceparent supplied the links)
                    payload = _trace_payload(self.path)
                    if outer._cluster_scope(self.path):
                        fwd = outer._strip_scope(self.path)
                        agg = outer.federator.fanout_debug(fwd, payload)
                        tid = payload.get("trace_id")
                        if tid:
                            stitched = stitch_trace_trees(
                                tid, list(agg["procs"].values())
                            )
                            stitched["scope"] = "cluster"
                            stitched["errors"] = agg["errors"]
                            payload = stitched
                        else:
                            payload = agg
                    self._send_body(
                        200, "OK",
                        json.dumps(payload).encode("utf-8"),
                        "application/json",
                    )
                    return
                if route != f"/{outer.api_name}":
                    self.send_response(404, "Not Found")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if outer._stopping.is_set():
                    self._send_body(
                        503, "Service Unavailable",
                        b'{"error": "gateway stopping"}', "application/json",
                    )
                    return
                # the gateway's root span: every fabric decision this
                # request triggers (attempts, retries, hedges, sheds,
                # breaker trips) hangs off it, and its traceparent rides
                # to the worker so the worker's http/parse/score/reply
                # spans join the SAME tree. An upstream caller's own
                # traceparent is honored — the gateway can itself be a hop.
                gw_span = outer._tracer.start_span(
                    "gateway", context=extract_context(self.headers),
                    attrs={"path": self.path, "method": self.command,
                           "gateway": outer.fabric.gateway_label},
                )
                t0 = time.monotonic()
                # admission control: shed NOW rather than queue to death.
                # admission.in_flight doubles as the gateway's in-flight
                # meter (stop() waits on it).
                if not outer.fabric.admission.try_acquire():
                    outer.fabric.shed("admission")
                    if gw_span.recording:
                        gw_span.add_event("shed", reason="admission")
                        outer._tracer.mark_trace(gw_span.trace_id, "shed")
                    outer._finish_gateway(gw_span, 429, t0, None)
                    self._send_body(
                        429, "Too Many Requests",
                        b'{"error": "overloaded, retry later"}',
                        "application/json",
                        extra_headers=(("Retry-After", "1"),)
                        + outer._trace_header(gw_span),
                    )
                    return
                outer.fabric.fund_retry_budget()
                route_info: Dict[str, Any] = {}
                try:
                    status, reason, ct, payload = outer._forward_api(
                        self.command, self.path, body,
                        self.headers.get("Content-Type"),
                        gw_span, route_info,
                    )
                except Exception as e:  # defensive: policy must not 500 the gateway
                    log.exception("gateway_forward_failed")
                    status, reason = 502, "Bad Gateway"
                    ct = "application/json"
                    payload = json.dumps(
                        {"error": f"bad gateway: {e!r}"}
                    ).encode("utf-8")
                latency_ms = (time.monotonic() - t0) * 1e3
                outer.fabric.admission.release(
                    latency_ms, overloaded=status in (502, 503)
                )
                outer._finish_gateway(gw_span, status, t0, route_info)
                self._send_body(status, reason, payload,
                                ct or "application/json",
                                extra_headers=outer._trace_header(gw_span))

            do_GET = do_POST
            do_PUT = do_POST

        from mmlspark_tpu.serving.server import _GatewayHTTPServer

        self._httpd = _GatewayHTTPServer((self.host, self._port), Gateway)
        self._port = self._httpd.server_address[1]
        httpd = self._httpd
        threading.Thread(
            target=lambda: httpd.serve_forever(poll_interval=0.05),
            daemon=True,
        ).start()
        log.info(
            "distributed_serving_started", url=self.url,
            workers=len(self.workers),
            ports=[w.port for w in self.workers],
        )
        return self

    def _cluster_scope(self, path: str) -> bool:
        """True when the request asked for ``?scope=cluster`` and this
        gateway has a federator to answer it (without one, the local
        payload is the whole truth and the flag is ignored)."""
        if self.federator is None:
            return False
        query = path.split("?", 1)[1] if "?" in path else ""
        opts = urllib.parse.parse_qs(query)
        return opts.get("scope", [""])[-1] == "cluster"

    @staticmethod
    def _strip_scope(path: str) -> str:
        """The fan-out path: same endpoint + query minus ``scope`` — a
        worker answering its own payload must not recurse the fan-out."""
        base, _, query = path.partition("?")
        kept = [
            (k, v)
            for k, vs in urllib.parse.parse_qs(query).items()
            for v in vs
            if k != "scope"
        ]
        if not kept:
            return base
        return base + "?" + urllib.parse.urlencode(kept)

    @staticmethod
    def _trace_header(span: Span) -> Tuple[Tuple[str, str], ...]:
        """An ``X-Trace-Id`` response header while the request is traced,
        so a client holding a slow/failed response can fetch its tree from
        ``GET /debug/trace?trace_id=`` without log archaeology."""
        if span is not None and span.recording:
            return (("X-Trace-Id", span.trace_id),)
        return ()

    def _finish_gateway(self, span: Span, status: int, t0: float,
                        info: Optional[Dict[str, Any]]) -> None:
        """Close out one gateway request: end the root span (5xx marks the
        trace erred, so tail retention pins it), record edge latency into
        the shared serving_request_latency_ms family under the gateway
        label, feed the SLO monitor, and emit the actionable slow_request
        line (worker index, attempt count, total backoff queue-wait) when
        over `slow_request_ms`."""
        dt_ms = (time.monotonic() - t0) * 1e3
        traced = span is not None and span.recording
        info = info or {}
        if traced:
            span.set_attribute("status_code", status)
            if info.get("attempts"):
                span.set_attribute("attempts", info["attempts"])
            if info.get("worker") is not None:
                span.set_attribute("worker", info["worker"])
            if status >= 500:
                span.set_attribute("error", f"http {status}")
            self._tracer.end_span(span)
        gw_label = self.fabric.gateway_label
        self._lat_hist.labels(engine=gw_label, code=str(status)).observe(
            dt_ms,
            trace_id=span.trace_id if traced else None,
            span_id=span.span_id if traced else None,
        )
        slo_monitor().observe(
            gw_label, status, dt_ms,
            trace_id=span.trace_id if traced else None,
        )
        if self.slow_request_ms is not None and dt_ms >= self.slow_request_ms:
            log.warning(
                "slow_request", gateway=gw_label, status=status,
                latency_ms=round(dt_ms, 1),
                threshold_ms=self.slow_request_ms,
                worker=info.get("worker"),
                attempts=info.get("attempts", 0),
                queue_wait_ms=round(info.get("backoff_ms", 0.0), 1),
                hedged=bool(info.get("hedged")),
                span_path=(
                    self._tracer.trace_summary(span.trace_id)
                    if traced else "untraced"
                ),
                trace_id=span.trace_id if traced else None,
            )

    def _healthz(self) -> Tuple[int, bytes]:
        """Gateway liveness: 200 while at least one worker is routable (the
        gateway can still serve — that is the whole point of the fabric),
        503 when none are or the gateway is stopping. `status` grades it:
        ok (everything green) / degraded (serving around failures OR a
        page-severity SLO burn alert is active) / stopping / unavailable.
        SLO burn keeps the 200 — a burning gateway is still the place to
        send traffic; the status string is the operator signal."""
        healths = [w.health() for w in self.workers]
        router = self.fabric.snapshot()
        routable = [w for w in router["workers"] if w["healthy"]]
        stopping = self._stopping.is_set()
        gw_label = self.fabric.gateway_label
        slos = slo_monitor().status(engine=gw_label)
        slo_degraded = slo_monitor().page_burn_active(engine=gw_label)
        federation = None
        cluster_slos = None
        if self.federator is not None:
            federation = self.federator.snapshot()
            # cluster SLOs evaluate the FEDERATED request stream (the
            # deltas every scrape replays under the cluster engine), so a
            # worker-side burn pages here even if the gateway's own edge
            # never saw the errors
            cluster_slos = slo_monitor().status(engine=self.cluster_engine)
            slo_degraded = slo_degraded or slo_monitor().page_burn_active(
                engine=self.cluster_engine
            )
        if stopping:
            status, code = "stopping", 503
        elif not routable:
            status, code = "unavailable", 503
        elif (
            len(routable) < len(self.workers)
            or not all(h[0] for h in healths)
            or slo_degraded
        ):
            status, code = "degraded", 200
        else:
            status, code = "ok", 200
        body = json.dumps({
            "status": status,
            "workers": [h[1] for h in healths],
            "router": router,
            "slos": slos,
            "cluster_slos": cluster_slos,
            "federation": federation,
        }, sort_keys=True).encode("utf-8")
        return code, body

    def stop(self, drain_timeout: float = 10.0) -> None:
        """Graceful stop: refuse new work (503), wait for in-flight gateway
        requests to complete (bounded by drain_timeout), then tear down the
        gateway, the workers, and the fabric's registry hooks."""
        self._stopping.set()
        deadline = time.monotonic() + drain_timeout
        while (
            self.fabric.admission.in_flight > 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        if self.federator is not None:
            # before the workers stop: a scrape racing a dying worker is
            # just noise in the failure counter
            self.federator.close()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._hedge_pool is not None:
            self._hedge_pool.shutdown(wait=False)
            self._hedge_pool = None
        for w in self.workers:
            w.stop()
        self.fabric.close()

    def __enter__(self) -> "DistributedServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
