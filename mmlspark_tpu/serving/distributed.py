"""Distributed serving: a worker pool behind a routing gateway.

Reference: io/http/src/main/scala/DistributedHTTPSource.scala:89-242 — one
JVMSharedServer per executor, each binding its own port and scoring its own
partition, with a driver-side gateway (PortForwarding.scala:12) fronting the
pool — and HTTPSourceV2.scala:167-404's continuous per-partition commit (no
cross-partition lock).

TPU re-design: the partition==executor mapping becomes worker==replica. Each
worker owns a PRIVATE handler instance (its own compiled model, its own
model lock), so continuous-mode scoring never serializes across workers —
the exact fix for the single `_model_lock` bottleneck flagged in round 3.
Workers are in-process threads sharing the chip: XLA executes their
dispatches back-to-back, so concurrency hides host-side overhead (request
parse, feature build, reply encode) behind device compute. Multi-host scale
uses the same topology with workers on peer hosts and the router as the
cross-host gateway.
"""

from __future__ import annotations

import http.client
import http.server
import itertools
import json
import socket
import threading
from typing import Callable, List, Optional

from mmlspark_tpu.core.config import get_logger
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.obs import registry as obs_registry
from mmlspark_tpu.serving.server import ServingServer

log = get_logger("mmlspark_tpu.serving")


class DistributedServingServer:
    """N ServingServer workers + a routing gateway on one public port.

    handler_factory() is called once PER WORKER so each worker holds its own
    handler state (compiled model replica, locks). Pass a plain handler only
    if it is stateless/thread-safe.
    """

    def __init__(
        self,
        handler_factory: Callable[[], Callable[[DataFrame], DataFrame]],
        n_workers: int = 2,
        host: str = "127.0.0.1",
        port: int = 0,
        api_name: str = "serving",
        mode: str = "continuous",
        max_batch_size: int = 64,
        max_wait_ms: float = 5.0,
        request_timeout: float = 30.0,
        engine: str = "pipelined",
        in_flight_depth: int = 2,
    ):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.host = host
        self.api_name = api_name
        self._port = port
        self.workers: List[ServingServer] = [
            ServingServer(
                handler_factory(),
                host=host,
                port=0,
                api_name=api_name,
                mode=mode,
                max_batch_size=max_batch_size,
                max_wait_ms=max_wait_ms,
                request_timeout=request_timeout,
                engine=engine,
                in_flight_depth=in_flight_depth,
            )
            for _ in range(n_workers)
        ]
        self._rr = itertools.count()
        self._httpd: Optional[http.server.ThreadingHTTPServer] = None
        # keep-alive connections to workers, one per (gateway thread, worker)
        self._local = threading.local()

    @property
    def port(self) -> int:
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self._port}/{self.api_name}"

    # -- gateway ---------------------------------------------------------------

    def _worker_conn(self, idx: int) -> http.client.HTTPConnection:
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        conn = conns.get(idx)
        if conn is None:
            conn = http.client.HTTPConnection(
                self.workers[idx].host, self.workers[idx].port
            )
            conn.connect()
            # small writes both ways: Nagle + delayed ACK would add ~40 ms
            # per forwarded exchange (same fix as ServingServer's handler)
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conns[idx] = conn
        return conn

    def _forward(self, idx: int, method: str, path: str, body: bytes,
                 content_type: str):
        conn = self._worker_conn(idx)
        headers = {"Content-Type": content_type or "application/json"}
        try:
            conn.request(method, path, body=body, headers=headers)
            return conn.getresponse()
        except (http.client.HTTPException, ConnectionError, OSError):
            # stale keep-alive: rebuild once and retry
            conn.close()
            self._local.conns.pop(idx, None)
            conn = self._worker_conn(idx)
            conn.request(method, path, body=body, headers=headers)
            return conn.getresponse()

    def start(self) -> "DistributedServingServer":
        for w in self.workers:
            w.start()
        outer = self

        class Gateway(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):
                log.debug("gateway %s " + fmt, self.address_string(), *args)

            def _send_body(self, code: int, reason: str, payload: bytes,
                           content_type: str) -> None:
                self.send_response(code, reason)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                route = self.path.split("?", 1)[0].rstrip("/")
                # observability surfaces: workers share this process, so
                # the gateway serves the shared registry directly and
                # aggregates per-worker liveness (docs/observability.md)
                if route in ("/metrics", "/healthz"):
                    # drain any body first: on a keep-alive connection
                    # unread bytes would corrupt the next request
                    n = int(self.headers.get("Content-Length") or 0)
                    if n:
                        self.rfile.read(n)
                if route == "/metrics":
                    self._send_body(
                        200, "OK",
                        obs_registry().render_prometheus().encode("utf-8"),
                        "text/plain; version=0.0.4",
                    )
                    return
                if route == "/healthz":
                    healths = [w.health() for w in outer.workers]
                    ok = all(h[0] for h in healths)
                    body = json.dumps({
                        "status": "ok" if ok else "degraded",
                        "workers": [h[1] for h in healths],
                    }, sort_keys=True).encode("utf-8")
                    self._send_body(
                        200 if ok else 503,
                        "OK" if ok else "Service Unavailable",
                        body, "application/json",
                    )
                    return
                if route != f"/{outer.api_name}":
                    self.send_response(404, "Not Found")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                idx = next(outer._rr) % len(outer.workers)
                try:
                    resp = outer._forward(
                        idx, self.command, self.path, body,
                        self.headers.get("Content-Type"),
                    )
                    payload = resp.read()
                except Exception as e:  # dead worker: surface a 502
                    log.warning("worker %d unreachable: %r", idx, e)
                    msg = b'{"error": "bad gateway: worker unreachable"}'
                    self.send_response(502, "Bad Gateway")
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(msg)))
                    self.end_headers()
                    self.wfile.write(msg)
                    return
                self.send_response(resp.status, resp.reason)
                ct = resp.getheader("Content-Type")
                if ct:
                    self.send_header("Content-Type", ct)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST
            do_PUT = do_POST

        self._httpd = http.server.ThreadingHTTPServer(
            (self.host, self._port), Gateway
        )
        self._httpd.daemon_threads = True
        self._port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        log.info(
            "distributed serving %s -> %d workers (%s)",
            self.url, len(self.workers),
            ", ".join(str(w.port) for w in self.workers),
        )
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for w in self.workers:
            w.stop()

    def __enter__(self) -> "DistributedServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
