"""Text feature stages: tokenize -> stopwords -> ngrams -> TF(-IDF).

Reference: text-featurizer/src/main/scala/TextFeaturizer.scala (the
composed Estimator, :179) and the SparkML stages it wires. Hashing uses
Python's stable md5 (not id-based hash()) so vectors are reproducible
across processes.
"""

from __future__ import annotations

import hashlib
import re
from typing import Any, List, Optional

import numpy as np

from mmlspark_tpu.core.dataframe import Column, DataFrame, DataType, Field
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasInputCol,
    HasOutputCol,
    Param,
    TypeConverters,
    Wrappable,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer

# SparkML's english stop word list (abridged, public domain)
ENGLISH_STOP_WORDS = """a about above after again against all am an and any are as at be because
been before being below between both but by could did do does doing down during each few for from
further had has have having he her here hers herself him himself his how i if in into is it its
itself just me more most my myself no nor not now of off on once only or other our ours ourselves
out over own same she should so some such than that the their theirs them themselves then there
these they this those through to too under until up very was we were what when where which while
who whom why will with you your yours yourself yourselves""".split()


def _stable_hash(token: str, buckets: int) -> int:
    digest = hashlib.md5(token.encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "little") % buckets


class Tokenizer(Transformer, HasInputCol, HasOutputCol, Wrappable):
    """Lowercase whitespace tokenizer (SparkML Tokenizer semantics)."""

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None):
        super().__init__()
        if input_col:
            self.set(self.input_col, input_col)
        if output_col:
            self.set(self.output_col, output_col)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.ARRAY)]

    def transform(self, df: DataFrame) -> DataFrame:
        out = np.empty(len(df), dtype=object)
        for i, v in enumerate(df[self.get(self.input_col)]):
            out[i] = str(v).lower().split()
        return df.with_column(self.get(self.output_col), Column(out, DataType.ARRAY))


class RegexTokenizer(Transformer, HasInputCol, HasOutputCol, Wrappable):
    """Regex-driven tokenizer (pattern matches separators or tokens)."""

    pattern = Param("pattern", "Regex (split pattern if gaps else match pattern)", TypeConverters.to_string)
    gaps = Param("gaps", "True: pattern matches gaps; False: matches tokens", TypeConverters.to_boolean)
    to_lowercase = Param("to_lowercase", "Lowercase first", TypeConverters.to_boolean)
    min_token_length = Param("min_token_length", "Drop shorter tokens", TypeConverters.to_int)

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None,
                 pattern: str = r"\s+", gaps: bool = True, to_lowercase: bool = True,
                 min_token_length: int = 1):
        super().__init__()
        if input_col:
            self.set(self.input_col, input_col)
        if output_col:
            self.set(self.output_col, output_col)
        self.set(self.pattern, pattern)
        self.set(self.gaps, gaps)
        self.set(self.to_lowercase, to_lowercase)
        self.set(self.min_token_length, min_token_length)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.ARRAY)]

    def transform(self, df: DataFrame) -> DataFrame:
        pat = re.compile(self.get(self.pattern))
        min_len = self.get(self.min_token_length)
        out = np.empty(len(df), dtype=object)
        for i, v in enumerate(df[self.get(self.input_col)]):
            text = str(v)
            if self.get(self.to_lowercase):
                text = text.lower()
            tokens = pat.split(text) if self.get(self.gaps) else pat.findall(text)
            out[i] = [t for t in tokens if len(t) >= min_len]
        return df.with_column(self.get(self.output_col), Column(out, DataType.ARRAY))


class StopWordsRemover(Transformer, HasInputCol, HasOutputCol, Wrappable):
    """Remove stop words from a token list column."""

    stop_words = Param("stop_words", "Words to filter out", TypeConverters.to_list_string)
    case_sensitive = Param("case_sensitive", "Case sensitive matching", TypeConverters.to_boolean)

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None,
                 stop_words: Optional[List[str]] = None, case_sensitive: bool = False):
        super().__init__()
        if input_col:
            self.set(self.input_col, input_col)
        if output_col:
            self.set(self.output_col, output_col)
        self.set(self.stop_words, stop_words or ENGLISH_STOP_WORDS)
        self.set(self.case_sensitive, case_sensitive)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.ARRAY)]

    def transform(self, df: DataFrame) -> DataFrame:
        cs = self.get(self.case_sensitive)
        stops = set(
            w if cs else w.lower() for w in self.get(self.stop_words)
        )
        out = np.empty(len(df), dtype=object)
        for i, tokens in enumerate(df[self.get(self.input_col)]):
            out[i] = [
                t for t in tokens if (t if cs else str(t).lower()) not in stops
            ]
        return df.with_column(self.get(self.output_col), Column(out, DataType.ARRAY))


class NGram(Transformer, HasInputCol, HasOutputCol, Wrappable):
    """Token list -> n-gram string list."""

    n = Param("n", "N-gram length", TypeConverters.to_int)

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None,
                 n: int = 2):
        super().__init__()
        if input_col:
            self.set(self.input_col, input_col)
        if output_col:
            self.set(self.output_col, output_col)
        self.set(self.n, n)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.ARRAY)]

    def transform(self, df: DataFrame) -> DataFrame:
        n = self.get(self.n)
        out = np.empty(len(df), dtype=object)
        for i, tokens in enumerate(df[self.get(self.input_col)]):
            tokens = list(tokens)
            out[i] = [
                " ".join(tokens[j : j + n]) for j in range(len(tokens) - n + 1)
            ]
        return df.with_column(self.get(self.output_col), Column(out, DataType.ARRAY))


class HashingTF(Transformer, HasInputCol, HasOutputCol, Wrappable):
    """Token list -> dense term-frequency vector by stable hashing."""

    num_features = Param("num_features", "Vector width (hash buckets)", TypeConverters.to_int)
    binary = Param("binary", "1/0 presence instead of counts", TypeConverters.to_boolean)

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None,
                 num_features: int = 4096, binary: bool = False):
        super().__init__()
        if input_col:
            self.set(self.input_col, input_col)
        if output_col:
            self.set(self.output_col, output_col)
        self.set(self.num_features, num_features)
        self.set(self.binary, binary)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.VECTOR)]

    def transform(self, df: DataFrame) -> DataFrame:
        width = self.get(self.num_features)
        binary = self.get(self.binary)
        values = df[self.get(self.input_col)]
        out = np.zeros((len(values), width), np.float32)
        for i, tokens in enumerate(values):
            for t in tokens:
                j = _stable_hash(str(t), width)
                if binary:
                    out[i, j] = 1.0
                else:
                    out[i, j] += 1.0
        return df.with_column(self.get(self.output_col), out, DataType.VECTOR)


class IDF(Estimator, HasInputCol, HasOutputCol, Wrappable):
    """Inverse document frequency estimator over term-frequency vectors (TextFeaturizer pipeline element)."""

    min_doc_freq = Param("min_doc_freq", "Zero out terms in fewer docs", TypeConverters.to_int)

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None,
                 min_doc_freq: int = 0):
        super().__init__()
        if input_col:
            self.set(self.input_col, input_col)
        if output_col:
            self.set(self.output_col, output_col)
        self.set(self.min_doc_freq, min_doc_freq)

    def fit(self, df: DataFrame) -> "IDFModel":
        tf = df[self.get(self.input_col)]
        n = len(tf)
        doc_freq = (tf > 0).sum(axis=0)
        idf = np.log((n + 1.0) / (doc_freq + 1.0))
        idf[doc_freq < self.get(self.min_doc_freq)] = 0.0
        model = IDFModel(idf.astype(np.float64))
        model.set(model.input_col, self.get(self.input_col))
        model.set(model.output_col, self.get(self.output_col))
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.VECTOR)]


class IDFModel(Model, HasInputCol, HasOutputCol, Wrappable):
    """Fitted IDF: scales term-frequency vectors by log((n+1)/(df+1)) weights."""

    idf = ComplexParam("idf", "Inverse document frequency vector")

    def __init__(self, idf: Optional[np.ndarray] = None):
        super().__init__()
        if idf is not None:
            self.set(self.idf, np.asarray(idf))

    def transform(self, df: DataFrame) -> DataFrame:
        idf = self.get(self.idf)
        tf = df[self.get(self.input_col)]
        return df.with_column(
            self.get(self.output_col), tf * idf[None, :], DataType.VECTOR
        )

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.VECTOR)]


class TextFeaturizer(Estimator, HasInputCol, HasOutputCol, Wrappable):
    """Composed text pipeline: tokenize -> stopwords -> ngrams -> TF -> IDF
    (reference: TextFeaturizer.scala:179, same toggle params)."""

    use_tokenizer = Param("use_tokenizer", "Tokenize the input", TypeConverters.to_boolean)
    tokenizer_pattern = Param("tokenizer_pattern", "Regex pattern", TypeConverters.to_string)
    tokenizer_gaps = Param("tokenizer_gaps", "Pattern matches gaps", TypeConverters.to_boolean)
    to_lowercase = Param("to_lowercase", "Lowercase first", TypeConverters.to_boolean)
    min_token_length = Param("min_token_length", "Minimum token length", TypeConverters.to_int)
    use_stop_words_remover = Param("use_stop_words_remover", "Remove stop words", TypeConverters.to_boolean)
    case_sensitive_stop_words = Param("case_sensitive_stop_words", "Case sensitive stops", TypeConverters.to_boolean)
    use_ngram = Param("use_ngram", "Add n-grams", TypeConverters.to_boolean)
    n = Param("n", "N-gram length", TypeConverters.to_int)
    binary = Param("binary", "Binary term frequency", TypeConverters.to_boolean)
    num_features = Param("num_features", "Hash width", TypeConverters.to_int)
    use_idf = Param("use_idf", "Scale by IDF", TypeConverters.to_boolean)
    min_doc_freq = Param("min_doc_freq", "IDF min document frequency", TypeConverters.to_int)

    def __init__(self, input_col: Optional[str] = None, output_col: Optional[str] = None,
                 **kwargs: Any):
        super().__init__()
        self._set_defaults(
            use_tokenizer=True, tokenizer_pattern=r"\s+", tokenizer_gaps=True,
            to_lowercase=True, min_token_length=0, use_stop_words_remover=False,
            case_sensitive_stop_words=False, use_ngram=False, n=2, binary=False,
            num_features=4096, use_idf=True, min_doc_freq=1,
        )
        if input_col:
            self.set(self.input_col, input_col)
        if output_col:
            self.set(self.output_col, output_col)
        self.set_params(**kwargs)

    def _stages(self, out_col: str) -> List[Transformer]:
        from mmlspark_tpu.core.schema import find_unused_column_name

        cur = self.get(self.input_col)
        stages: List[Any] = []
        if self.get(self.use_tokenizer):
            nxt = "__tokens__"
            stages.append(RegexTokenizer(
                cur, nxt, self.get(self.tokenizer_pattern), self.get(self.tokenizer_gaps),
                self.get(self.to_lowercase), self.get(self.min_token_length),
            ))
            cur = nxt
        if self.get(self.use_stop_words_remover):
            nxt = "__nostops__"
            stages.append(StopWordsRemover(
                cur, nxt, case_sensitive=self.get(self.case_sensitive_stop_words)
            ))
            cur = nxt
        if self.get(self.use_ngram):
            nxt = "__ngrams__"
            stages.append(NGram(cur, nxt, self.get(self.n)))
            cur = nxt
        tf_out = "__tf__" if self.get(self.use_idf) else out_col
        stages.append(HashingTF(cur, tf_out, self.get(self.num_features), self.get(self.binary)))
        return stages, tf_out

    def fit(self, df: DataFrame) -> "TextFeaturizerModel":
        out_col = self.get(self.output_col)
        stages, tf_out = self._stages(out_col)
        cur = df
        for st in stages:
            cur = st.transform(cur)
        fitted: List[Transformer] = list(stages)
        if self.get(self.use_idf):
            idf = IDF(tf_out, out_col, self.get(self.min_doc_freq)).fit(cur)
            fitted.append(idf)
        model = TextFeaturizerModel(fitted, out_col)
        model.set(model.input_col, self.get(self.input_col))
        model.set(model.output_col, out_col)
        return model

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.VECTOR)]


class TextFeaturizerModel(Model, HasInputCol, HasOutputCol, Wrappable):
    """Fitted TextFeaturizer: tokenize/filter/ngram/hash/IDF pipeline to feature vectors."""

    stages = ComplexParam("stages", "Fitted sub-stages")

    def __init__(self, stages: Optional[List[Transformer]] = None,
                 final_col: Optional[str] = None):
        super().__init__()
        if stages is not None:
            self.set(self.stages, stages)

    def transform(self, df: DataFrame) -> DataFrame:
        out = df
        for st in self.get(self.stages):
            out = st.transform(out)
        keep = [c for c in out.columns if not c.startswith("__") or c in df.columns]
        return out.select(*keep)

    def transform_schema(self, schema: List[Field]) -> List[Field]:
        return schema + [Field(self.get(self.output_col), DataType.VECTOR)]
