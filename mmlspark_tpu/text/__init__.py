"""text — tokenization and text featurization stages.

Equivalent of the reference's text-featurizer module (SURVEY.md §2.3,
TextFeaturizer.scala:179) plus the SparkML primitives it composes
(Tokenizer, StopWordsRemover, NGram, HashingTF, IDF).

Dense-data-plane note: Spark's HashingTF emits 2^18-dim sparse vectors; a
dense TPU tensor that wide is waste, so the default here is the reference's
tree/NN featurization width (2^12, Featurize.scala:13-19). Raise
num_features if hash collisions matter more than memory.
"""

from mmlspark_tpu.text.features import (
    HashingTF,
    IDF,
    IDFModel,
    NGram,
    RegexTokenizer,
    StopWordsRemover,
    TextFeaturizer,
    TextFeaturizerModel,
    Tokenizer,
)

__all__ = [
    "HashingTF",
    "IDF",
    "IDFModel",
    "NGram",
    "RegexTokenizer",
    "StopWordsRemover",
    "TextFeaturizer",
    "TextFeaturizerModel",
    "Tokenizer",
]
