"""Structured, trace-correlated logging — the library's ONE log emitter.

Every log line is a single JSON object (JSON Lines): machine-parseable,
greppable by field, and stamped with the active span's ``trace_id`` /
``span_id`` from the tracing contextvar (obs/tracing.py) whenever one is
recording — so a warning emitted inside a served request links straight to
that request's trace in the flight recorder, the same id a histogram
exemplar carries (docs/observability.md "Exemplars").

    from mmlspark_tpu.obs.logging import get_logger
    log = get_logger("mmlspark_tpu.serving")
    log.warning("slow_request", request_id=rid, latency_ms=412.0)
    # -> {"event": "slow_request", "latency_ms": 412.0, "level": "WARNING",
    #     "logger": "mmlspark_tpu.serving", "request_id": "...",
    #     "trace_id": "9f2c...", "span_id": "01ab...", "ts": 1754300000.123}

The first positional argument is the **event name** — a stable snake_case
identifier you alert/aggregate on; everything else is keyword fields.
Messages ride stdlib ``logging`` underneath (one ``%(message)s`` handler on
the ``mmlspark_tpu`` parent logger), so level configuration
(``MMLSPARK_TPU_SDK_LOGGING_LEVEL``), ``caplog``, and any handlers the host
application installs keep working — only the message *payload* is
structured.

graftcheck's ``unstructured-log-in-library`` rule pins this in place:
direct ``logging.getLogger`` / bare ``print(`` / legacy
``core.config.get_logger`` call sites anywhere else in ``mmlspark_tpu/``
fail the tier-1 package scan (docs/static-analysis.md).
"""

from __future__ import annotations

import json
import logging as _stdlib
import threading
import time
import traceback
from typing import Any, Dict

__all__ = ["StructuredLogger", "get_logger", "stdlib_logger"]

_setup_lock = threading.Lock()
_cache: Dict[str, "StructuredLogger"] = {}


def stdlib_logger(name: str = "mmlspark_tpu") -> _stdlib.Logger:
    """The underlying stdlib logger for `name`, with the package handler
    installed once on the `mmlspark_tpu` parent (message-only format — the
    structured payload IS the line). Deferential like the old
    core/config.get_logger: when the host application configured root
    handlers, we emit through those instead of adding our own."""
    logger = _stdlib.getLogger(name)
    # install the handler on the ancestor that actually covers `name`: the
    # package parent for in-package loggers, the named logger itself for
    # external names (which never propagate into the mmlspark_tpu
    # hierarchy — the old core/config.get_logger contract).
    in_pkg = name == "mmlspark_tpu" or name.startswith("mmlspark_tpu.")
    owner = _stdlib.getLogger("mmlspark_tpu") if in_pkg else logger
    with _setup_lock:
        if not owner.handlers and not _stdlib.getLogger().handlers:
            from mmlspark_tpu.core.config import get as _cfg_get

            handler = _stdlib.StreamHandler()
            handler.setFormatter(_stdlib.Formatter("%(message)s"))
            owner.addHandler(handler)
            owner.setLevel(str(_cfg_get("sdk.logging.level", "INFO")))
    return logger


def _jsonable(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_jsonable(x) for x in v]
    item = getattr(v, "item", None)  # numpy scalars
    if callable(item) and getattr(v, "ndim", None) == 0:
        try:
            return item()
        except (TypeError, ValueError):
            pass
    return repr(v)


class StructuredLogger:
    """JSON-lines logger with automatic trace correlation.

    Methods mirror stdlib levels but take ``(event, **fields)`` instead of
    a format string: ``log.info("worker_started", port=8899)``. Reserved
    keys the emitter owns (``event``, ``level``, ``logger``, ``ts``,
    ``trace_id``, ``span_id``, ``exc``) are not overridable by fields.
    An explicit ``trace_id=`` field wins over the contextvar — callers
    holding a span object for a request whose context is gone (e.g. the
    HTTP edge after the span ended) pass it through."""

    __slots__ = ("name", "_logger")

    _RESERVED = ("event", "level", "logger", "ts", "exc")

    def __init__(self, name: str):
        self.name = name
        self._logger = stdlib_logger(name)

    def _emit(self, level: int, event: str, fields: Dict[str, Any],
              exc: bool = False) -> None:
        if not self._logger.isEnabledFor(level):
            return
        rec: Dict[str, Any] = {
            "event": event,
            "level": _stdlib.getLevelName(level),
            "logger": self.name,
            # absolute wall-clock timestamp (legitimate time.time() use:
            # log records are anchors, never differenced)
            "ts": round(time.time(), 6),
        }
        trace_id = fields.pop("trace_id", None)
        span_id = fields.pop("span_id", None)
        if trace_id is None:
            from mmlspark_tpu.obs.tracing import current_span

            span = current_span()
            if span is not None and span.recording:
                trace_id, span_id = span.trace_id, span.span_id
        if trace_id is not None:
            rec["trace_id"] = trace_id
        if span_id is not None:
            rec["span_id"] = span_id
        for k, v in fields.items():
            if k not in self._RESERVED:
                rec[k] = _jsonable(v)
        if exc:
            rec["exc"] = traceback.format_exc()
        self._logger.log(level, json.dumps(rec, sort_keys=True, default=repr))

    def debug(self, event: str, **fields: Any) -> None:
        self._emit(_stdlib.DEBUG, event, fields)

    def info(self, event: str, **fields: Any) -> None:
        self._emit(_stdlib.INFO, event, fields)

    def warning(self, event: str, **fields: Any) -> None:
        self._emit(_stdlib.WARNING, event, fields)

    def error(self, event: str, **fields: Any) -> None:
        self._emit(_stdlib.ERROR, event, fields)

    def exception(self, event: str, **fields: Any) -> None:
        """ERROR line carrying the active exception's traceback (`exc`)."""
        self._emit(_stdlib.ERROR, event, fields, exc=True)

    def isEnabledFor(self, level: int) -> bool:
        return self._logger.isEnabledFor(level)


def get_logger(name: str = "mmlspark_tpu") -> StructuredLogger:
    """The structured logger for `name` (cached per name)."""
    logger = _cache.get(name)
    if logger is None:
        logger = _cache.setdefault(name, StructuredLogger(name))
    return logger
