"""obs — the unified observability layer.

One subsystem every layer reports into, scrapeable over HTTP
(docs/observability.md):

- **Metrics** (`obs.metrics`): a process-wide `MetricsRegistry` of labelled
  Counter/Gauge/Histogram instruments with streaming-quantile latency
  sketches and Prometheus text exposition. The dataplane counters
  (utils/profiling.DataplaneCounters), the serving engine's stage meters
  (ServingPipelineCounters), pipeline/GBDT stage timings and the dispatch
  cache all live here; `ServingServer` serves the whole registry at
  ``GET /metrics``.
- **Tracing** (`obs.tracing`): Dapper-style spans with ids, parent links
  and attributes. A served request's id propagates from the HTTP edge
  through parse -> score -> reply and into per-stage `PipelineModel`
  spans — and ACROSS processes via W3C ``traceparent`` inject/extract, so
  a gateway-routed request is one tree from admission through
  retries/hedges to the worker's stages. Retention is tail-based: erred,
  shed, retried and slow traces pin; healthy traces stay 1-in-N sampled.
  Export as JSONL or Chrome trace_event (Perfetto) to line host stages up
  against `profile_to`'s device traces.
- **SLOs** (`obs.slo`): declarative availability/latency objectives over
  the serving request stream, per-objective error-budget gauges, and
  multi-window multi-burn-rate alerting
  (`slo_burn_alerts_total{slo,window}`) with exemplar trace ids; a
  page-severity burn alert degrades ``/healthz``.
- **Liveness**: ``GET /healthz`` on a `ServingServer` reports engine thread
  health, queue depth, in-flight batches, last-dispatch age and per-SLO
  status.
- **Profiling** (`obs.profiler`): XLA cost-model MFU accounting, 1-in-N
  sampled device timing, and a bounded per-dispatch flight recorder served
  at ``GET /debug/flight`` (``GET /debug/trace`` serves the tracer ring as
  Chrome trace_event JSON).
- **Device memory** (`obs.memory`): the `DeviceMemoryLedger` — resident
  device bytes per (device, class) with high-watermarks, HBM-pressure
  gauges, a growth-trend leak detector, and a `jax.live_arrays()`
  truth-check (`device_ledger_drift_total`); served at
  ``GET /debug/memory``.
- **Structured logging** (`obs.logging`): JSON-lines log records stamped
  with the active span's trace/span ids — the library's only log emitter
  (pinned by graftcheck's `unstructured-log-in-library` rule).
- **Federation** (`obs.federation`): the cross-process plane — a gateway
  `Federator` scrapes each worker's ``GET /metrics``, merges (counters
  sum reset-corrected, gauges pass through, histogram sketches merge)
  and re-exports under ``proc`` labels with cluster aggregates, fans
  ``/debug/*`` out with ``?scope=cluster``, stitches cross-process trace
  trees, and replays worker request outcomes into the SLO monitor under
  a cluster engine label — with its own scrape health telemetry
  (``obs_federation_*``) and per-worker staleness feeding the router.

`set_enabled(False)` turns the whole layer off (metrics AND tracing) — the
rollback lever the overhead smoke bench (bench.run_obs_overhead_smoke,
BENCH_pr05.json) measures against.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

from mmlspark_tpu.obs.federation import (
    FederationConfig,
    Federator,
    proc_identity,
    scrape_payload,
    set_proc_label,
)
from mmlspark_tpu.obs.logging import StructuredLogger, get_logger
from mmlspark_tpu.obs.memory import (
    CLASSES,
    DeviceMemoryLedger,
    device_label,
    memory_ledger,
)
from mmlspark_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    QuantileSketch,
    parse_prometheus,
    registry,
)
from mmlspark_tpu.obs.profiler import (
    DeviceProfiler,
    device_profiler,
    profiler_sampling,
)
from mmlspark_tpu.obs.slo import BurnWindow, SLOMonitor, SLOSpec, slo_monitor
from mmlspark_tpu.obs.tracing import (
    Span,
    SpanContext,
    Tracer,
    current_span,
    extract_context,
    format_traceparent,
    inject_context,
    stitch_trace_trees,
    tracer,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "QuantileSketch",
    "parse_prometheus",
    "registry",
    "Span",
    "SpanContext",
    "Tracer",
    "current_span",
    "extract_context",
    "format_traceparent",
    "inject_context",
    "stitch_trace_trees",
    "tracer",
    "FederationConfig",
    "Federator",
    "proc_identity",
    "scrape_payload",
    "set_proc_label",
    "BurnWindow",
    "SLOMonitor",
    "SLOSpec",
    "slo_monitor",
    "StructuredLogger",
    "get_logger",
    "DeviceProfiler",
    "device_profiler",
    "profiler_sampling",
    "CLASSES",
    "DeviceMemoryLedger",
    "device_label",
    "memory_ledger",
    "set_enabled",
    "disabled",
]


def set_enabled(enabled: bool) -> None:
    """Enable/disable the whole observability layer: every metric
    instrument and every span becomes a no-op when off."""
    registry().set_enabled(enabled)
    tracer().set_enabled(enabled)


@contextlib.contextmanager
def disabled() -> Iterator[None]:
    """Scoped full-off (the overhead bench's baseline arm)."""
    prev = (registry().enabled, tracer().enabled)
    set_enabled(False)
    try:
        yield
    finally:
        registry().set_enabled(prev[0])
        tracer().set_enabled(prev[1])
