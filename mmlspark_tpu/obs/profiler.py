"""Device-utilization profiler: cost-model MFU accounting, sampled device
timing, and a bounded per-dispatch flight recorder.

The ROADMAP's central open problem is that the hardware is mostly idle
(6-27% MFU in the bench artifacts) — but utilization was only measurable
*offline*, by bench.py dividing hand-counted FLOPs by wall-clock. This
module makes device efficiency a continuous runtime observable, wired
through the dispatch hot path (core/dispatch.py + models/tpu_model.py)
rather than bolted onto benchmarks:

- **Cost-model capture.** When the dispatch cache AOT-compiles a program
  (``jit(...).lower(...).compile()``), it reports the compile wall time and
  the harvested ``compiled.cost_analysis()`` (flops, bytes accessed) here,
  per program key — ``dispatch_compile_seconds{site}`` histogram plus a
  bounded cost table. ``Network.flops_per_example()`` is the documented
  fallback/cross-check when XLA's cost model is unavailable on a backend
  (callers pass it as ``fallback_flops``).
- **Sampled device timing.** ``should_sample()`` is a 1-in-N gate: sampled
  dispatches block until ready and report real device wall time; off-sample
  dispatches stay fully async. Samples feed rolling ``device_mfu{model}``,
  ``device_flops_per_sec{model}`` and ``device_arithmetic_intensity{model}``
  gauges against the per-backend peak-FLOPs table in core/env.py, plus a
  ``dispatch_device_seconds{site}`` histogram.
- **Flight recorder.** Every profiled dispatch appends a bounded ring
  record (program key, bucket signature, queue -> dispatch -> done
  timestamps, flops, bytes, donation/cache-hit/compile flags, active trace
  id). ``GET /debug/flight`` on every server serves ``flight()`` as JSON,
  so a live production pause is diagnosable without redeploying.
- **Compile-storm detection.** More than ``storm_threshold`` fresh compiles
  attributed to one trace (or, untraced, one thread within a short window)
  means ragged traffic escaped the power-of-two buckets: ONE structured
  warning with the offending signatures + ``dispatch_compile_storms_total``.

Rollback parity: everything here no-ops under ``obs.set_enabled(False)`` /
``obs.disabled()`` exactly like the PR 5 metrics do — ``enabled`` mirrors
the registry switch, so the overhead bench's baseline arm pays zero
profiler cost (gated <= 5% by bench.run_profiler_smoke, BENCH_pr13.json).
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

from mmlspark_tpu.obs.logging import get_logger
from mmlspark_tpu.obs.metrics import registry
from mmlspark_tpu.obs.tracing import _epoch, current_span

__all__ = [
    "DeviceProfiler",
    "device_profiler",
    "profiler_sampling",
]

log = get_logger("mmlspark_tpu.obs")

#: default 1-in-N device-timing sample rate (config: obs.profiler.sample_every)
DEFAULT_SAMPLE_EVERY = 32
#: flight-recorder ring capacity (records, not bytes; each is a small dict)
DEFAULT_MAX_RECORDS = 1024
#: fresh compiles per trace/thread-window before a storm warning fires
DEFAULT_STORM_THRESHOLD = 8
#: untraced storm attribution window: compiles on one thread separated by
#: more than this are different "requests"
_STORM_GAP_S = 5.0
#: rolling MFU window length (sampled dispatches per model label)
_MFU_WINDOW = 256


class DeviceProfiler:
    """Process-wide device-efficiency meters; one instance per process
    (``device_profiler()``), mirroring the metrics registry it reports
    into. Thread-safe; every public method is a no-op while the
    observability layer is disabled."""

    def __init__(self, sample_every: Optional[int] = None,
                 max_records: int = DEFAULT_MAX_RECORDS,
                 storm_threshold: Optional[int] = None):
        from mmlspark_tpu.core.config import get as _cfg_get

        if sample_every is None:
            sample_every = int(
                _cfg_get("obs.profiler.sample.every", DEFAULT_SAMPLE_EVERY)
            )
        if storm_threshold is None:
            storm_threshold = int(
                _cfg_get("obs.profiler.storm.threshold",
                         DEFAULT_STORM_THRESHOLD)
            )
        self._lock = threading.Lock()
        self._sample_every = max(0, int(sample_every))
        self._seq = itertools.count()
        self._records: "deque[Dict[str, Any]]" = deque(maxlen=max_records)
        self._total_records = 0
        # program cost table: (key, signature) -> {"flops", "bytes",
        # "compile_s"}; bounded so a churning model mix can't grow it forever
        self._costs: "OrderedDict[Tuple[Any, Any], Dict[str, float]]" = (
            OrderedDict()
        )
        self._max_costs = 256
        # rolling per-model windows: label -> deque[(flops, bytes, secs)]
        self._windows: Dict[str, "deque"] = {}
        self.storm_threshold = max(1, int(storm_threshold))
        self._storms: "OrderedDict[Any, Dict[str, Any]]" = OrderedDict()
        self._peak: Optional[float] = None  # resolved lazily (imports jax)

        reg = registry()
        self._compile_hist = reg.histogram(
            "dispatch_compile_seconds",
            "XLA AOT compile wall seconds per dispatch site",
            ("site",),
        )
        self._device_hist = reg.histogram(
            "dispatch_device_seconds",
            "Sampled device wall seconds per dispatch site",
            ("site",),
        )
        self._mfu_gauge = reg.gauge(
            "device_mfu",
            "Rolling model-FLOPs utilization (0-1) over sampled dispatches",
            ("model",),
        )
        self._fps_gauge = reg.gauge(
            "device_flops_per_sec",
            "Rolling device FLOP/s over sampled dispatches",
            ("model",),
        )
        self._ai_gauge = reg.gauge(
            "device_arithmetic_intensity",
            "Rolling flops per byte accessed (cost model) over sampled "
            "dispatches",
            ("model",),
        )
        self._sampled_total = reg.counter(
            "dispatch_sampled_total",
            "Dispatches that paid the block-until-ready device timing",
        )
        self._storm_total = reg.counter(
            "dispatch_compile_storms_total",
            "Requests/transforms that triggered more than the storm "
            "threshold of fresh XLA compiles",
        )
        self._flight_total = reg.counter(
            "flight_records_total",
            "Per-dispatch flight-recorder records written (ring-bounded "
            "retention; this counter is the monotonic total)",
        )

    # -- enable/disable (mirrors obs.set_enabled) ------------------------------

    @property
    def enabled(self) -> bool:
        return registry().enabled

    # -- sampling --------------------------------------------------------------

    @property
    def sample_every(self) -> int:
        return self._sample_every

    def set_sample_every(self, n: int) -> None:
        """1-in-N device-timing rate; 0 disables sampling (dispatches stay
        fully async), 1 times every dispatch (bench mode)."""
        self._sample_every = max(0, int(n))

    def should_sample(self) -> bool:
        """True when THIS dispatch should pay a block_until_ready to
        measure device time. Counter-based 1-in-N (not random: overhead is
        deterministic and testable); always False while obs is disabled or
        sampling is off."""
        n = self._sample_every
        if n <= 0 or not self.enabled:
            return False
        return next(self._seq) % n == 0

    # -- cost-model capture ----------------------------------------------------

    def note_compile(self, key: Any, signature: Any, site: str,
                     seconds: float, cost: Optional[Dict[str, float]]) -> None:
        """One fresh XLA compile at `site`: wall time into the histogram,
        harvested cost model (``{"flops", "bytes"}``, either may be absent)
        into the per-program table, storm accounting bumped."""
        if not self.enabled:
            return
        self._compile_hist.labels(site=site).observe(float(seconds))
        entry = {"compile_s": float(seconds)}
        if cost:
            if cost.get("flops") is not None:
                entry["flops"] = float(cost["flops"])
            if cost.get("bytes") is not None:
                entry["bytes"] = float(cost["bytes"])
        with self._lock:
            self._costs[(key, signature)] = entry
            while len(self._costs) > self._max_costs:
                self._costs.popitem(last=False)
        self._note_storm(site, signature)

    def cost_for(self, key: Any, signature: Any) -> Optional[Dict[str, float]]:
        """The harvested cost-model entry for a program, or None when the
        backend's cost model was unavailable (callers fall back to analytic
        FLOPs — Network.flops_per_example)."""
        with self._lock:
            return self._costs.get((key, signature))

    def _note_storm(self, site: str, signature: Any) -> None:
        span = current_span()
        now = time.monotonic()
        if span is not None and span.recording:
            group: Any = ("trace", span.trace_id)
            trace_id: Optional[str] = span.trace_id
        else:
            group = ("thread", threading.get_ident())
            trace_id = None
        with self._lock:
            st = self._storms.get(group)
            if st is None or (
                group[0] == "thread" and now - st["last"] > _STORM_GAP_S
            ):
                st = {"count": 0, "signatures": [], "warned": False,
                      "last": now}
                self._storms[group] = st
                while len(self._storms) > 128:
                    self._storms.popitem(last=False)
            st["count"] += 1
            st["last"] = now
            if len(st["signatures"]) < 16:
                st["signatures"].append(_jsonable_sig(signature))
            storm = st["count"] > self.storm_threshold and not st["warned"]
            if storm:
                st["warned"] = True
                count, sigs = st["count"], list(st["signatures"])
        if storm:
            self._storm_total.inc()
            log.warning(
                "compile_storm",
                site=site,
                compiles=count,
                threshold=self.storm_threshold,
                signatures=sigs,
                trace_id=trace_id,
            )

    # -- dispatch recording ----------------------------------------------------

    def record_dispatch(self, *, site: str, model: str, key: Any,
                        signature: Any, rows: int,
                        t_queue: float, t_dispatch: float,
                        device_s: Optional[float] = None,
                        fallback_flops: Optional[float] = None,
                        donated: bool = False,
                        first_compile: bool = False) -> None:
        """One device dispatch: a flight-recorder record always (while
        enabled), MFU/intensity gauge updates when `device_s` was sampled.
        Timestamps are time.monotonic() readings; the flight export maps
        them to epoch through the tracer's wall anchor."""
        if not self.enabled:
            return
        cost = self.cost_for(key, signature)
        flops = cost.get("flops") if cost else None
        nbytes = cost.get("bytes") if cost else None
        flops_src = "cost_model"
        if flops is None and fallback_flops is not None:
            flops = float(fallback_flops)
            flops_src = "analytic"
        span = current_span()
        rec: Dict[str, Any] = {
            "site": site,
            "model": model,
            "program": _jsonable_sig(key),
            "signature": _jsonable_sig(signature),
            "rows": int(rows),
            "t_queue": round(_epoch(t_queue), 6),
            "t_dispatch": round(_epoch(t_dispatch), 6),
            "t_done": (
                round(_epoch(t_dispatch + device_s), 6)
                if device_s is not None else None
            ),
            "device_s": (
                round(device_s, 6) if device_s is not None else None
            ),
            "sampled": device_s is not None,
            "flops": flops,
            "flops_source": flops_src if flops is not None else None,
            "bytes": nbytes,
            "donated": bool(donated),
            "cache_hit": not first_compile,
            "trace_id": (
                span.trace_id if span is not None and span.recording
                else None
            ),
        }
        with self._lock:
            self._records.append(rec)
            self._total_records += 1
        self._flight_total.inc()
        if device_s is not None:
            self._sampled_total.inc()
            self._device_hist.labels(site=site).observe(float(device_s))
            if flops is not None:
                self._update_window(model, float(flops),
                                    float(nbytes) if nbytes else 0.0,
                                    float(device_s))

    def record_device_work(self, *, site: str, model: str, seconds: float,
                           flops: float, nbytes: float = 0.0,
                           rows: Optional[int] = None,
                           flops_source: Optional[str] = None,
                           attrs: Optional[Dict[str, Any]] = None) -> None:
        """Aggregate device work that is not a single cached dispatch (a
        GBDT boost phase, a training epoch): feeds the same
        dispatch_device_seconds histogram and rolling MFU gauges. `flops`
        is usually an analytic estimate — callers document theirs.

        When `flops_source`/`attrs` are given, the work also lands in the
        flight recorder so the MFU feed is ATTRIBUTABLE after the fact:
        e.g. the GBDT trainer stamps the active `hist_impl` and engine on
        every round record, which is what lets /debug/flight separate
        pallas-tier from einsum-tier `device_mfu` samples
        (docs/observability.md "MFU attribution")."""
        if not self.enabled or seconds <= 0:
            return
        if flops_source is not None or attrs is not None:
            t_done = time.monotonic()
            span = current_span()
            rec: Dict[str, Any] = {
                "site": site,
                "model": model,
                "program": None,
                "signature": None,
                "rows": None if rows is None else int(rows),
                "t_queue": round(_epoch(t_done - seconds), 6),
                "t_dispatch": round(_epoch(t_done - seconds), 6),
                "t_done": round(_epoch(t_done), 6),
                "device_s": round(float(seconds), 6),
                "sampled": True,
                "flops": float(flops),
                "flops_source": flops_source,
                "bytes": float(nbytes) if nbytes else None,
                "donated": False,
                "cache_hit": True,
                "attrs": {k: _jsonable_sig(v) for k, v in (attrs or {}).items()},
                "trace_id": (
                    span.trace_id if span is not None and span.recording
                    else None
                ),
            }
            with self._lock:
                self._records.append(rec)
                self._total_records += 1
            self._flight_total.inc()
        self._device_hist.labels(site=site).observe(float(seconds))
        self._update_window(model, float(flops), float(nbytes),
                            float(seconds))

    def _update_window(self, model: str, flops: float, nbytes: float,
                       seconds: float) -> None:
        with self._lock:
            win = self._windows.get(model)
            if win is None:
                win = self._windows[model] = deque(maxlen=_MFU_WINDOW)
            win.append((flops, nbytes, seconds))
            f_sum = sum(f for f, _, _ in win)
            b_sum = sum(b for _, b, _ in win)
            s_sum = sum(s for _, _, s in win)
        if s_sum <= 0:
            return
        fps = f_sum / s_sum
        self._fps_gauge.labels(model=model).set(fps)
        if b_sum > 0:
            self._ai_gauge.labels(model=model).set(f_sum / b_sum)
        peak = self._peak_flops()
        if peak > 0:
            self._mfu_gauge.labels(model=model).set(fps / peak)

    def _peak_flops(self) -> float:
        if self._peak is None:
            from mmlspark_tpu.core.env import peak_flops_per_sec

            try:
                self._peak = float(peak_flops_per_sec())
            except Exception as e:  # backend not initializable: omit MFU
                log.debug("peak_flops_unavailable", error=repr(e))
                self._peak = 0.0
        return self._peak

    def mfu(self, model: str) -> float:
        """The current rolling MFU gauge value for `model` (nan before any
        sample)."""
        return self._mfu_gauge.labels(model=model).value() or float("nan")

    # -- flight recorder export ------------------------------------------------

    def flight(self, limit: Optional[int] = None) -> Dict[str, Any]:
        """The ``GET /debug/flight`` payload: recent per-dispatch records
        (oldest first) plus reconciliation counters — `total_records` is
        the monotonic count (== flight_records_total), `ring_capacity` the
        retention bound, and `sample_every` the active timing rate."""
        with self._lock:
            records = list(self._records)
            total = self._total_records
        if limit is not None:
            records = records[-int(limit):]
        from mmlspark_tpu.obs.federation import proc_identity

        return {
            "proc_identity": proc_identity(),
            "records": records,
            "total_records": total,
            "ring_capacity": self._records.maxlen,
            "sample_every": self._sample_every,
            "storm_threshold": self.storm_threshold,
        }

    def clear(self) -> None:
        """Drop ring/cost/window state (tests); registry series persist."""
        with self._lock:
            self._records.clear()
            self._total_records = 0
            self._costs.clear()
            self._windows.clear()
            self._storms.clear()


def _jsonable_sig(value: Any) -> Any:
    """Program keys/signatures are arbitrary hashables; flatten to a JSON-
    safe shape (tuples -> lists, everything exotic -> str). Long strings
    (a TPUModel key embeds the whole network spec) truncate to a prefix +
    content hash so 1024 flight records stay a bounded payload while two
    records with the same program still compare equal."""
    if value is None or isinstance(value, (bool, int, float)):
        return value
    if isinstance(value, str):
        if len(value) <= 160:
            return value
        import hashlib

        digest = hashlib.sha1(value.encode("utf-8")).hexdigest()[:12]
        return f"{value[:80]}...sha1:{digest}"
    if isinstance(value, (tuple, list)):
        return [_jsonable_sig(v) for v in value]
    return _jsonable_sig(str(value))


_PROFILER = DeviceProfiler()


def device_profiler() -> DeviceProfiler:
    """The process-wide device profiler singleton."""
    return _PROFILER


@contextlib.contextmanager
def profiler_sampling(every: int) -> Iterator[DeviceProfiler]:
    """Scoped sample-rate override (bench/tests): ``profiler_sampling(1)``
    times every dispatch, ``profiler_sampling(0)`` turns timing off."""
    prof = device_profiler()
    prev = prof.sample_every
    prof.set_sample_every(every)
    try:
        yield prof
    finally:
        prof.set_sample_every(prev)
