"""Device-memory ledger: per-device resident-byte accounting by class.

The flow-observability layers (PR 5 metrics, PR 13 MFU/flight, PR 14
tracing/SLO) answer "what is the device *doing*"; nothing answered "what
is resident in HBM on device k right now, and who owns it" — the question
both ROADMAP tentpoles (the HBM byte-budget manager and pod-scale GSPMD
training) start from. This module is that accounting:

- **Ledger.** ``memory_ledger()`` is the process singleton. Every
  framework allocation/free of device-resident bytes reports
  ``record_alloc`` / ``record_free`` with a device, a **class** (one of
  ``CLASSES``: model_weights, dispatch_programs, data_shards,
  prefetch_chunks, train_batches, scratch) and an optional owner tag. Gauges:
  ``device_resident_bytes{device,class}`` (live),
  ``device_resident_bytes_peak{device,class}`` (high-watermark) and
  ``device_memory_pressure{device}`` (total resident / the per-kind HBM
  capacity table in core/env.py).
- **Leak detection.** Per class, the ledger keeps a short growth trend
  (samples between frees): a class that only grows across
  ``leak_min_samples`` allocations, by more than ``leak_growth_frac``
  (with a bytes floor), earns ONE structured ``device_memory_leak``
  warning carrying the class, per-device breakdown, top owners and the
  active trace id, plus ``device_memory_leak_warnings_total{class}``. Any
  free of that class resets the trend — growth that drains is churn, not
  a leak.
- **Truth-check.** ``reconcile()`` samples ``jax.live_arrays()`` and
  compares per-device live bytes against the ledger's ARRAY-BACKED
  classes. The invariant is ``ledger <= live + tolerance`` — the ledger
  tracks a *subset* of live arrays (jit temporaries, constants and user
  arrays are legitimately unattributed), so live exceeding the ledger is
  reported (``unattributed_bytes``) but only a ledger claiming MORE than
  exists (phantom residency: a free site that never decremented) counts
  as drift, incrementing ``device_ledger_drift_total{device}`` and
  logging the discrepancy. dispatch_programs is excluded from the
  comparison — XLA executables hold real device memory that
  ``live_arrays()`` can never confirm — and reported separately as
  ``executable_bytes``. ``GET /debug/memory`` (serving/server.py and the
  gateway) serves ``debug_payload()`` — snapshot, watermarks, pressure,
  last reconcile, top-N owners — and re-reconciles when the last check
  is stale.

Wired call sites: ``NetworkBundle.device_variables`` and the mesh
replicated-weights upload (model_weights), ``DispatchCache`` AOT
executable retention/eviction (dispatch_programs — evictions decrement),
``Booster._packed_device`` (model_weights), the
``DeviceChunkPrefetcher`` chunk lifecycle including PR 15 owner-device
placement (prefetch_chunks), and the data-parallel GBDT trainer's
per-shard resident state (data_shards). graftcheck's
``untracked-device-upload`` rule keeps new dataplane upload sites from
bypassing this accounting (docs/static-analysis.md).

Rollback parity: every recording method no-ops under
``obs.set_enabled(False)`` / ``obs.disabled()`` — gated <= 5% overhead by
``bench.run_memory_smoke`` (BENCH_pr16.json).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from mmlspark_tpu.obs.logging import get_logger
from mmlspark_tpu.obs.metrics import registry
from mmlspark_tpu.obs.tracing import current_span

__all__ = [
    "CLASSES",
    "DeviceMemoryLedger",
    "device_label",
    "memory_ledger",
]

log = get_logger("mmlspark_tpu.obs")

#: the resident-byte classes the ledger accounts by — every framework
#: allocation belongs to exactly one
CLASSES = (
    "model_weights",
    "dispatch_programs",
    "data_shards",
    "prefetch_chunks",
    "train_batches",
    "scratch",
)

#: growth-trend samples (allocations with no intervening free) before a
#: class can be called a leak (config: obs.memory.leak.min.samples)
DEFAULT_LEAK_MIN_SAMPLES = 16
#: net growth fraction over the trend start before warning
DEFAULT_LEAK_GROWTH_FRAC = 0.5
#: absolute growth floor — a near-zero start must still grow by this much
DEFAULT_LEAK_MIN_GROWTH_BYTES = 1 << 20
#: reconcile tolerance: phantom bytes allowed before drift counts, as a
#: fraction of live bytes and an absolute floor
DEFAULT_DRIFT_TOL_FRAC = 0.05
DEFAULT_DRIFT_TOL_BYTES = 1 << 20
#: /debug/memory re-reconciles when the last truth-check is older than this
DEFAULT_RECONCILE_STALE_S = 60.0
#: owner-table retention bound (top-N attribution, not the totals)
_MAX_OWNERS = 512


def device_label(device: Any) -> str:
    """Stable registry label for a device-ish value: a jax Device becomes
    ``platform:id`` ("tpu:3", "cpu:0"); a single-device Sharding or a
    device-resident array resolves through its device set; a string passes
    through; None/unresolvable become "unknown" (callers that know better
    pass better)."""
    if device is None:
        return "unknown"
    if isinstance(device, str):
        return device
    platform = getattr(device, "platform", None)
    dev_id = getattr(device, "id", None)
    if platform is not None and dev_id is not None:
        return f"{platform}:{dev_id}"
    devs = getattr(device, "device_set", None)  # Sharding
    if devs is None:
        get_devs = getattr(device, "devices", None)  # jax.Array
        if callable(get_devs):
            try:
                devs = get_devs()
            except Exception:  # committed-elsewhere array; label, not truth  # graftcheck: ignore[broad-except]
                devs = None
    if devs:
        devs = sorted(devs, key=lambda d: getattr(d, "id", 0))
        if len(devs) == 1:
            return device_label(devs[0])
        return "mesh"
    return "unknown"


def default_device_label() -> str:
    """The label of jax's default device (imports jax — call lazily)."""
    import jax

    # a LABEL probe, not a placement: single-device uploads commit to the
    # default device and the ledger names it
    return device_label(jax.devices()[0])  # graftcheck: ignore[hardcoded-device-index]


class DeviceMemoryLedger:
    """Thread-safe resident-byte accounting per (device, class); one
    process-wide instance (``memory_ledger()``), registry-backed like the
    DeviceProfiler it sits beside. Every recording method is a no-op while
    the observability layer is disabled — callers must then also skip the
    matching frees, which ``obs.disabled()`` scopes do symmetrically."""

    def __init__(self,
                 leak_min_samples: Optional[int] = None,
                 leak_growth_frac: Optional[float] = None,
                 leak_min_growth_bytes: Optional[int] = None,
                 drift_tol_frac: Optional[float] = None,
                 drift_tol_bytes: Optional[int] = None):
        from mmlspark_tpu.core.config import get as _cfg_get

        if leak_min_samples is None:
            leak_min_samples = int(_cfg_get(
                "obs.memory.leak.min.samples", DEFAULT_LEAK_MIN_SAMPLES))
        if leak_growth_frac is None:
            leak_growth_frac = float(_cfg_get(
                "obs.memory.leak.growth.frac", DEFAULT_LEAK_GROWTH_FRAC))
        if leak_min_growth_bytes is None:
            leak_min_growth_bytes = int(_cfg_get(
                "obs.memory.leak.min.growth.bytes",
                DEFAULT_LEAK_MIN_GROWTH_BYTES))
        if drift_tol_frac is None:
            drift_tol_frac = float(_cfg_get(
                "obs.memory.drift.tol.frac", DEFAULT_DRIFT_TOL_FRAC))
        if drift_tol_bytes is None:
            drift_tol_bytes = int(_cfg_get(
                "obs.memory.drift.tol.bytes", DEFAULT_DRIFT_TOL_BYTES))
        self._lock = threading.Lock()
        self.leak_min_samples = max(2, int(leak_min_samples))
        self.leak_growth_frac = float(leak_growth_frac)
        self.leak_min_growth_bytes = int(leak_min_growth_bytes)
        self.drift_tol_frac = float(drift_tol_frac)
        self.drift_tol_bytes = int(drift_tol_bytes)
        # (device, class) -> resident bytes; the source of truth
        self._resident: Dict[Tuple[str, str], int] = {}
        self._peaks: Dict[Tuple[str, str], int] = {}
        self._dev_peaks: Dict[str, int] = {}
        # (device, class, owner) -> bytes; bounded top-N attribution only
        self._owners: "OrderedDict[Tuple[str, str, str], int]" = OrderedDict()
        # class -> [(monotonic_t, class_total), ...] growth trend; cleared
        # by any free of that class
        self._trend: Dict[str, List[Tuple[float, int]]] = {}
        self._leak_warned: Dict[str, bool] = {}
        self._leak_events: "deque" = deque(maxlen=32)
        self._last_reconcile: Optional[Dict[str, Any]] = None
        self._last_reconcile_t: float = 0.0
        self._capacity: Optional[float] = None  # lazy (imports jax)

        reg = registry()
        self._resident_gauge = reg.gauge(
            "device_resident_bytes",
            "Framework-attributed resident device bytes by class",
            ("device", "class"),
        )
        self._peak_gauge = reg.gauge(
            "device_resident_bytes_peak",
            "High-water mark of framework-attributed resident device bytes",
            ("device", "class"),
        )
        self._pressure_gauge = reg.gauge(
            "device_memory_pressure",
            "Total attributed resident bytes / per-device HBM capacity "
            "(core/env.py table; absent when capacity is unknown)",
            ("device",),
        )
        self._drift_total = reg.counter(
            "device_ledger_drift_total",
            "Reconcile passes where the ledger claimed more resident bytes "
            "than jax.live_arrays() holds (beyond tolerance)",
            ("device",),
        )
        self._leak_total = reg.counter(
            "device_memory_leak_warnings_total",
            "Growth-trend leak warnings emitted, by resident-byte class",
            ("class",),
        )

    @property
    def enabled(self) -> bool:
        return registry().enabled

    # -- recording -------------------------------------------------------------

    def record_alloc(self, device: Any, cls: str, nbytes: int,
                     owner: Optional[str] = None) -> None:
        """`nbytes` became resident on `device` under class `cls`."""
        self._record(device, cls, int(nbytes), owner)

    def record_free(self, device: Any, cls: str, nbytes: int,
                    owner: Optional[str] = None) -> None:
        """`nbytes` previously recorded for (device, cls) were released."""
        self._record(device, cls, -int(nbytes), owner)

    def record_alloc_devices(self, devices, cls: str, nbytes_per_device: int,
                             owner: Optional[str] = None) -> None:
        """A replicated allocation: `nbytes_per_device` resident on EACH of
        `devices` (a mesh-replicated weight tree holds one full copy per
        chip)."""
        for d in devices:
            self._record(d, cls, int(nbytes_per_device), owner)

    def record_free_devices(self, devices, cls: str, nbytes_per_device: int,
                            owner: Optional[str] = None) -> None:
        for d in devices:
            self._record(d, cls, -int(nbytes_per_device), owner)

    def _record(self, device: Any, cls: str, delta: int,
                owner: Optional[str]) -> None:
        if delta == 0 or not self.enabled:
            return
        if cls not in CLASSES:
            cls = "scratch"
        dev = device_label(device)
        leak = None
        with self._lock:
            key = (dev, cls)
            total = max(0, self._resident.get(key, 0) + delta)
            self._resident[key] = total
            if total > self._peaks.get(key, 0):
                self._peaks[key] = total
            dev_total = sum(
                v for (d, _), v in self._resident.items() if d == dev
            )
            if dev_total > self._dev_peaks.get(dev, 0):
                self._dev_peaks[dev] = dev_total
            if owner is not None:
                okey = (dev, cls, str(owner))
                obytes = self._owners.get(okey, 0) + delta
                if obytes <= 0:
                    self._owners.pop(okey, None)
                else:
                    self._owners[okey] = obytes
                    self._owners.move_to_end(okey)
                    while len(self._owners) > _MAX_OWNERS:
                        self._owners.popitem(last=False)
            if delta > 0:
                leak = self._note_growth(cls)
            else:
                # a free is the anti-leak signal: the trend restarts, and
                # a once-warned class earns a fresh warning if it leaks
                # again later
                self._trend.pop(cls, None)
                self._leak_warned.pop(cls, None)
        self._resident_gauge.labels(device=dev, **{"class": cls}).set(
            float(total))
        self._peak_gauge.labels(device=dev, **{"class": cls}).set_max(
            float(total))
        cap = self._hbm_capacity()
        if cap > 0:
            self._pressure_gauge.labels(device=dev).set(dev_total / cap)
        if leak is not None:
            self._warn_leak(cls, leak)

    def _note_growth(self, cls: str) -> Optional[Dict[str, Any]]:
        """Append a growth sample for `cls` (lock held); returns the leak
        payload when the trend crosses the threshold un-warned."""
        total = sum(v for (_, c), v in self._resident.items() if c == cls)
        trend = self._trend.setdefault(cls, [])
        trend.append((time.monotonic(), total))
        if len(trend) > 4 * self.leak_min_samples:
            del trend[0]
        if len(trend) < self.leak_min_samples or self._leak_warned.get(cls):
            return None
        start = trend[0][1]
        growth = total - start
        threshold = max(self.leak_min_growth_bytes,
                        int(self.leak_growth_frac * start))
        if growth < threshold:
            return None
        self._leak_warned[cls] = True
        by_device = {
            d: v for (d, c), v in self._resident.items()
            if c == cls and v > 0
        }
        owners = sorted(
            ((o, v) for (d, c, o), v in self._owners.items() if c == cls),
            key=lambda kv: -kv[1],
        )[:5]
        return {
            "class": cls,
            "samples": len(trend),
            "start_bytes": start,
            "now_bytes": total,
            "growth_bytes": growth,
            "by_device": by_device,
            "top_owners": owners,
        }

    def _warn_leak(self, cls: str, payload: Dict[str, Any]) -> None:
        span = current_span()
        trace_id = (
            span.trace_id if span is not None and span.recording else None
        )
        payload = dict(payload, trace_id=trace_id)
        with self._lock:
            self._leak_events.append(payload)
        self._leak_total.labels(**{"class": cls}).inc()
        log.warning(
            "device_memory_leak",
            **{"class": cls},
            samples=payload["samples"],
            start_bytes=payload["start_bytes"],
            now_bytes=payload["now_bytes"],
            growth_bytes=payload["growth_bytes"],
            by_device=payload["by_device"],
            top_owners=payload["top_owners"],
            trace_id=trace_id,
        )

    # -- truth-check -----------------------------------------------------------

    def live_device_bytes(self) -> Dict[str, float]:
        """Per-device live bytes from jax.live_arrays() (each array's bytes
        split evenly across its device set). The reconcile baseline — also
        what the bench's delta-based tolerance gate samples directly."""
        import jax

        live: Dict[str, float] = {}
        for arr in jax.live_arrays():
            try:
                if arr.is_deleted():
                    continue
                devs = list(arr.sharding.device_set)
                nbytes = float(arr.nbytes)
            except Exception:  # arrays may be deleted mid-iteration; skip  # graftcheck: ignore[broad-except]
                continue
            if not devs:
                continue
            share = nbytes / len(devs)
            for d in devs:
                lbl = device_label(d)
                live[lbl] = live.get(lbl, 0.0) + share
        return live

    def reconcile(self) -> Dict[str, Any]:
        """One truth-check pass: per device, the ledger's ARRAY-BACKED
        total vs live bytes. `unattributed_bytes` (live > ledger) is
        informational — jit constants/temporaries and user arrays are
        legitimately untracked; `phantom_bytes` (ledger > live) beyond
        tolerance is drift: a free site that never decremented. Drift
        increments ``device_ledger_drift_total{device}`` and logs a
        warning. The dispatch_programs class is excluded from the phantom
        comparison — XLA executables hold real device memory but are not
        jax arrays, so ``jax.live_arrays()`` can never confirm them; their
        bytes are reported per device as ``executable_bytes`` instead."""
        if not self.enabled:
            return {"skipped": "observability disabled"}
        live = self.live_device_bytes()
        with self._lock:
            ledger: Dict[str, int] = {}
            execs: Dict[str, int] = {}
            for (d, c), v in self._resident.items():
                if c == "dispatch_programs":
                    execs[d] = execs.get(d, 0) + v
                else:
                    ledger[d] = ledger.get(d, 0) + v
        devices: Dict[str, Dict[str, float]] = {}
        drifted: List[str] = []
        for dev in sorted(set(live) | set(ledger) | set(execs)):
            lv = live.get(dev, 0.0)
            lg = float(ledger.get(dev, 0))
            tol = max(float(self.drift_tol_bytes),
                      self.drift_tol_frac * max(lv, lg))
            phantom = max(0.0, lg - lv)
            drift = phantom > tol
            devices[dev] = {
                "ledger_bytes": lg,
                "live_bytes": round(lv, 1),
                "executable_bytes": float(execs.get(dev, 0)),
                "unattributed_bytes": round(max(0.0, lv - lg), 1),
                "phantom_bytes": round(phantom, 1),
                "tolerance_bytes": round(tol, 1),
                "within_tolerance": not drift,
            }
            if drift:
                drifted.append(dev)
                self._drift_total.labels(device=dev).inc()
        result = {
            "devices": devices,
            "drifted": drifted,
            "checked_at": round(time.time(), 3),
        }
        with self._lock:
            self._last_reconcile = result
            self._last_reconcile_t = time.monotonic()
        if drifted:
            log.warning(
                "device_ledger_drift",
                drifted=drifted,
                devices={d: devices[d] for d in drifted},
            )
        return result

    # -- views -----------------------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """{device: {class: resident_bytes}} for all nonzero entries."""
        with self._lock:
            out: Dict[str, Dict[str, int]] = {}
            for (d, c), v in self._resident.items():
                if v > 0:
                    out.setdefault(d, {})[c] = v
            return out

    def total_bytes(self, device: Optional[Any] = None) -> int:
        with self._lock:
            if device is None:
                return sum(self._resident.values())
            dev = device_label(device)
            return sum(
                v for (d, _), v in self._resident.items() if d == dev
            )

    def watermarks(self) -> Dict[str, Dict[str, int]]:
        """{device: {class: peak_bytes, "_total": device_peak}}."""
        with self._lock:
            out: Dict[str, Dict[str, int]] = {}
            for (d, c), v in self._peaks.items():
                if v > 0:
                    out.setdefault(d, {})[c] = v
            for d, v in self._dev_peaks.items():
                if v > 0:
                    out.setdefault(d, {})["_total"] = v
            return out

    def top_owners(self, n: int = 10) -> List[Dict[str, Any]]:
        with self._lock:
            rows = sorted(
                self._owners.items(), key=lambda kv: -kv[1]
            )[:max(0, int(n))]
        return [
            {"device": d, "class": c, "owner": o, "bytes": v}
            for (d, c, o), v in rows
        ]

    def leak_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._leak_events]

    def debug_payload(self, top_n: int = 10,
                      reconcile: str = "auto") -> Dict[str, Any]:
        """The ``GET /debug/memory`` payload: per-device snapshot,
        watermarks, pressure, last truth-check, leak events and top-N
        owners. ``reconcile="auto"`` runs a fresh truth-check when the last
        one is missing or stale; "never" serves whatever is cached
        (tests/disabled paths)."""
        from mmlspark_tpu.core.config import get as _cfg_get

        stale_s = float(_cfg_get(
            "obs.memory.reconcile.stale.seconds", DEFAULT_RECONCILE_STALE_S))
        if reconcile == "always" or (
            reconcile == "auto" and self.enabled and (
                self._last_reconcile is None
                or time.monotonic() - self._last_reconcile_t > stale_s
            )
        ):
            self.reconcile()
        cap = self._hbm_capacity()
        with self._lock:
            last = self._last_reconcile
        snap = self.snapshot()
        pressure = {
            d: round(sum(by_cls.values()) / cap, 6)
            for d, by_cls in snap.items()
        } if cap > 0 else {}
        from mmlspark_tpu.obs.federation import proc_identity

        return {
            "proc_identity": proc_identity(),
            "classes": list(CLASSES),
            "resident": snap,
            "total_bytes": self.total_bytes(),
            "watermarks": self.watermarks(),
            "hbm_capacity_bytes": cap,
            "pressure": pressure,
            "reconcile": last,
            "drift_total": {
                "/".join(lbls): int(child.value())
                for lbls, child in self._drift_total.children()
            },
            "leak_events": self.leak_events(),
            "top_owners": self.top_owners(top_n),
        }

    def _hbm_capacity(self) -> float:
        if self._capacity is None:
            from mmlspark_tpu.core.env import hbm_bytes_per_device

            try:
                self._capacity = float(hbm_bytes_per_device())
            except Exception as e:  # backend not initializable: omit
                log.debug("hbm_capacity_unavailable", error=repr(e))
                self._capacity = 0.0
        return self._capacity

    def clear(self) -> None:
        """Drop all ledger state (tests); registry series persist but the
        live gauges zero out."""
        with self._lock:
            entries = list(self._resident.items())
            self._resident.clear()
            self._peaks.clear()
            self._dev_peaks.clear()
            self._owners.clear()
            self._trend.clear()
            self._leak_warned.clear()
            self._leak_events.clear()
            self._last_reconcile = None
            self._last_reconcile_t = 0.0
        for (d, c), _ in entries:
            self._resident_gauge.labels(device=d, **{"class": c}).set(0.0)


_LEDGER = DeviceMemoryLedger()


def memory_ledger() -> DeviceMemoryLedger:
    """The process-wide device-memory ledger singleton."""
    return _LEDGER
