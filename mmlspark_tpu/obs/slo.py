"""SLO engine: declarative objectives, error budgets, multi-window
multi-burn-rate alerts.

Raw gauges answer "what is the p99 right now"; an SLO answers the question
operators actually page on: "are we burning the error budget fast enough
that users will notice before the month ends". This module implements the
standard multi-window, multi-burn-rate construction (Beyer et al., *The
Site Reliability Workbook*, ch. 5) over the same request stream the
``serving_request_latency_ms{engine,code}`` family observes: every HTTP
edge (`ServingServer` and the distributed gateway) reports each finished
request into the process-wide `slo_monitor()`, and declarative `SLOSpec`s
evaluate availability or latency-threshold objectives over it.

- **Objectives.** ``availability``: a request is budget-burning when it
  finished 5xx (or died in transport). ``latency``: additionally when it
  exceeded ``latency_threshold_ms``. Shed 429s are deliberately NOT
  counted against availability — shedding is the overload protection
  doing its job and has its own counter (`serving_shed_requests_total`).
- **Burn rate.** For a window, ``burn = error_rate / (1 - target)``:
  burn 1 consumes exactly the budget by period end; burn 14.4 on a 99.9%
  SLO exhausts a 30-day budget in ~2 days. An alert fires only when BOTH
  the short and the long window of a `BurnWindow` pair exceed the
  threshold — the long window proves it's sustained, the short window
  resets the alert promptly once the burn stops.
- **Surfaces.** `slo_burn_alerts_total{slo,window}` counts activations;
  `slo_error_budget_remaining{slo}` and `slo_burn_rate{slo,window}` are
  gauges; every activation emits ONE structured ``slo_burn_alert`` log
  line carrying exemplar trace ids of budget-burning requests (the same
  ids the histogram exemplars and the flight recorder carry), and
  ``GET /healthz`` on both servers degrades to ``"degraded"`` while a
  page-severity burn alert is active (docs/observability.md "SLOs &
  burn-rate alerts").

Everything no-ops under ``obs.set_enabled(False)`` — `observe` consults
the metrics registry's enable flag, so the overhead bench's
`obs.disabled()` arm measures a true zero-cost baseline.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from mmlspark_tpu.obs.logging import get_logger
from mmlspark_tpu.obs.metrics import registry

__all__ = [
    "BurnWindow",
    "SLOSpec",
    "SLOMonitor",
    "slo_monitor",
]

log = get_logger("mmlspark_tpu.obs")


@dataclass(frozen=True)
class BurnWindow:
    """One (short, long) window pair with its burn-rate threshold.
    ``severity="page"`` degrades /healthz while active; ``"ticket"``
    alerts and counts without touching health."""

    name: str
    short_s: float
    long_s: float
    burn_threshold: float
    severity: str = "page"

    def __post_init__(self) -> None:
        if self.short_s <= 0 or self.long_s <= 0:
            raise ValueError("window lengths must be > 0")
        if self.short_s > self.long_s:
            raise ValueError("short window must not exceed the long window")
        if self.severity not in ("page", "ticket"):
            raise ValueError("severity must be 'page' or 'ticket'")


#: the SRE-workbook defaults for a 30-day budget: 5m/1h fast-burn page +
#: 30m/6h slow-burn ticket (tests/benches substitute scaled-down windows)
DEFAULT_WINDOWS: Tuple[BurnWindow, ...] = (
    BurnWindow("fast", 300.0, 3600.0, 14.4, "page"),
    BurnWindow("slow", 1800.0, 21600.0, 6.0, "ticket"),
)


@dataclass(frozen=True)
class SLOSpec:
    """One declarative objective over the serving request stream.

    ``engine`` selects one HTTP edge by its metrics label (a
    `ServingServer`'s ``engine`` label or the gateway's ``gateway``
    label); None spans every edge in the process. ``min_events`` keeps a
    cold window from alerting off two requests."""

    name: str
    objective: str = "availability"
    target: float = 0.99
    latency_threshold_ms: Optional[float] = None
    engine: Optional[str] = None
    windows: Tuple[BurnWindow, ...] = DEFAULT_WINDOWS
    min_events: int = 10

    def __post_init__(self) -> None:
        if self.objective not in ("availability", "latency"):
            raise ValueError("objective must be 'availability' or 'latency'")
        if not 0.0 < self.target < 1.0:
            raise ValueError("target must be in (0, 1)")
        if self.objective == "latency" and self.latency_threshold_ms is None:
            raise ValueError(
                "latency objective requires latency_threshold_ms"
            )
        if not self.windows:
            raise ValueError("at least one BurnWindow is required")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


class _Event:
    """One finished request as the SLO engine sees it."""

    __slots__ = ("t", "engine", "code", "latency_ms", "trace_id")

    def __init__(self, t: float, engine: str, code: int,
                 latency_ms: float, trace_id: Optional[str]):
        self.t = t
        self.engine = engine
        self.code = code
        self.latency_ms = latency_ms
        self.trace_id = trace_id


class SLOMonitor:
    """Process-wide burn-rate evaluator: bounded event ring, registered
    specs, active-alert state. `observe` is the hot path (append + an
    interval-gated evaluation); `evaluate` recomputes every spec/window
    and transitions alerts."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 max_events: int = 65536, eval_interval_s: float = 1.0):
        self._clock = clock
        self._lock = threading.Lock()
        self._events: "deque[_Event]" = deque(maxlen=max_events)
        self._specs: Dict[str, SLOSpec] = {}
        #: (slo, window) -> activation info for currently-firing alerts
        self._active: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.eval_interval_s = eval_interval_s
        self._last_eval = float("-inf")
        reg = registry()
        self._alerts_total = reg.counter(
            "slo_burn_alerts_total",
            "Multi-window burn-rate alert activations per SLO",
            ("slo", "window"),
        )
        self._budget_gauge = reg.gauge(
            "slo_error_budget_remaining",
            "Fraction of the SLO error budget left over the longest window",
            ("slo",),
        )
        self._burn_gauge = reg.gauge(
            "slo_burn_rate",
            "Short-window burn rate per SLO window pair at last evaluation",
            ("slo", "window"),
        )

    # -- spec management -------------------------------------------------------

    def register(self, spec: SLOSpec) -> SLOSpec:
        """Add (or replace) a spec; evaluation picks it up immediately."""
        with self._lock:
            self._specs[spec.name] = spec
            stale = [k for k in self._active if k[0] == spec.name]
            for k in stale:
                self._active.pop(k)
        return spec

    def unregister(self, name: str) -> None:
        with self._lock:
            self._specs.pop(name, None)
            for k in [k for k in self._active if k[0] == name]:
                self._active.pop(k)

    def specs(self) -> List[SLOSpec]:
        with self._lock:
            return list(self._specs.values())

    def clear(self) -> None:
        """Drop every spec, buffered event and active alert (metric series
        stay — Prometheus counters survive their source)."""
        with self._lock:
            self._specs.clear()
            self._events.clear()
            self._active.clear()

    # -- the observation hot path ----------------------------------------------

    def observe(self, engine: str, code: int, latency_ms: float,
                trace_id: Optional[str] = None) -> None:
        """Record one finished request (called by every HTTP edge at the
        same site that feeds serving_request_latency_ms). No-ops while the
        obs layer is disabled."""
        if not registry().enabled:
            return
        with self._lock:
            # clock read under the lock: appends stay timestamp-ordered, so
            # the evaluator's newest-to-oldest scan can stop at the window
            # edge without skipping a concurrently-appended newer event
            now = self._clock()
            self._events.append(
                _Event(now, engine, int(code), float(latency_ms), trace_id)
            )
            due = (
                self._specs
                and now - self._last_eval >= self.eval_interval_s
            )
            if due:
                self._last_eval = now
        if due:
            self.evaluate(now)

    def observe_batch(self, engine: str, code: int, latency_ms: float,
                      n: int) -> None:
        """Record `n` identical finished requests at once — the federation
        feed path (obs/federation.py), which sees worker outcomes as
        count/sum DELTAS per scrape rather than per-request calls. Events
        land at the current clock reading (the scrape time): federated
        burn-rate windows are therefore quantized to the scrape interval,
        which is the documented staleness floor of any scrape-based SLO."""
        if n <= 0 or not registry().enabled:
            return
        with self._lock:
            now = self._clock()
            ev = _Event(now, engine, int(code), float(latency_ms), None)
            self._events.extend([ev] * int(n))
            due = (
                self._specs
                and now - self._last_eval >= self.eval_interval_s
            )
            if due:
                self._last_eval = now
        if due:
            self.evaluate(now)

    # -- evaluation ------------------------------------------------------------

    @staticmethod
    def _classify(spec: SLOSpec, ev: _Event) -> Optional[bool]:
        """True = budget-burning, False = good, None = excluded. The
        availability objective burns on 5xx/transport failures; the
        latency objective burns on slow SUCCESSES and excludes errors
        entirely (they are availability's problem — counting them twice
        makes a latency 'control' fire on every error burst)."""
        errored = ev.code >= 500 or ev.code < 0
        if spec.objective == "latency":
            if errored:
                return None
            return ev.latency_ms > float(spec.latency_threshold_ms)
        return errored

    def _window_stats(
        self, spec: SLOSpec, events: List[_Event], now: float,
        lengths: List[float],
    ) -> Dict[float, Tuple[int, int, List[str]]]:
        """(total, bad, bad-trace-id exemplars) per trailing window length,
        computed in ONE newest-to-oldest pass — each event is engine-matched
        and classified once and folded into every window it falls in (the
        short windows are subsets of the longest, so separate scans would
        redo the same classification work per window)."""
        cutoffs = [(length, now - length) for length in lengths]
        oldest = now - max(lengths)
        acc: Dict[float, List[Any]] = {
            length: [0, 0, []] for length in lengths
        }
        for ev in reversed(events):
            if ev.t < oldest:
                break
            if spec.engine is not None and ev.engine != spec.engine:
                continue
            verdict = self._classify(spec, ev)
            if verdict is None:
                continue
            for length, cutoff in cutoffs:
                if ev.t < cutoff:
                    continue
                s = acc[length]
                s[0] += 1
                if verdict:
                    s[1] += 1
                    if ev.trace_id and len(s[2]) < 5:
                        s[2].append(ev.trace_id)
        return {
            length: (s[0], s[1], s[2]) for length, s in acc.items()
        }

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Recompute every spec/window, transition alert state, update the
        gauges; returns `status()`. Cheap at smoke scale (ONE reverse scan
        of the bounded ring per spec, all windows folded in) and
        interval-gated on the hot path."""
        now = self._clock() if now is None else now
        with self._lock:
            specs = list(self._specs.values())
            events = list(self._events)
        activated: List[Tuple[SLOSpec, BurnWindow, Dict[str, Any]]] = []
        resolved: List[Tuple[str, str]] = []
        for spec in specs:
            longest = max(w.long_s for w in spec.windows)
            lengths = {longest}
            for w in spec.windows:
                lengths.update((w.short_s, w.long_s))
            stats = self._window_stats(spec, events, now, sorted(lengths))
            total_l, bad_l, _ = stats[longest]
            err_l = bad_l / total_l if total_l else 0.0
            self._budget_gauge.labels(slo=spec.name).set(
                max(0.0, 1.0 - (err_l / spec.budget))
            )
            for win in spec.windows:
                t_s, b_s, ex_s = stats[win.short_s]
                t_l, b_l, _ = stats[win.long_s]
                burn_s = (b_s / t_s) / spec.budget if t_s else 0.0
                burn_l = (b_l / t_l) / spec.budget if t_l else 0.0
                self._burn_gauge.labels(
                    slo=spec.name, window=win.name
                ).set(round(burn_s, 4))
                firing = (
                    t_s >= spec.min_events
                    and t_l >= spec.min_events
                    and burn_s > win.burn_threshold
                    and burn_l > win.burn_threshold
                )
                key = (spec.name, win.name)
                with self._lock:
                    was = key in self._active
                    if firing and not was:
                        info = {
                            "since": now,
                            "severity": win.severity,
                            "burn_short": round(burn_s, 3),
                            "burn_long": round(burn_l, 3),
                            "threshold": win.burn_threshold,
                            "exemplar_trace_ids": ex_s,
                        }
                        self._active[key] = info
                        activated.append((spec, win, info))
                    elif not firing and was:
                        self._active.pop(key)
                        resolved.append(key)
        # alert bookkeeping outside the lock: counters + ONE structured
        # log line per activation, carrying the burning requests' trace
        # ids so the alert is joinable to traces/exemplars/flight records
        for spec, win, info in activated:
            self._alerts_total.labels(slo=spec.name, window=win.name).inc()
            log.warning(
                "slo_burn_alert", slo=spec.name, window=win.name,
                severity=win.severity, objective=spec.objective,
                target=spec.target, burn_short=info["burn_short"],
                burn_long=info["burn_long"], threshold=win.burn_threshold,
                exemplar_trace_ids=info["exemplar_trace_ids"],
            )
        for slo_name, win_name in resolved:
            log.info("slo_burn_resolved", slo=slo_name, window=win_name)
        return self.status()

    # -- health surfaces -------------------------------------------------------

    def _matches(self, spec: SLOSpec, engine: Optional[str]) -> bool:
        return engine is None or spec.engine is None or spec.engine == engine

    def status(self, engine: Optional[str] = None) -> Dict[str, Any]:
        """Per-SLO health for /healthz: alert state per window plus the
        budget gauge's last value. `engine` filters to specs covering that
        edge (None = all)."""
        with self._lock:
            specs = [
                s for s in self._specs.values() if self._matches(s, engine)
            ]
            active = dict(self._active)
        out: Dict[str, Any] = {}
        for spec in specs:
            alerts = {
                win.name: active[(spec.name, win.name)]
                for win in spec.windows
                if (spec.name, win.name) in active
            }
            out[spec.name] = {
                "objective": spec.objective,
                "target": spec.target,
                "engine": spec.engine,
                "healthy": not any(
                    a["severity"] == "page" for a in alerts.values()
                ),
                "burning": sorted(alerts),
                "alerts": alerts,
                "error_budget_remaining": round(
                    self._budget_gauge.labels(slo=spec.name).value(), 4
                ),
            }
        return out

    def page_burn_active(self, engine: Optional[str] = None) -> bool:
        """True while any page-severity burn alert is active for a spec
        covering `engine` — the /healthz 'degraded' trigger."""
        with self._lock:
            specs = {
                s.name: s for s in self._specs.values()
                if self._matches(s, engine)
            }
            return any(
                info["severity"] == "page"
                for (slo, _win), info in self._active.items()
                if slo in specs
            )


_MONITOR: List[SLOMonitor] = []
_MONITOR_LOCK = threading.Lock()


def slo_monitor() -> SLOMonitor:
    """The process-wide SLO monitor every HTTP edge reports into (lazy:
    instrument registration must not run at import time)."""
    if not _MONITOR:
        with _MONITOR_LOCK:
            if not _MONITOR:
                _MONITOR.append(SLOMonitor())
    return _MONITOR[0]
