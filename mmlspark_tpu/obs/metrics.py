"""Metrics registry: labelled Counter/Gauge/Histogram with Prometheus text
exposition.

The single metrics store every layer reports into (the pull-based
Prometheus/Monarch model): the dataplane counters, the serving engine's
stage meters, pipeline/GBDT stage timings all register here, and
`ServingServer` exposes the whole registry over ``GET /metrics``
(docs/observability.md). Design constraints, in order:

1. **Hot-path cheap.** `Counter.inc` / `Histogram.observe` are a lock plus
   two float adds — they run per transfer / per request on serving hot
   paths. Aggregation (quantiles, occupancy) happens at scrape time.
2. **Bounded memory.** Latency distributions go through a KLL-style
   streaming compactor (`QuantileSketch`): O(k·log n) floats regardless of
   traffic volume, so p50/p95/p99 stay cheap forever.
3. **Disableable.** `MetricsRegistry.set_enabled(False)` turns every
   instrument into a no-op (the rollback lever; the overhead smoke bench
   measures instrumented vs disabled throughput, BENCH_pr05.json).

Naming follows Prometheus conventions: counters end in ``_total``, time is
``_seconds`` or ``_ms``, label names are snake_case. Histograms render as
Prometheus *summary* families (``{quantile="0.99"}`` + ``_count``/``_sum``)
because the sketch gives exact-ish quantiles without fixed buckets.
"""

from __future__ import annotations

import threading
import time
import urllib.parse
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# top-level on purpose: observe() consults the active span per call, and a
# function-level import would re-run import machinery on the hot path.
# No cycle: tracing imports metrics only lazily (_dropped_counter).
from mmlspark_tpu.obs.tracing import current_span as _current_span

__all__ = [
    "QuantileSketch",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "parse_prometheus",
]


class QuantileSketch:
    """Bounded-memory streaming quantiles (a KLL-style merging compactor).

    Values land in a level-0 buffer of `k` floats; a full level sorts and
    keeps every other element (weight doubles) into the level above, so n
    observations occupy O(k·log(n/k)) floats. Rank error is O(1/k) — with
    the default k=128 the p99 of a latency stream is exact enough to gate a
    bench on. `quantile()` answers from one weighted sorted pass, so asking
    for p50/p95/p99 together costs one sort of ≤ k·levels items.

    Deterministic: compaction alternates keep-parity per level instead of
    randomizing, so identical streams give identical sketches (tests can
    assert exact behavior). Not thread-safe by itself — Histogram serializes
    access under its child lock.
    """

    def __init__(self, k: int = 128):
        if k < 8:
            raise ValueError("sketch k must be >= 8")
        self._k = int(k)
        self._levels: List[List[float]] = [[]]
        self._parity: List[int] = [0]
        self.count = 0
        self.min = float("inf")
        self.max = float("-inf")

    def add(self, value: float) -> None:
        v = float(value)
        self.count += 1
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self._levels[0].append(v)
        if len(self._levels[0]) >= self._k:
            self._compact(0)

    def _compact(self, i: int) -> None:
        lvl = sorted(self._levels[i])
        keep = lvl[self._parity[i]:: 2]
        self._parity[i] ^= 1
        self._levels[i] = []
        if i + 1 == len(self._levels):
            self._levels.append([])
            self._parity.append(0)
        self._levels[i + 1].extend(keep)
        if len(self._levels[i + 1]) >= self._k:
            self._compact(i + 1)

    def quantile(self, q: float) -> float:
        """Value at quantile q in [0, 1]; nan when empty. Always one of the
        retained samples, so min <= quantile(q) <= max, and monotone in q."""
        if self.count == 0:
            return float("nan")
        q = min(max(float(q), 0.0), 1.0)
        weighted: List[Tuple[float, int]] = []
        for i, lvl in enumerate(self._levels):
            w = 1 << i
            weighted.extend((v, w) for v in lvl)
        weighted.sort(key=lambda t: t[0])
        total = sum(w for _, w in weighted)
        target = q * total
        cum = 0
        for v, w in weighted:
            cum += w
            if cum >= target:
                return v
        return weighted[-1][0]

    def quantiles(self, qs: Iterable[float]) -> List[float]:
        return [self.quantile(q) for q in qs]

    # -- merge / serialization (the histogram-federation substrate) -----------
    #
    # Error bound under merge (docs/observability.md "Federation"): a merge
    # concatenates level buffers weight-for-weight, so it introduces NO new
    # error by itself; only the compactions it triggers do, and each
    # compaction of level i perturbs any rank by at most 2^i — the same
    # budget the streaming path spends. The merged sketch therefore keeps
    # the streaming guarantee: rank error O(log(n/k)/k) over the COMBINED
    # count n, not the sum of both inputs' worst cases. Merging m sketches
    # is no worse than one sketch that saw all n values in sequence.

    def merge(self, other: "QuantileSketch") -> None:
        """Fold `other` into this sketch in place. Level buffers concatenate
        weight-for-weight (level i carries weight 2^i in both), then any
        overfull level compacts through the usual deterministic path."""
        if other.count == 0:
            return
        self.count += other.count
        if other.min < self.min:
            self.min = other.min
        if other.max > self.max:
            self.max = other.max
        for i, lvl in enumerate(other._levels):
            while i >= len(self._levels):
                self._levels.append([])
                self._parity.append(0)
            self._levels[i].extend(lvl)
        # compact bottom-up: a spill from level i lands in i+1 before i+1
        # is itself checked, so one pass restores the <k invariant
        for i in range(len(self._levels)):
            while len(self._levels[i]) >= self._k:
                self._compact(i)

    def to_dict(self) -> Dict[str, Any]:
        """JSON-safe state (min/max are None when empty — inf round-trips
        through json as Infinity only under nonstandard parsers)."""
        return {
            "k": self._k,
            "count": self.count,
            "min": None if self.count == 0 else self.min,
            "max": None if self.count == 0 else self.max,
            "levels": [list(lvl) for lvl in self._levels],
            "parity": list(self._parity),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "QuantileSketch":
        sk = cls(int(d["k"]))
        levels = [[float(v) for v in lvl] for lvl in d["levels"]]
        parity = [int(p) for p in d["parity"]]
        if len(levels) != len(parity) or not levels:
            raise ValueError("sketch levels/parity mismatch")
        sk._levels = levels
        sk._parity = parity
        sk.count = int(d["count"])
        sk.min = float("inf") if d["min"] is None else float(d["min"])
        sk.max = float("-inf") if d["max"] is None else float(d["max"])
        return sk


def _label_key(
    labelnames: Tuple[str, ...], labels: Dict[str, str]
) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared {sorted(labelnames)}"
        )
    return tuple(str(labels[n]) for n in labelnames)


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _render_labels(
    labelnames: Tuple[str, ...], values: Tuple[str, ...],
    extra: Optional[Tuple[str, str]] = None,
) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(labelnames, values)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{extra[1]}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Family:
    """One named metric plus its labelled children (get-or-create)."""

    kind = "untyped"

    def __init__(self, reg: "MetricsRegistry", name: str, help: str,
                 labelnames: Tuple[str, ...]):
        self._reg = reg
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}

    def labels(self, **labels: str):
        key = _label_key(self.labelnames, labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def remove(self, **labels: str) -> None:
        """Drop one labelled child (and its series) from the family —
        callback gauges closing over a torn-down object MUST be removed at
        teardown or the registry pins the object graph forever."""
        key = _label_key(self.labelnames, labels)
        with self._lock:
            self._children.pop(key, None)

    def _default_child(self):
        if self.labelnames:
            raise ValueError(
                f"metric {self.name} declares labels {self.labelnames}; "
                "use .labels(...)"
            )
        return self.labels()

    def _make_child(self):
        raise NotImplementedError

    def children(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())


class _CounterChild:
    __slots__ = ("_fam", "_lock", "_value")

    def __init__(self, fam: "Counter"):
        self._fam = fam
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not self._fam._reg._enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    def value(self) -> float:
        with self._lock:
            return self._value


class Counter(_Family):
    kind = "counter"

    def _make_child(self) -> _CounterChild:
        return _CounterChild(self)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def value(self) -> float:
        return self._default_child().value()


class _GaugeChild:
    __slots__ = ("_fam", "_lock", "_value", "_fn")

    def __init__(self, fam: "Gauge"):
        self._fam = fam
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        if not self._fam._reg._enabled:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> float:
        """Add (may be negative); returns the new value so callers can do
        atomic read-modify chains (e.g. track a high-water mark)."""
        with self._lock:
            if self._fam._reg._enabled:
                self._value += amount
            return self._value

    def dec(self, amount: float = 1.0) -> float:
        return self.inc(-amount)

    def set_max(self, candidate: float) -> None:
        """value = max(value, candidate) — high-water marks."""
        if not self._fam._reg._enabled:
            return
        with self._lock:
            if candidate > self._value:
                self._value = float(candidate)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Collect-time callback: the gauge reads `fn()` at scrape instead
        of a stored value (queue depths, occupancy ratios)."""
        with self._lock:
            self._fn = fn

    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return float(fn())
        except Exception as e:
            # a dead callback must not kill the whole scrape; surface it as
            # NaN and log at debug
            _log().debug("gauge_callback_failed", error=repr(e))
            return float("nan")


class Gauge(_Family):
    kind = "gauge"

    def _make_child(self) -> _GaugeChild:
        return _GaugeChild(self)

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> float:
        return self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> float:
        return self._default_child().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._default_child().set_function(fn)

    def value(self) -> float:
        return self._default_child().value()


class _HistogramChild:
    __slots__ = ("_fam", "_lock", "_sketch", "_sum", "_exemplars")

    def __init__(self, fam: "Histogram"):
        self._fam = fam
        self._lock = threading.Lock()
        self._sketch = QuantileSketch(fam.sketch_k)
        self._sum = 0.0
        # recent trace-linked observations (value, trace_id, span_id, ts);
        # exposition renders the max-valued one so a p99 spike on the
        # scrape links to the trace that caused it (OpenMetrics exemplars)
        self._exemplars: List[Tuple[float, str, Optional[str], float]] = []

    def observe(self, value: float, trace_id: Optional[str] = None,
                span_id: Optional[str] = None) -> None:
        """Record one observation. When the histogram family has exemplars
        enabled, the active span's trace/span ids (or an explicit
        `trace_id=` for callers whose span already left the contextvar —
        the HTTP edge) ride along and surface in the exposition."""
        if not self._fam._reg._enabled:
            return
        if self._fam.exemplars and trace_id is None:
            span = _current_span()
            if span is not None and span.recording:
                trace_id, span_id = span.trace_id, span.span_id
        with self._lock:
            self._sketch.add(value)
            self._sum += value
            if trace_id is not None and self._fam.exemplars:
                self._exemplars.append(
                    (float(value), str(trace_id), span_id, time.time())
                )
                if len(self._exemplars) > 8:
                    del self._exemplars[0]

    def exemplar(self) -> Optional[Tuple[float, str, Optional[str], float]]:
        """The max-valued recent trace-linked observation, or None."""
        with self._lock:
            if not self._exemplars:
                return None
            return max(self._exemplars, key=lambda e: e[0])

    def quantile(self, q: float) -> float:
        with self._lock:
            return self._sketch.quantile(q)

    def count(self) -> int:
        with self._lock:
            return self._sketch.count

    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = {
                "count": float(self._sketch.count),
                "sum": self._sum,
                "min": self._sketch.min,
                "max": self._sketch.max,
            }
            for q in self._fam.quantiles:
                out[f"q{q}"] = self._sketch.quantile(q)
            return out


class Histogram(_Family):
    """Streaming-quantile histogram; renders as a Prometheus summary."""

    kind = "summary"

    def __init__(self, reg, name, help, labelnames,
                 quantiles: Tuple[float, ...] = (0.5, 0.95, 0.99),
                 sketch_k: int = 128, exemplars: bool = True):
        super().__init__(reg, name, help, labelnames)
        self.quantiles = tuple(quantiles)
        self.sketch_k = sketch_k
        self.exemplars = exemplars

    def _make_child(self) -> _HistogramChild:
        return _HistogramChild(self)

    def observe(self, value: float, trace_id: Optional[str] = None,
                span_id: Optional[str] = None) -> None:
        self._default_child().observe(value, trace_id=trace_id,
                                      span_id=span_id)

    def quantile(self, q: float) -> float:
        return self._default_child().quantile(q)

    def count(self) -> int:
        return self._default_child().count()

    def sum(self) -> float:
        return self._default_child().sum()


def _log():
    from mmlspark_tpu.obs.logging import get_logger

    return get_logger("mmlspark_tpu.obs")


def _format_value(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """Named, typed metric families; one instance per scrape surface.

    `registry()` returns the process-wide default every subsystem reports
    into and `/metrics` renders. Get-or-create semantics: asking for an
    existing name returns the existing family (type/labels must match —
    a mismatch is a programming error and raises).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self._enabled = True

    # -- enable/disable (the overhead rollback lever) -------------------------

    def set_enabled(self, enabled: bool) -> None:
        self._enabled = bool(enabled)

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- instrument constructors ----------------------------------------------

    def _family(self, cls, name: str, help: str,
                labelnames: Iterable[str], **kwargs) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if type(fam) is not cls or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} re-registered as {cls.kind} with "
                        f"labels {labelnames}, but exists as {fam.kind} with "
                        f"{fam.labelnames}"
                    )
                # kwargs (histogram quantiles/sketch_k) must match too — a
                # silent mismatch would drop the second caller's series
                mismatched = {
                    k: v for k, v in kwargs.items()
                    if getattr(fam, k, v) != v
                }
                if mismatched:
                    raise ValueError(
                        f"metric {name!r} re-registered with {mismatched}, "
                        "but the existing family differs"
                    )
                return fam
            fam = cls(self, name, help, labelnames, **kwargs)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Iterable[str] = ()) -> Counter:
        return self._family(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Iterable[str] = ()) -> Gauge:
        return self._family(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Iterable[str] = (),
                  quantiles: Tuple[float, ...] = (0.5, 0.95, 0.99),
                  sketch_k: int = 128, exemplars: bool = True) -> Histogram:
        return self._family(Histogram, name, help, labelnames,
                            quantiles=quantiles, sketch_k=sketch_k,
                            exemplars=exemplars)

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    # -- exposition -----------------------------------------------------------

    def render_prometheus(self, exemplars: bool = False) -> str:
        """The registry in Prometheus text exposition format 0.0.4.

        ``exemplars=True`` appends OpenMetrics-style exemplars to histogram
        ``_count`` lines. That syntax is NOT part of the classic text
        format — a stock Prometheus scraper would reject the whole payload
        — so servers emit it only on the explicit ``GET /metrics?
        exemplars=1`` diagnostic opt-in (render_scrape); the default
        exposition stays classic-parser safe."""
        lines: List[str] = []
        for fam in self.families():
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in fam.children():
                if isinstance(child, _HistogramChild):
                    snap = child.snapshot()
                    for q in fam.quantiles:
                        lines.append(
                            fam.name
                            + _render_labels(fam.labelnames, key,
                                             extra=("quantile", str(q)))
                            + f" {_format_value(snap[f'q{q}'])}"
                        )
                    base = _render_labels(fam.labelnames, key)
                    # OpenMetrics exemplar on the _count series: the max
                    # recent trace-linked observation, so a latency spike on
                    # the scrape carries the trace id that explains it.
                    # Rendered only when the caller asked (OpenMetrics
                    # negotiation) and suppressed while the registry is
                    # disabled (rollback parity).
                    ex = ""
                    exemplar = (child.exemplar()
                                if exemplars and self._enabled else None)
                    if exemplar is not None:
                        v, tid, sid, ts = exemplar
                        pairs = [("trace_id", tid)]
                        if sid:
                            pairs.append(("span_id", sid))
                        exl = ",".join(
                            f'{n}="{_escape_label(x)}"' for n, x in pairs
                        )
                        ex = (f" # {{{exl}}} {_format_value(v)} "
                              f"{round(ts, 3)}")
                    lines.append(f"{fam.name}_count{base} "
                                 f"{_format_value(snap['count'])}{ex}")
                    lines.append(f"{fam.name}_sum{base} "
                                 f"{_format_value(snap['sum'])}")
                else:
                    lines.append(
                        fam.name + _render_labels(fam.labelnames, key)
                        + f" {_format_value(child.value())}"
                    )
        return "\n".join(lines) + "\n"

    def render_scrape(self, query: str = "") -> Tuple[bytes, str]:
        """(body, content_type) for a GET /metrics exchange. The default is
        ALWAYS the classic 0.0.4 text a stock Prometheus parser accepts —
        regardless of Accept headers, which stock Prometheus fills with
        ``application/openmetrics-text`` by default while our exemplar
        exposition is OpenMetrics-STYLE, not spec-complete (exemplars ride
        summary-family ``_count`` lines). Exemplars are an explicit
        diagnostic opt-in via the ``?exemplars=1`` query parameter, which
        no stock scraper sends; ``parse_prometheus(return_exemplars=True)``
        is the matching consumer."""
        opts = urllib.parse.parse_qs(query or "")
        if opts.get("exemplars", ["0"])[-1].lower() in ("1", "true"):
            return (self.render_prometheus(exemplars=True).encode("utf-8"),
                    EXEMPLAR_CONTENT_TYPE)
        return (self.render_prometheus().encode("utf-8"),
                "text/plain; version=0.0.4")

    def export_sketches(self) -> Dict[str, Any]:
        """JSON-able histogram state for federation: the text exposition
        carries quantile VALUES, which cannot be recombined into an honest
        cluster p99 — so the federation scrape (`GET /metrics?sketches=1`)
        ships the full mergeable sketch per series instead. Keyed by family
        name; each series carries its labels, sketch state, and running sum
        (count lives inside the sketch)."""
        out: Dict[str, Any] = {}
        for fam in self.families():
            if not isinstance(fam, Histogram):
                continue
            series = []
            for key, child in fam.children():
                with child._lock:
                    series.append({
                        "labels": dict(zip(fam.labelnames, key)),
                        "sketch": child._sketch.to_dict(),
                        "sum": child._sum,
                    })
            out[fam.name] = {
                "help": fam.help,
                "labelnames": list(fam.labelnames),
                "quantiles": list(fam.quantiles),
                "series": series,
            }
        return out


#: content type for the opt-in exemplar-bearing exposition: classic text
#: plus OpenMetrics-style exemplar suffixes — a diagnostic format for
#: parse_prometheus and humans, NOT claimed as application/openmetrics-text
EXEMPLAR_CONTENT_TYPE = "text/plain; version=0.0.4; exemplars=1"


def _scan_label_block(s: str, start: int) -> Tuple[str, int]:
    """`s[start]` must be '{'; returns (inner blob, index past the closing
    '}'), quote-aware so label values holding '}' or '#' can't derail the
    scan."""
    in_q = escaped = False
    for i in range(start + 1, len(s)):
        ch = s[i]
        if escaped:
            escaped = False
        elif ch == "\\":
            escaped = True
        elif ch == '"':
            in_q = not in_q
        elif ch == "}" and not in_q:
            return s[start + 1:i], i + 1
    raise ValueError(f"unterminated label block in line: {s!r}")


def _parse_label_blob(blob: str, raw: str) -> List[Tuple[str, str]]:
    labels = []
    for item in _split_labels(blob):
        if not item:
            continue
        k, _, v = item.partition("=")
        v = v.strip()
        if not (v.startswith('"') and v.endswith('"')):
            raise ValueError(f"unquoted label value in line: {raw!r}")
        labels.append((k.strip(), _unescape_label(v[1:-1])))
    return labels


def parse_prometheus(
    text: str, return_exemplars: bool = False
) -> Any:
    """Parse Prometheus text exposition into {(name, ((label, value), ...)):
    value}. Covers the subset `render_prometheus` emits (and standard
    Prometheus output for it) — the scrape-parses gate in
    tests/test_bench_smoke.py uses this, so 'it renders' and 'it parses'
    are the same check.

    OpenMetrics exemplars (``... value # {trace_id="..."} exemplar_value
    ts``) are skipped by default — a parser that ignores them still reads
    the base series. With ``return_exemplars=True`` the result is
    ``(samples, exemplars)`` where exemplars maps the same series key to
    ``{"labels": {...}, "value": float, "timestamp": float | None}`` —
    the round-trip the exemplar tests gate on."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    exemplars: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                    Dict[str, Any]] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        brace = line.find("{")
        space = line.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            name = line[:brace].strip()
            blob, end = _scan_label_block(line, brace)
            labels = _parse_label_blob(blob, raw)
            rest = line[end:].strip()
        else:
            name, _, rest = line.partition(" ")
            name = name.strip()
            labels = []
            rest = rest.strip()
        if not rest:
            raise ValueError(f"unparseable metric line: {raw!r}")
        # the sample value never contains '#': everything after one is the
        # (optional) exemplar
        value_part, hash_, ex_part = rest.partition("#")
        parts = value_part.split()
        if not parts:
            raise ValueError(f"unparseable metric line: {raw!r}")
        key = (name, tuple(sorted(labels)))
        out[key] = float(parts[0])
        if hash_ and return_exemplars:
            ex = ex_part.strip()
            if not ex.startswith("{"):
                raise ValueError(f"malformed exemplar in line: {raw!r}")
            ex_blob, ex_end = _scan_label_block(ex, 0)
            ex_fields = ex[ex_end:].split()
            if not ex_fields:
                raise ValueError(f"exemplar missing value in line: {raw!r}")
            exemplars[key] = {
                "labels": dict(_parse_label_blob(ex_blob, raw)),
                "value": float(ex_fields[0]),
                "timestamp": (
                    float(ex_fields[1]) if len(ex_fields) > 1 else None
                ),
            }
    if return_exemplars:
        return out, exemplars
    return out


def _unescape_label(s: str) -> str:
    """Left-to-right unescape of a label value (inverse of _escape_label).
    Ordered str.replace would corrupt values holding literal backslash
    sequences — '\\\\n' must decode to backslash+n, not newline."""
    out: List[str] = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _split_labels(blob: str) -> List[str]:
    """Split a label block on commas outside quotes."""
    items, cur, in_q, escaped = [], [], False, False
    for ch in blob:
        if escaped:
            cur.append(ch)
            escaped = False
        elif ch == "\\":
            cur.append(ch)
            escaped = True
        elif ch == '"':
            cur.append(ch)
            in_q = not in_q
        elif ch == "," and not in_q:
            items.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    items.append("".join(cur).strip())
    return items


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry (`/metrics` renders this one)."""
    return _REGISTRY
