"""Request tracing: spans with ids, parent links and attributes.

The Dapper span model, sized for an in-process serving stack: a request
gets a root span at the HTTP edge (`ServingServer`), every stage it crosses
(parse -> score -> reply, then each `PipelineModel` stage) attaches a child
span, and the finished tree is exportable two ways:

- **JSONL** (`export_jsonl`): one span per line — greppable, diffable,
  loadable into anything.
- **Chrome trace_event** (`export_chrome_trace`): ``{"traceEvents": [...]}``
  with complete ("X") events — load it in Perfetto / chrome://tracing next
  to `profile_to`'s device traces to line host stages up against device
  activity.

Span timing uses `time.monotonic()` (durations must survive clock steps);
export converts to epoch timestamps through a wall-clock anchor captured
once at import. The tracer keeps a bounded ring of finished spans
(default 8192) so always-on tracing has O(1) memory; `set_enabled(False)`
makes every span a shared no-op object (the overhead lever, mirrored with
the metrics registry by `obs.set_enabled`).

Cross-thread propagation is explicit: the serving engine hands the request
span along in its work items and re-`activate()`s it in the worker thread.
Within a thread, `tracer().span(...)` nests under the currently active span
automatically (contextvars).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

__all__ = ["Span", "Tracer", "tracer", "current_span"]

# wall-clock anchor for export: spans time with monotonic, export maps to
# epoch as anchor_wall + (t - anchor_mono). time.time() is used ONLY as the
# fixed anchor, never differenced against another reading.
_ANCHOR_WALL = time.time()
_ANCHOR_MONO = time.monotonic()


def _epoch(t_mono: float) -> float:
    return _ANCHOR_WALL + (t_mono - _ANCHOR_MONO)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One timed, attributed operation. Mutable until `end()`; safe to hand
    across threads (attribute writes are GIL-atomic dict stores)."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "attrs", "events",
        "t_start", "t_end", "thread",
    )

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 attrs: Optional[Dict[str, Any]] = None,
                 t_start: Optional[float] = None):
        self.name = name
        self.trace_id = trace_id or _new_id()
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.events: List[Dict[str, Any]] = []
        self.t_start = time.monotonic() if t_start is None else t_start
        self.t_end: Optional[float] = None
        self.thread = threading.get_ident()

    @property
    def recording(self) -> bool:
        return True

    def set_attribute(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def add_event(self, name: str, **attrs: Any) -> None:
        """Point-in-time annotation inside the span (e.g. a d2h sync)."""
        self.events.append(
            {"name": name, "t": time.monotonic(), "attrs": attrs}
        )

    def duration_ms(self) -> float:
        end = self.t_end if self.t_end is not None else time.monotonic()
        return (end - self.t_start) * 1e3

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ts": round(_epoch(self.t_start), 6),
            "duration_ms": round(self.duration_ms(), 3),
            "attrs": self.attrs,
            "events": [
                {
                    "name": e["name"],
                    "ts": round(_epoch(e["t"]), 6),
                    "attrs": e["attrs"],
                }
                for e in self.events
            ],
            "thread": self.thread,
        }

    def __repr__(self) -> str:
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"span={self.span_id}, parent={self.parent_id})")


class _NoopSpan:
    """Shared do-nothing span returned while tracing is disabled."""

    trace_id = span_id = parent_id = None
    name = "noop"
    attrs: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    t_start = 0.0
    t_end = 0.0
    thread = 0

    @property
    def recording(self) -> bool:
        return False

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def add_event(self, name: str, **attrs: Any) -> None:
        pass

    def duration_ms(self) -> float:
        return 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {}


_NOOP = _NoopSpan()

_CURRENT: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "mmlspark_tpu_current_span", default=None
)


def current_span() -> Optional[Span]:
    """The span active in this thread/context, or None."""
    return _CURRENT.get()


class Tracer:
    """Creates spans, tracks the active one per thread, retains finished
    spans in a bounded ring for export."""

    def __init__(self, max_spans: int = 8192):
        self._lock = threading.Lock()
        self._finished: "deque[Span]" = deque(maxlen=max_spans)
        self._enabled = True
        # ring-overflow accounting: a deque with maxlen evicts SILENTLY, so
        # a tracing consumer can't tell "no spans" from "spans rotated out".
        # Evictions are counted per instance AND into a process counter
        # (trace_spans_dropped_total); high_water is the retention peak.
        self._dropped = 0
        self._high_water = 0

    # -- enable/disable --------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        self._enabled = bool(enabled)

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- span lifecycle --------------------------------------------------------

    def start_span(self, name: str, parent: Optional[Span] = None,
                   attrs: Optional[Dict[str, Any]] = None) -> Span:
        """Begin a span. `parent=None` nests under the context's current
        span when there is one; pass an explicit parent to propagate across
        threads (the serving engine's path)."""
        if not self._enabled:
            return _NOOP
        if parent is None:
            parent = _CURRENT.get()
        if parent is not None and parent.recording:
            return Span(name, trace_id=parent.trace_id,
                        parent_id=parent.span_id, attrs=attrs)
        return Span(name, attrs=attrs)

    def end_span(self, span: Span, t_end: Optional[float] = None) -> None:
        if not span.recording:
            return
        if span.t_end is None:
            span.t_end = time.monotonic() if t_end is None else t_end
        with self._lock:
            maxlen = self._finished.maxlen
            dropped = maxlen is not None and len(self._finished) >= maxlen
            self._finished.append(span)
            if dropped:
                self._dropped += 1
            if len(self._finished) > self._high_water:
                self._high_water = len(self._finished)
        if dropped:
            _dropped_counter().inc()

    def add_span(self, name: str, parent: Optional[Span],
                 t_start: float, t_end: float,
                 attrs: Optional[Dict[str, Any]] = None) -> Span:
        """Record an already-timed operation retroactively — batch stages
        attach one of these per request after timing the batch once."""
        if not self._enabled or (parent is not None and not parent.recording):
            return _NOOP
        span = Span(
            name,
            trace_id=parent.trace_id if parent is not None else None,
            parent_id=parent.span_id if parent is not None else None,
            attrs=attrs, t_start=t_start,
        )
        self.end_span(span, t_end=t_end)
        return span

    @contextlib.contextmanager
    def activate(self, span: Span) -> Iterator[Span]:
        """Make `span` the context's current span (so nested tracer.span
        calls parent to it) without ending it on exit."""
        if not span.recording:
            yield span
            return
        token = _CURRENT.set(span)
        try:
            yield span
        finally:
            _CURRENT.reset(token)

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[Span] = None,
             **attrs: Any) -> Iterator[Span]:
        """Context manager: start, activate, end. Exceptions mark the span
        (`error` attr) and propagate."""
        span = self.start_span(name, parent=parent, attrs=attrs or None)
        if not span.recording:
            yield span
            return
        token = _CURRENT.set(span)
        try:
            yield span
        except BaseException as e:
            span.set_attribute("error", repr(e))
            raise
        finally:
            _CURRENT.reset(token)
            self.end_span(span)

    # -- inspection / export ---------------------------------------------------

    def spans(self, trace_id: Optional[str] = None) -> List[Span]:
        """Finished spans (oldest first), optionally one trace's."""
        with self._lock:
            out = list(self._finished)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()

    def summary(self) -> Dict[str, Any]:
        """Ring health: retained/capacity, the retention high-water mark,
        and how many finished spans overflow has evicted — the signal that
        an export arrived too late to see the whole story."""
        with self._lock:
            return {
                "finished": len(self._finished),
                "max_spans": self._finished.maxlen,
                "high_water": self._high_water,
                "dropped": self._dropped,
            }

    def trace_summary(self, trace_id: str) -> str:
        """'http 12.3ms -> parse 1.1ms -> score 8.0ms -> reply 0.9ms' —
        the slow-request log line (children in start order)."""
        spans = sorted(self.spans(trace_id), key=lambda s: s.t_start)
        return " -> ".join(f"{s.name} {s.duration_ms():.1f}ms" for s in spans)

    def to_jsonl(self, trace_id: Optional[str] = None) -> str:
        return "".join(
            json.dumps(s.to_dict(), sort_keys=True) + "\n"
            for s in self.spans(trace_id)
        )

    def export_jsonl(self, path: str, trace_id: Optional[str] = None) -> int:
        """Write spans as JSON Lines; returns the span count."""
        spans = self.spans(trace_id)
        with open(path, "w", encoding="utf-8") as f:
            for s in spans:
                f.write(json.dumps(s.to_dict(), sort_keys=True) + "\n")
        return len(spans)

    def chrome_trace(self, trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Chrome trace_event JSON (Perfetto / chrome://tracing loadable):
        complete ("X") events per span, instant ("i") events per span event."""
        pid = os.getpid()
        events: List[Dict[str, Any]] = []
        for s in self.spans(trace_id):
            args = dict(s.attrs)
            args["trace_id"] = s.trace_id
            args["span_id"] = s.span_id
            if s.parent_id:
                args["parent_id"] = s.parent_id
            events.append({
                "name": s.name,
                "cat": "mmlspark_tpu",
                "ph": "X",
                "ts": round(_epoch(s.t_start) * 1e6, 1),
                "dur": round(s.duration_ms() * 1e3, 1),
                "pid": pid,
                "tid": s.thread,
                "args": args,
            })
            for e in s.events:
                events.append({
                    "name": e["name"],
                    "cat": "mmlspark_tpu.event",
                    "ph": "i",
                    "s": "t",
                    "ts": round(_epoch(e["t"]) * 1e6, 1),
                    "pid": pid,
                    "tid": s.thread,
                    "args": dict(e["attrs"]),
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str,
                            trace_id: Optional[str] = None) -> int:
        """Write the Chrome trace_event file; returns the event count."""
        trace = self.chrome_trace(trace_id)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(trace, f)
        return len(trace["traceEvents"])


_DROPPED = []


def _dropped_counter():
    """The process-wide overflow counter, resolved lazily: obs.metrics
    imports this module at its top level, so importing it back eagerly
    (or from Tracer.__init__, which runs during THIS module's import)
    would deadlock the partially-initialized module graph."""
    if not _DROPPED:
        from mmlspark_tpu.obs.metrics import registry

        _DROPPED.append(registry().counter(
            "trace_spans_dropped_total",
            "Finished spans evicted from a tracer ring by overflow",
        ))
    return _DROPPED[0]


_TRACER = Tracer()


def tracer() -> Tracer:
    """The process-wide tracer every layer reports spans into."""
    return _TRACER
