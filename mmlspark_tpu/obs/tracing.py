"""Request tracing: spans with ids, parent links and attributes.

The Dapper span model, sized for an in-process serving stack: a request
gets a root span at the HTTP edge (`ServingServer`), every stage it crosses
(parse -> score -> reply, then each `PipelineModel` stage) attaches a child
span, and the finished tree is exportable two ways:

- **JSONL** (`export_jsonl`): one span per line — greppable, diffable,
  loadable into anything.
- **Chrome trace_event** (`export_chrome_trace`): ``{"traceEvents": [...]}``
  with complete ("X") events — load it in Perfetto / chrome://tracing next
  to `profile_to`'s device traces to line host stages up against device
  activity.

Span timing uses `time.monotonic()` (durations must survive clock steps);
export converts to epoch timestamps through a wall-clock anchor captured
once at import. The tracer keeps a bounded ring of finished spans
(default 8192) so always-on tracing has O(1) memory; `set_enabled(False)`
makes every span a shared no-op object (the overhead lever, mirrored with
the metrics registry by `obs.set_enabled`).

Cross-thread propagation is explicit: the serving engine hands the request
span along in its work items and re-`activate()`s it in the worker thread.
Within a thread, `tracer().span(...)` nests under the currently active span
automatically (contextvars).

Cross-PROCESS propagation is W3C Trace Context: `inject_context(span,
headers)` writes a ``traceparent`` (and optional ``tracestate``) header,
`extract_context(headers)` parses one back into a `SpanContext` that
`start_span(context=...)` parents under — the serving gateway injects on
every worker-bound request and `ServingServer` extracts, so one trace id
follows a request from gateway admission through retries/hedges into the
worker's parse/score/reply tree (docs/observability.md "Trace
propagation"). The ``sampled`` flag rides bit 0 of the trace-flags byte so
workers agree with the gateway's head-sampling decision.

Retention is TAIL-BASED, not FIFO: the interesting traces are the rare bad
ones, so spans whose trace erred, shed, retried, or crossed the latency
threshold are pinned in a separate ring while healthy spans stay 1-in-N
sampled (`set_sampling`) and rotate out first. `mark_trace` is how the
fabric flags a trace mid-flight (retry/hedge/shed) — already-finished
spans of that trace are promoted out of the healthy ring so the whole
tree survives overflow.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import re
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Iterable, Iterator, List, Mapping, Optional

__all__ = [
    "Span",
    "SpanContext",
    "Tracer",
    "tracer",
    "current_span",
    "extract_context",
    "format_traceparent",
    "inject_context",
    "stitch_trace_trees",
]

# wall-clock anchor for export: spans time with monotonic, export maps to
# epoch as anchor_wall + (t - anchor_mono). time.time() is used ONLY as the
# fixed anchor, never differenced against another reading.
_ANCHOR_WALL = time.time()
_ANCHOR_MONO = time.monotonic()


def _epoch(t_mono: float) -> float:
    return _ANCHOR_WALL + (t_mono - _ANCHOR_MONO)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


# -- W3C Trace Context (cross-process propagation) -----------------------------

#: version "00" traceparent: version-traceid(32 lhex)-parentid(16 lhex)-flags
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)
_SAMPLED_FLAG = 0x01


@dataclass(frozen=True)
class SpanContext:
    """A remote parent extracted from ``traceparent``: enough to continue
    the trace in this process without holding the remote Span object."""

    trace_id: str
    span_id: str
    sampled: bool = True
    tracestate: Optional[str] = None


def format_traceparent(span: Any) -> Optional[str]:
    """The W3C ``traceparent`` value for `span`, or None while the span is
    not recording (tracing disabled — nothing to propagate). Our 16-hex
    trace ids are zero-padded to the wire's 32; extract strips the padding
    back so inject -> extract round-trips to the same id."""
    if span is None or not getattr(span, "recording", False):
        return None
    flags = _SAMPLED_FLAG if getattr(span, "sampled", True) else 0x00
    return f"00-{span.trace_id:0>32}-{span.span_id:0>16}-{flags:02x}"


def inject_context(
    span: Any, headers: Dict[str, str],
    tracestate: Optional[str] = None,
) -> Dict[str, str]:
    """Write ``traceparent`` (and a pass-through ``tracestate``) into the
    headers dict for an outbound cross-process call; returns the same dict.
    graftcheck's ``untraced-cross-process-call`` rule keys on this being
    visibly applied to every gateway->worker send."""
    tp = format_traceparent(span)
    if tp is not None:
        headers["traceparent"] = tp
        if tracestate:
            headers["tracestate"] = tracestate
    return headers


def extract_context(headers: Mapping[str, str]) -> Optional[SpanContext]:
    """Parse an inbound ``traceparent`` into a SpanContext, or None when
    the header is absent or malformed (an untraced or garbage caller must
    never fail the request — the span just becomes a fresh root)."""
    try:
        raw = headers.get("traceparent")
    except (AttributeError, TypeError):  # not a mapping: treat as absent
        return None
    if not raw or not isinstance(raw, str):
        return None
    m = _TRACEPARENT_RE.match(raw.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    # all-zero ids are invalid per spec; version ff is reserved-invalid
    if version == "ff" or set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    if trace_id.startswith("0" * 16) and set(trace_id[16:]) != {"0"}:
        trace_id = trace_id[16:]  # our own zero-padded 16-hex ids
    try:
        tracestate = headers.get("tracestate")
    except (AttributeError, TypeError):  # not a mapping: no state to carry
        tracestate = None
    return SpanContext(
        trace_id, span_id,
        sampled=bool(int(flags, 16) & _SAMPLED_FLAG),
        tracestate=tracestate or None,
    )


class Span:
    """One timed, attributed operation. Mutable until `end()`; safe to hand
    across threads (attribute writes are GIL-atomic dict stores)."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "attrs", "events",
        "t_start", "t_end", "thread", "sampled", "end_seq",
    )

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 attrs: Optional[Dict[str, Any]] = None,
                 t_start: Optional[float] = None):
        self.name = name
        self.trace_id = trace_id or _new_id()
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.events: List[Dict[str, Any]] = []
        self.t_start = time.monotonic() if t_start is None else t_start
        self.t_end: Optional[float] = None
        self.thread = threading.get_ident()
        # head-sampling verdict for HEALTHY retention (inherited from the
        # parent / propagated context; tail pinning overrides it for
        # interesting traces) and the tracer-assigned finish order
        self.sampled = True
        self.end_seq = 0

    @property
    def recording(self) -> bool:
        return True

    def set_attribute(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def add_event(self, name: str, **attrs: Any) -> None:
        """Point-in-time annotation inside the span (e.g. a d2h sync)."""
        self.events.append(
            {"name": name, "t": time.monotonic(), "attrs": attrs}
        )

    def duration_ms(self) -> float:
        end = self.t_end if self.t_end is not None else time.monotonic()
        return (end - self.t_start) * 1e3

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ts": round(_epoch(self.t_start), 6),
            "duration_ms": round(self.duration_ms(), 3),
            "attrs": self.attrs,
            "events": [
                {
                    "name": e["name"],
                    "ts": round(_epoch(e["t"]), 6),
                    "attrs": e["attrs"],
                }
                for e in self.events
            ],
            "thread": self.thread,
        }

    def __repr__(self) -> str:
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"span={self.span_id}, parent={self.parent_id})")


class _NoopSpan:
    """Shared do-nothing span returned while tracing is disabled."""

    trace_id = span_id = parent_id = None
    name = "noop"
    attrs: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    t_start = 0.0
    t_end = 0.0
    thread = 0
    sampled = False
    end_seq = 0

    @property
    def recording(self) -> bool:
        return False

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def add_event(self, name: str, **attrs: Any) -> None:
        pass

    def duration_ms(self) -> float:
        return 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {}


_NOOP = _NoopSpan()

_CURRENT: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "mmlspark_tpu_current_span", default=None
)


def current_span() -> Optional[Span]:
    """The span active in this thread/context, or None."""
    return _CURRENT.get()


class Tracer:
    """Creates spans, tracks the active one per thread, retains finished
    spans with tail-based priority: interesting traces (erred, shed,
    retried, slow — flagged via `mark_trace` or self-classified at
    `end_span`) land in a pinned ring that healthy-span overflow can never
    evict; healthy spans stay head-sampled 1-in-N (`set_sampling`) and
    rotate FIFO. Unsampled healthy spans wait in a small limbo ring so a
    trace flagged LATE (the root errs after its children finished) still
    assembles a complete tree."""

    def __init__(self, max_spans: int = 8192,
                 max_pinned: Optional[int] = None,
                 sample_every: int = 1,
                 latency_threshold_ms: Optional[float] = None):
        self._lock = threading.Lock()
        self._finished: "deque[Span]" = deque(maxlen=max_spans)
        self._pinned: "deque[Span]" = deque(
            maxlen=max_pinned if max_pinned is not None
            else max(64, max_spans // 4)
        )
        self._limbo: "deque[Span]" = deque(maxlen=max(16, max_spans // 8))
        self._enabled = True
        # ring-overflow accounting: a deque with maxlen evicts SILENTLY, so
        # a tracing consumer can't tell "no spans" from "spans rotated out".
        # Evictions are counted per instance AND into a process counter
        # (trace_spans_dropped_total); high_water is the retention peak.
        self._dropped = 0
        self._sampled_out = 0
        self._high_water = 0
        self._sample_every = max(1, int(sample_every))
        self._latency_threshold_ms = latency_threshold_ms
        self._root_count = 0
        self._seq = itertools.count(1)
        # interesting trace ids -> reason, bounded FIFO so always-on
        # flagging is O(1) memory like the rings
        self._flagged: Dict[str, str] = {}
        self._flag_cap = 4096

    # -- enable/disable --------------------------------------------------------

    def set_enabled(self, enabled: bool) -> None:
        self._enabled = bool(enabled)

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- retention policy knobs ------------------------------------------------

    def set_sampling(self, sample_every: int) -> None:
        """Head-sample healthy-trace retention to 1-in-N new roots (1 =
        keep every healthy trace, the default). The verdict is stored on
        the root span, inherited by children, and propagated cross-process
        in the traceparent sampled flag so workers agree with the
        gateway's decision. Interesting traces are pinned regardless."""
        self._sample_every = max(1, int(sample_every))

    @property
    def sample_every(self) -> int:
        return self._sample_every

    def set_latency_threshold_ms(self, threshold_ms: Optional[float]) -> None:
        """Spans at/over this duration classify their trace as interesting
        (pinned) at end_span; None disables latency pinning."""
        self._latency_threshold_ms = threshold_ms

    def mark_trace(self, trace_id: Optional[str], reason: str = "flagged") -> None:
        """Flag a trace as interesting mid-flight (retry, hedge, shed,
        breaker trip): every span of it — already finished OR still open —
        is retained in the pinned ring instead of the healthy rotation."""
        if not self._enabled or not trace_id:
            return
        evicted = 0
        with self._lock:
            evicted = self._flag_locked(trace_id, reason)
        for _ in range(evicted):
            _dropped_counter().inc()

    def trace_flag(self, trace_id: str) -> Optional[str]:
        """The reason a trace was flagged, or None."""
        with self._lock:
            return self._flagged.get(trace_id)

    # -- retention internals (caller holds the lock) ---------------------------

    def _flag_locked(self, trace_id: str, reason: str) -> int:
        if trace_id in self._flagged:
            return 0
        self._flagged[trace_id] = reason
        while len(self._flagged) > self._flag_cap:
            self._flagged.pop(next(iter(self._flagged)))
        # promote this trace's already-finished spans out of the healthy
        # and limbo rings so later overflow can't break up its tree
        evicted = 0
        for ring in (self._finished, self._limbo):
            moved = [s for s in ring if s.trace_id == trace_id]
            if moved:
                kept = [s for s in ring if s.trace_id != trace_id]
                ring.clear()
                ring.extend(kept)
                for s in moved:
                    evicted += self._pin_locked(s)
        return evicted

    def _pin_locked(self, span: Span) -> int:
        maxlen = self._pinned.maxlen
        evicting = maxlen is not None and len(self._pinned) >= maxlen
        if evicting:
            self._dropped += 1
        self._pinned.append(span)
        return 1 if evicting else 0

    # -- span lifecycle --------------------------------------------------------

    def start_span(self, name: str, parent: Optional[Span] = None,
                   attrs: Optional[Dict[str, Any]] = None,
                   context: Optional[SpanContext] = None) -> Span:
        """Begin a span. `parent=None` nests under the context's current
        span when there is one; pass an explicit parent to propagate across
        threads (the serving engine's path), or a `SpanContext` from
        `extract_context` to continue a remote caller's trace (the
        cross-process path — context wins over any local parent)."""
        if not self._enabled:
            return _NOOP
        if context is not None:
            span = Span(name, trace_id=context.trace_id,
                        parent_id=context.span_id, attrs=attrs)
            span.sampled = bool(context.sampled)
            return span
        if parent is None:
            parent = _CURRENT.get()
        if parent is not None and parent.recording:
            span = Span(name, trace_id=parent.trace_id,
                        parent_id=parent.span_id, attrs=attrs)
            span.sampled = parent.sampled
            return span
        span = Span(name, attrs=attrs)
        span.sampled = self._sample_root()
        return span

    def _sample_root(self) -> bool:
        if self._sample_every <= 1:
            return True
        with self._lock:
            self._root_count += 1
            return self._root_count % self._sample_every == 1

    def end_span(self, span: Span, t_end: Optional[float] = None) -> None:
        if not span.recording:
            return
        if span.t_end is None:
            span.t_end = time.monotonic() if t_end is None else t_end
        # self-classification: an error attr or a duration over the
        # threshold makes the whole TRACE interesting (tail-based), not
        # just this span
        reason: Optional[str] = None
        if "error" in span.attrs:
            reason = "error"
        else:
            thr = self._latency_threshold_ms
            if thr is not None and (span.t_end - span.t_start) * 1e3 >= thr:
                reason = "slow"
        evicted = 0
        with self._lock:
            span.end_seq = next(self._seq)
            if reason is not None:
                evicted += self._flag_locked(span.trace_id, reason)
            if span.trace_id in self._flagged:
                evicted += self._pin_locked(span)
            elif span.sampled:
                maxlen = self._finished.maxlen
                if maxlen is not None and len(self._finished) >= maxlen:
                    self._dropped += 1
                    evicted += 1
                self._finished.append(span)
            else:
                maxlen = self._limbo.maxlen
                if maxlen is not None and len(self._limbo) >= maxlen:
                    self._sampled_out += 1
                self._limbo.append(span)
            retained = len(self._finished) + len(self._pinned)
            if retained > self._high_water:
                self._high_water = retained
        for _ in range(evicted):
            _dropped_counter().inc()

    def add_span(self, name: str, parent: Optional[Span],
                 t_start: float, t_end: float,
                 attrs: Optional[Dict[str, Any]] = None) -> Span:
        """Record an already-timed operation retroactively — batch stages
        attach one of these per request after timing the batch once."""
        if not self._enabled or (parent is not None and not parent.recording):
            return _NOOP
        span = Span(
            name,
            trace_id=parent.trace_id if parent is not None else None,
            parent_id=parent.span_id if parent is not None else None,
            attrs=attrs, t_start=t_start,
        )
        if parent is not None:
            span.sampled = parent.sampled
        self.end_span(span, t_end=t_end)
        return span

    @contextlib.contextmanager
    def activate(self, span: Span) -> Iterator[Span]:
        """Make `span` the context's current span (so nested tracer.span
        calls parent to it) without ending it on exit."""
        if not span.recording:
            yield span
            return
        token = _CURRENT.set(span)
        try:
            yield span
        finally:
            _CURRENT.reset(token)

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[Span] = None,
             **attrs: Any) -> Iterator[Span]:
        """Context manager: start, activate, end. Exceptions mark the span
        (`error` attr) and propagate."""
        span = self.start_span(name, parent=parent, attrs=attrs or None)
        if not span.recording:
            yield span
            return
        token = _CURRENT.set(span)
        try:
            yield span
        except BaseException as e:
            span.set_attribute("error", repr(e))
            raise
        finally:
            _CURRENT.reset(token)
            self.end_span(span)

    # -- inspection / export ---------------------------------------------------

    def spans(self, trace_id: Optional[str] = None) -> List[Span]:
        """Finished retained spans in finish order (oldest first),
        optionally one trace's — the healthy ring and the pinned ring
        merged; limbo (unsampled, not yet flagged) spans are not
        exported."""
        with self._lock:
            out = sorted(
                itertools.chain(self._finished, self._pinned),
                key=lambda s: s.end_seq,
            )
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()
            self._pinned.clear()
            self._limbo.clear()
            self._flagged.clear()

    def summary(self) -> Dict[str, Any]:
        """Ring health: retained/capacity, the retention high-water mark,
        and how many finished spans overflow has evicted — the signal that
        an export arrived too late to see the whole story. `pinned` /
        `flagged_traces` report the tail-retention side; `sampled_out`
        counts healthy spans head-sampling let rotate out of limbo."""
        with self._lock:
            return {
                "finished": len(self._finished),
                "pinned": len(self._pinned),
                "limbo": len(self._limbo),
                "max_spans": self._finished.maxlen,
                "max_pinned": self._pinned.maxlen,
                "high_water": self._high_water,
                "dropped": self._dropped,
                "sampled_out": self._sampled_out,
                "flagged_traces": len(self._flagged),
                "sample_every": self._sample_every,
            }

    def trace_tree(self, trace_id: str) -> Dict[str, Any]:
        """The assembled cross-hop tree for one trace: every retained span
        nested under its parent (spans whose parent is missing — a remote
        hop that never reported, or rotation loss — surface as roots).
        ``GET /debug/trace?trace_id=`` serves exactly this."""
        spans = sorted(self.spans(trace_id), key=lambda s: s.t_start)
        by_id: Dict[str, Dict[str, Any]] = {}
        for s in spans:
            d = s.to_dict()
            d["children"] = []
            by_id[s.span_id] = d
        roots: List[Dict[str, Any]] = []
        for s in spans:
            d = by_id[s.span_id]
            if s.parent_id and s.parent_id in by_id:
                by_id[s.parent_id]["children"].append(d)
            else:
                roots.append(d)
        return {
            "trace_id": trace_id,
            "span_count": len(spans),
            "flag": self.trace_flag(trace_id),
            "roots": roots,
        }

    def trace_summary(self, trace_id: str) -> str:
        """'http 12.3ms -> parse 1.1ms -> score 8.0ms -> reply 0.9ms' —
        the slow-request log line (children in start order)."""
        spans = sorted(self.spans(trace_id), key=lambda s: s.t_start)
        return " -> ".join(f"{s.name} {s.duration_ms():.1f}ms" for s in spans)

    def to_jsonl(self, trace_id: Optional[str] = None) -> str:
        return "".join(
            json.dumps(s.to_dict(), sort_keys=True) + "\n"
            for s in self.spans(trace_id)
        )

    def export_jsonl(self, path: str, trace_id: Optional[str] = None) -> int:
        """Write spans as JSON Lines; returns the span count."""
        spans = self.spans(trace_id)
        with open(path, "w", encoding="utf-8") as f:
            for s in spans:
                f.write(json.dumps(s.to_dict(), sort_keys=True) + "\n")
        return len(spans)

    def chrome_trace(self, trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Chrome trace_event JSON (Perfetto / chrome://tracing loadable):
        complete ("X") events per span, instant ("i") events per span event."""
        pid = os.getpid()
        events: List[Dict[str, Any]] = []
        for s in self.spans(trace_id):
            args = dict(s.attrs)
            args["trace_id"] = s.trace_id
            args["span_id"] = s.span_id
            if s.parent_id:
                args["parent_id"] = s.parent_id
            events.append({
                "name": s.name,
                "cat": "mmlspark_tpu",
                "ph": "X",
                "ts": round(_epoch(s.t_start) * 1e6, 1),
                "dur": round(s.duration_ms() * 1e3, 1),
                "pid": pid,
                "tid": s.thread,
                "args": args,
            })
            for e in s.events:
                events.append({
                    "name": e["name"],
                    "cat": "mmlspark_tpu.event",
                    "ph": "i",
                    "s": "t",
                    "ts": round(_epoch(e["t"]) * 1e6, 1),
                    "pid": pid,
                    "tid": s.thread,
                    "args": dict(e["attrs"]),
                })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str,
                            trace_id: Optional[str] = None) -> int:
        """Write the Chrome trace_event file; returns the event count."""
        trace = self.chrome_trace(trace_id)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(trace, f)
        return len(trace["traceEvents"])


def stitch_trace_trees(
    trace_id: str, trees: Iterable[Dict[str, Any]]
) -> Dict[str, Any]:
    """Merge several processes' ``trace_tree`` payloads into ONE tree.

    The federation fan-out (``GET /debug/trace?trace_id=&scope=cluster``)
    collects one assembled tree per process; each carries the span dicts
    that process retained. Because traceparent propagation gives every
    cross-process child its remote parent's span_id, flattening all trees,
    deduping by span_id (a gateway and an in-process worker can both
    retain the same span), and re-nesting by parent_id reconstructs the
    cluster-wide tree — a worker's http span that was a ROOT in the
    worker's local view re-parents under the gateway's attempt span here.
    Spans whose parent is missing everywhere stay roots, same contract as
    ``Tracer.trace_tree``."""
    flat: Dict[str, Dict[str, Any]] = {}

    def _walk(node: Dict[str, Any]) -> None:
        sid = node.get("span_id")
        if sid is not None and sid not in flat:
            flat[sid] = {k: v for k, v in node.items() if k != "children"}
        for child in node.get("children", ()):
            _walk(child)

    flag = None
    for tree in trees:
        if not isinstance(tree, dict):
            continue
        if flag is None:
            flag = tree.get("flag")
        for root in tree.get("roots", ()):
            _walk(root)

    ordered = sorted(flat.values(), key=lambda d: d.get("start_ts") or 0.0)
    by_id: Dict[str, Dict[str, Any]] = {}
    for d in ordered:
        d = dict(d)
        d["children"] = []
        by_id[d["span_id"]] = d
    roots: List[Dict[str, Any]] = []
    for d in ordered:
        node = by_id[d["span_id"]]
        parent = d.get("parent_id")
        if parent and parent in by_id:
            by_id[parent]["children"].append(node)
        else:
            roots.append(node)
    return {
        "trace_id": trace_id,
        "span_count": len(by_id),
        "flag": flag,
        "roots": roots,
    }


_DROPPED = []


def _dropped_counter():
    """The process-wide overflow counter, resolved lazily: obs.metrics
    imports this module at its top level, so importing it back eagerly
    (or from Tracer.__init__, which runs during THIS module's import)
    would deadlock the partially-initialized module graph."""
    if not _DROPPED:
        from mmlspark_tpu.obs.metrics import registry

        _DROPPED.append(registry().counter(
            "trace_spans_dropped_total",
            "Finished spans evicted from a tracer ring by overflow",
        ))
    return _DROPPED[0]


_TRACER = Tracer()


def tracer() -> Tracer:
    """The process-wide tracer every layer reports spans into."""
    return _TRACER
