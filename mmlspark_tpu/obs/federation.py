"""Cross-process observability federation: scrape, merge, re-export.

Every obs surface built so far — MetricsRegistry, the Tracer ring, the
flight recorder, the device-memory ledger, the SLO monitor — is a
process-local singleton. That is fine while `DistributedServingServer`
workers share the gateway's process, and silently blind the moment they
become real subprocesses (the ROADMAP's process-isolation item). This
module is the bridge, built over the existing HTTP wire protocol so the
isolation PR can land without touching observability again:

- **Metrics federation** (`Federator`): the gateway scrapes each worker's
  ``GET /metrics?sketches=1`` on `scrape_interval_s`, parses the classic
  exposition with `parse_prometheus`, and re-exports the union with a
  `proc` label per source (``proc="gateway"`` / ``proc="worker-<i>"``)
  plus cluster-aggregate series under ``proc="cluster"``. Merge semantics
  per metric type (docs/observability.md "Federation"): counters sum
  (reset-corrected, so a worker restart never makes a merged counter go
  backwards), gauges pass through labelled, summaries pass quantiles
  through per-proc and recombine honest cluster quantiles by merging the
  serialized `QuantileSketch` state the ``?sketches=1`` payload carries —
  the text exposition alone ships quantile VALUES, which cannot be merged.
- **Process identity**: `proc_identity()` stamps payloads with
  (proc, pid, start_time). Sources whose identity matches are the SAME
  process registry seen twice (today's in-process workers), so federation
  dedupes by identity before merging — no double counting now, and the
  same code is automatically correct when identities diverge.
- **Cluster SLOs**: on each scrape round the federator diffs every
  worker-side `serving_request_latency_ms` count/sum series and feeds the
  deltas into the local `SLOMonitor` under a cluster engine label, so an
  `SLOSpec(engine=<cluster label>)` registered AT THE GATEWAY burns on
  worker-side errors it never forwarded — federated data alone.
- **Federation health telemetry**: `obs_federation_scrape_seconds{worker}`,
  `obs_federation_scrape_failures_total{worker,kind}`, and a scrape-time
  `obs_federation_staleness_seconds{worker}` gauge, plus a structured
  ``federation_scrape_failed`` warning; `is_stale()` feeds the router's
  health view (a worker unscrapeable for `stale_after_intervals` scrape
  intervals is suspect even if its socket still accepts).

Everything is clock-injectable and passive by default — `scrape_all()` is
driven either by the optional background thread (`start()`/`stop()`) or
lazily at exposition time, and unit tests drive it directly.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from mmlspark_tpu.obs.logging import get_logger
from mmlspark_tpu.obs.metrics import (
    EXEMPLAR_CONTENT_TYPE,
    MetricsRegistry,
    QuantileSketch,
    _escape_label,
    _format_value,
    parse_prometheus,
    registry as obs_registry,
)

log = get_logger("mmlspark_tpu.obs")

__all__ = [
    "FederationConfig",
    "Federator",
    "proc_identity",
    "set_proc_label",
    "identity_key",
    "scrape_payload",
]

#: wall-clock process start, anchored at import — with the pid it uniquely
#: names one OS process incarnation (a recycled pid won't recycle the pair)
_PROC_START = time.time()
_PROC_LABEL: Optional[str] = None
_PROC_LOCK = threading.Lock()


def set_proc_label(label: Optional[str]) -> None:
    """Name this process for debug payloads (``"worker-3"`` in a real
    subprocess worker). Defaults to ``pid-<pid>`` when unset."""
    global _PROC_LABEL
    with _PROC_LOCK:
        _PROC_LABEL = label


def proc_identity() -> Dict[str, Any]:
    """The process-identity stamp every /debug/flight and /debug/memory
    payload (and federation scrape payload) carries: which process said
    this. `start_time` disambiguates pid recycling and lets the federation
    layer detect a restarted worker behind a stable address."""
    with _PROC_LOCK:
        label = _PROC_LABEL
    pid = os.getpid()
    return {
        "proc": label or f"pid-{pid}",
        "pid": pid,
        "start_time": round(_PROC_START, 3),
    }


def identity_key(identity: Optional[Dict[str, Any]]) -> Optional[Tuple]:
    """Hashable dedupe key for a proc_identity dict (None when absent or
    malformed — such sources are never merged with anything)."""
    if not isinstance(identity, dict):
        return None
    pid, start = identity.get("pid"), identity.get("start_time")
    if pid is None or start is None:
        return None
    return (int(pid), float(start))


def scrape_payload(
    reg: Optional[MetricsRegistry] = None, probe: bool = False
) -> Dict[str, Any]:
    """The ``GET /metrics?sketches=1`` JSON body a federation scrape
    consumes in one exchange: the classic text exposition (parsed with
    `parse_prometheus`, counters/gauges/quantile values), the mergeable
    histogram sketch state (`MetricsRegistry.export_sketches`), and this
    process's identity (the dedupe/merge key).

    With ``probe=True`` (``?probe=1``) only the identity is returned.
    A federator requests this once it has learned a target shares its
    own process: the full exposition would be discarded by the identity
    dedupe anyway, and rendering it on every scrape makes in-process
    workers pay GIL time proportional to registry size just to prove
    they are alive."""
    if probe:
        return {"proc_identity": proc_identity(), "probe": True}
    reg = reg or obs_registry()
    return {
        "proc_identity": proc_identity(),
        "exposition": reg.render_prometheus(),
        "sketches": reg.export_sketches(),
    }


def _parse_meta(text: str) -> Tuple[Dict[str, str], Dict[str, str]]:
    """(types, helps) from ``# TYPE`` / ``# HELP`` comment lines — the
    family metadata `parse_prometheus` deliberately skips, which the
    merge layer needs to pick summation vs pass-through."""
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) >= 4:
                types[parts[2]] = parts[3]
        elif line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) >= 4:
                helps[parts[2]] = parts[3]
    return types, helps


@dataclass
class FederationConfig:
    """Federation knobs (docs/observability.md "Federation").

    `extra_targets` adds federation-only peers — (host, port) pairs the
    gateway scrapes and fans debug queries out to without routing API
    traffic at them. This is the seam the real-subprocess integration
    test uses, and the shape multi-host pools will plug into."""

    enabled: bool = True
    scrape_interval_s: float = 2.0
    scrape_timeout_s: float = 5.0
    #: a worker whose last successful scrape is older than
    #: stale_after_intervals * scrape_interval_s is suspect (router view)
    stale_after_intervals: int = 3
    #: re-export label values
    cluster_proc_label: str = "cluster"
    gateway_proc_label: str = "gateway"
    #: cluster-SLO feed: diff this summary family's _count/_sum per
    #: (engine, code) and replay the deltas into the local SLOMonitor
    feed_slo: bool = True
    slo_source_family: str = "serving_request_latency_ms"
    #: engine label the synthesized events carry; None lets the gateway
    #: pick a per-instance label (``<gateway_label>-cluster``)
    slo_engine: Optional[str] = None
    #: per-series cap on events replayed per scrape round (burst guard)
    slo_max_events_per_scrape: int = 1024
    extra_targets: Tuple[Tuple[str, int], ...] = ()


class _Target:
    """Scrape-side state for one federation peer."""

    __slots__ = (
        "name", "fetch", "last_attempt_t", "last_success_t", "last_error",
        "identity", "types", "helps", "samples", "raw", "offsets",
        "sketches", "ok_count", "fail_count",
    )

    def __init__(self, name: str,
                 fetch: Callable[[str], Tuple[int, bytes]],
                 now: float):
        self.name = name
        self.fetch = fetch
        self.last_attempt_t: Optional[float] = None
        # staleness is measured from registration until the first success
        # (grace: a just-added worker is not "stale", it is unscraped)
        self.last_success_t = now
        self.last_error: Optional[str] = None
        self.identity: Optional[Dict[str, Any]] = None
        self.types: Dict[str, str] = {}
        self.helps: Dict[str, str] = {}
        #: reset-corrected samples (what federation re-exports)
        self.samples: Dict[Tuple[str, Tuple], float] = {}
        #: last raw counter-like readings (reset detection)
        self.raw: Dict[Tuple[str, Tuple], float] = {}
        #: per-series monotonic carry across worker restarts
        self.offsets: Dict[Tuple[str, Tuple], float] = {}
        self.sketches: Dict[str, Any] = {}
        self.ok_count = 0
        self.fail_count = 0


class Federator:
    """Scrapes a set of peers, merges their metric state with the local
    registry, and renders the federated exposition. Thread-safe; one
    instance per gateway."""

    def __init__(
        self,
        reg: Optional[MetricsRegistry] = None,
        config: Optional[FederationConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        slo: Optional[Any] = None,
        slo_engine: Optional[str] = None,
        slo_exclude_engines: Tuple[str, ...] = (),
        gateway_label: Optional[str] = None,
    ):
        self.config = config or FederationConfig()
        self._reg = reg or obs_registry()
        self._clock = clock
        self._slo = slo
        self.slo_engine = (
            slo_engine or self.config.slo_engine or "cluster"
        )
        self._slo_exclude = set(slo_exclude_engines)
        self._slo_exclude.add(self.slo_engine)
        # the registry is process-global and gateways get torn up and down
        # within one process (tests, hot restarts): the gateway label keys
        # this instance's telemetry children apart, same contract as the
        # serving_fabric_* families
        self._gw = gateway_label or "gateway"
        # _lock guards target/merge state; _scrape_lock serializes scrape
        # rounds. NEITHER is ever held across a network fetch: a scraped
        # peer may share this process's registry (in-process workers), and
        # rendering it evaluates this federator's staleness gauge — a lock
        # held over the fetch would deadlock against the reply it awaits
        self._lock = threading.RLock()
        self._scrape_lock = threading.Lock()
        self._targets: Dict[str, _Target] = {}
        self._slo_base: Dict[Tuple, Tuple[float, float]] = {}
        #: source identities that already have a baseline epoch (see
        #: _feed_slo: priming is per-SOURCE, not per-series)
        self._slo_seen: set = set()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._scrape_hist = self._reg.histogram(
            "obs_federation_scrape_seconds",
            "Federation scrape duration per worker (fetch + parse + merge)",
            ("gateway", "worker"),
        )
        self._fail_counter = self._reg.counter(
            "obs_federation_scrape_failures_total",
            "Failed federation scrapes per worker by failure kind",
            ("gateway", "worker", "kind"),
        )
        self._stale_gauge = self._reg.gauge(
            "obs_federation_staleness_seconds",
            "Seconds since the last successful federation scrape per worker",
            ("gateway", "worker"),
        )

    # -- targets ---------------------------------------------------------------

    def set_targets(
        self, targets: Dict[str, Callable[[str], Tuple[int, bytes]]]
    ) -> None:
        """Replace the scrape-target set. Each value fetches a path from
        that peer and returns (status, body) — transport errors raise.
        Existing per-target state survives for names that persist."""
        with self._lock:
            for name in list(self._targets):
                if name not in targets:
                    del self._targets[name]
                    self._stale_gauge.remove(gateway=self._gw, worker=name)
            now = self._clock()
            for name, fetch in targets.items():
                tgt = self._targets.get(name)
                if tgt is None:
                    self._targets[name] = tgt = _Target(name, fetch, now)
                    self._stale_gauge.labels(
                        gateway=self._gw, worker=name
                    ).set_function(
                        lambda n=name: round(self.staleness_s(n), 3)
                    )
                else:
                    tgt.fetch = fetch

    def target_names(self) -> List[str]:
        with self._lock:
            return list(self._targets)

    # -- scraping --------------------------------------------------------------

    def _counter_like(self, name: str, types: Dict[str, str]) -> bool:
        if types.get(name) == "counter":
            return True
        for suffix in ("_count", "_sum"):
            if name.endswith(suffix):
                base = name[: -len(suffix)]
                if types.get(base) == "summary":
                    return True
        return False

    def _fail(self, tgt: _Target, kind: str, err: BaseException) -> None:
        tgt.fail_count += 1
        tgt.last_error = repr(err)
        self._fail_counter.labels(
            gateway=self._gw, worker=tgt.name, kind=kind
        ).inc()
        log.warning(
            "federation_scrape_failed", worker=tgt.name, kind=kind,
            error=repr(err),
            staleness_s=round(self.staleness_s(tgt.name), 3),
        )

    def scrape_target(self, name: str) -> bool:
        with self._scrape_lock:
            return self._scrape_one(name)

    def _scrape_one(self, name: str) -> bool:
        """One scrape of one peer; returns success. Failures are counted
        by kind (transport/http/parse), logged structurally, and leave the
        previous good state in place — a dead worker's last-known series
        keep rendering (with its staleness gauge rising) rather than
        vanishing mid-incident. The fetch runs OUTSIDE every lock (see
        __init__); only the state swap afterwards takes `_lock`."""
        me = identity_key(proc_identity())
        with self._lock:
            tgt = self._targets.get(name)
            probe = (
                tgt is not None
                and tgt.identity is not None
                and identity_key(tgt.identity) == me
            )
        if tgt is None:
            raise KeyError(f"unknown federation target {name!r}")
        # a target known to share this process gets an identity-only
        # probe: its exposition would be dropped by the identity dedupe,
        # so don't make it render the registry just to prove liveness
        path = ("/metrics?sketches=1&probe=1" if probe
                else "/metrics?sketches=1")
        t0 = self._clock()
        tgt.last_attempt_t = t0
        try:
            status, body = tgt.fetch(path)
        except Exception as e:  # transport: refused, timeout, reset
            self._fail(tgt, "transport", e)
            return False
        if status != 200:
            self._fail(tgt, "http", RuntimeError(f"HTTP {status}"))
            return False
        try:
            identity, text, sketches = self._decode_payload(body)
            if (identity is not None
                    and identity_key(identity) == me):
                # the peer shares THIS process's registry (today's
                # in-process workers): its parsed samples would be
                # discarded by the identity dedupe in sources() anyway,
                # so skip the parse/merge and keep the scrape as proof
                # of liveness — this is most of a scrape round's cost
                samples, types, helps, sketches = {}, {}, {}, {}
            else:
                samples = parse_prometheus(text)
                types, helps = _parse_meta(text)
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            self._fail(tgt, "parse", e)
            return False
        with self._lock:
            # counter-reset correction: a restarted worker's counters drop
            # to zero; folding the pre-restart reading into a per-series
            # offset keeps every re-exported counter monotonic
            restarted = (
                tgt.identity is not None
                and identity is not None
                and identity_key(identity) != identity_key(tgt.identity)
            )
            corrected: Dict[Tuple[str, Tuple], float] = {}
            new_raw: Dict[Tuple[str, Tuple], float] = {}
            for key, value in samples.items():
                if self._counter_like(key[0], types):
                    prev = tgt.raw.get(key)
                    if prev is not None and (restarted or value < prev):
                        tgt.offsets[key] = tgt.offsets.get(key, 0.0) + prev
                    new_raw[key] = value
                    corrected[key] = tgt.offsets.get(key, 0.0) + value
                else:
                    corrected[key] = value
            tgt.identity = identity
            tgt.types = types
            tgt.helps = helps
            tgt.samples = corrected
            tgt.raw = new_raw
            tgt.sketches = sketches
            tgt.last_success_t = self._clock()
            tgt.last_error = None
            tgt.ok_count += 1
        self._scrape_hist.labels(gateway=self._gw, worker=name).observe(
            max(0.0, tgt.last_success_t - t0)
        )
        return True

    @staticmethod
    def _decode_payload(
        body: bytes,
    ) -> Tuple[Optional[Dict[str, Any]], str, Dict[str, Any]]:
        """A federation payload (JSON with identity + sketches) or, as a
        downgrade path, a bare classic exposition from a peer that does
        not speak ``?sketches=1``."""
        text = body.decode("utf-8")
        if text.lstrip().startswith("{"):
            payload = json.loads(text)
            return (
                payload.get("proc_identity"),
                payload.get("exposition", ""),
                payload.get("sketches") or {},
            )
        return None, text, {}

    def scrape_all(self, force: bool = False) -> int:
        """Scrape every target whose last attempt is older than the
        configured interval (all of them with ``force=True``); then, if
        anything was scraped, replay worker request outcomes into the SLO
        monitor. Returns the number of targets scraped."""
        scraped = 0
        with self._scrape_lock:
            now = self._clock()
            with self._lock:
                due = [
                    name
                    for name, tgt in self._targets.items()
                    if force
                    or tgt.last_attempt_t is None
                    or now - tgt.last_attempt_t
                    >= self.config.scrape_interval_s
                ]
            for name in due:
                try:
                    self._scrape_one(name)
                except KeyError:
                    continue  # target removed mid-round
                scraped += 1
            if scraped and self.config.feed_slo:
                self._feed_slo()
        return scraped

    # -- staleness -------------------------------------------------------------

    def staleness_s(self, name: str) -> float:
        # deliberately lock-free (dict read + float read, atomic under the
        # GIL): this is the staleness gauge's scrape-time callback, and a
        # peer sharing this process renders that gauge while a scrape of
        # it is in flight — taking _lock here would re-create the deadlock
        # the fetch-outside-locks rule exists to prevent
        tgt = self._targets.get(name)
        if tgt is None:
            return 0.0
        return max(0.0, self._clock() - tgt.last_success_t)

    def is_stale(self, name: str) -> bool:
        """True when `name` has been unscrapeable past the staleness
        budget — the router-health signal (a worker that stopped
        answering scrapes is suspect even if its socket accepts)."""
        limit = (
            self.config.stale_after_intervals * self.config.scrape_interval_s
        )
        return self.staleness_s(name) > limit

    def snapshot(self) -> Dict[str, Any]:
        """The ``/healthz`` federation block: per-worker scrape health."""
        with self._lock:
            return {
                "scrape_interval_s": self.config.scrape_interval_s,
                "stale_after_intervals": self.config.stale_after_intervals,
                "slo_engine": self.slo_engine,
                "targets": {
                    name: {
                        "staleness_s": round(self.staleness_s(name), 3),
                        "stale": self.is_stale(name),
                        "scrapes_ok": tgt.ok_count,
                        "scrapes_failed": tgt.fail_count,
                        "last_error": tgt.last_error,
                        "proc_identity": tgt.identity,
                    }
                    for name, tgt in self._targets.items()
                },
            }

    # -- merge / render --------------------------------------------------------

    def _local_source(self) -> Dict[str, Any]:
        text = self._reg.render_prometheus()
        types, helps = _parse_meta(text)
        return {
            "label": self.config.gateway_proc_label,
            "local": True,
            "identity": proc_identity(),
            "samples": parse_prometheus(text),
            "types": types,
            "helps": helps,
            "sketches": self._reg.export_sketches(),
        }

    def sources(self) -> List[Dict[str, Any]]:
        """Merge inputs, deduped by process identity: the local registry
        first, then every successfully-scraped target whose identity is
        NOT one already seen. Today's in-process workers all collapse into
        the single local source (their scrapes ARE the shared registry);
        real subprocess workers each contribute their own."""
        with self._lock:
            out = [self._local_source()]
            seen = {identity_key(out[0]["identity"])}
            for name, tgt in self._targets.items():
                if not tgt.samples and tgt.identity is None:
                    continue  # never scraped successfully
                key = identity_key(tgt.identity)
                if key is not None and key in seen:
                    continue
                if key is not None:
                    seen.add(key)
                out.append({
                    "label": name,
                    "local": False,
                    "identity": tgt.identity,
                    "samples": tgt.samples,
                    "types": tgt.types,
                    "helps": tgt.helps,
                    "sketches": tgt.sketches,
                })
            return out

    @staticmethod
    def _labels_str(labels: Tuple, proc: str,
                    extra: Optional[Tuple[str, str]] = None) -> str:
        pairs = list(labels)
        if not any(k == "proc" for k, _ in pairs):
            pairs.append(("proc", proc))
        if extra is not None:
            pairs.append(extra)
        pairs.sort()
        body = ",".join(
            f'{k}="{_escape_label(str(v))}"' for k, v in pairs
        )
        return "{" + body + "}" if body else ""

    def _local_exemplars(self) -> Dict[Tuple[str, Tuple], str]:
        """Exemplar suffixes for gateway-local histogram ``_count`` lines
        (the ``?exemplars=1`` opt-in; remote scrapes don't carry them)."""
        from mmlspark_tpu.obs.metrics import Histogram

        out: Dict[Tuple[str, Tuple], str] = {}
        if not self._reg.enabled:
            return out
        for fam in self._reg.families():
            if not isinstance(fam, Histogram):
                continue
            for key, child in fam.children():
                ex = child.exemplar()
                if ex is None:
                    continue
                v, tid, sid, ts = ex
                pairs = [("trace_id", tid)]
                if sid:
                    pairs.append(("span_id", sid))
                blob = ",".join(
                    f'{n}="{_escape_label(x)}"' for n, x in pairs
                )
                labels = tuple(sorted(zip(fam.labelnames, key)))
                out[(fam.name, labels)] = (
                    f" # {{{blob}}} {_format_value(v)} {round(ts, 3)}"
                )
        return out

    def _family_meta(
        self, srcs: List[Dict[str, Any]]
    ) -> Dict[str, Tuple[str, str]]:
        meta: Dict[str, Tuple[str, str]] = {}
        summary_parts = set()
        for src in srcs:
            for fam, kind in src["types"].items():
                if fam not in meta:
                    meta[fam] = (kind, src["helps"].get(fam, ""))
                if kind == "summary":
                    summary_parts.add(fam + "_count")
                    summary_parts.add(fam + "_sum")
        # series with no TYPE line anywhere (foreign exposition): untyped
        for src in srcs:
            for (name, _labels) in src["samples"]:
                if name not in meta and name not in summary_parts:
                    meta[name] = ("untyped", "")
        return meta

    def render_text(self, exemplars: bool = False) -> str:
        """The federated exposition: per-source series under their `proc`
        label plus ``proc="cluster"`` aggregates (summed counters, merged
        sketch quantiles with summed count/sum). Valid 0.0.4 text — it
        parses back through `parse_prometheus` (the round-trip gate)."""
        srcs = self.sources()
        cluster = self.config.cluster_proc_label
        local_ex = self._local_exemplars() if exemplars else {}
        meta = self._family_meta(srcs)
        lines: List[str] = []
        for fam in sorted(meta):
            kind, help_ = meta[fam]
            if help_:
                lines.append(f"# HELP {fam} {help_}")
            lines.append(f"# TYPE {fam} {kind}")
            if kind == "summary":
                self._render_summary(
                    lines, fam, srcs, cluster, local_ex
                )
            elif kind == "counter":
                totals: Dict[Tuple, float] = {}
                for src in srcs:
                    for (name, labels), v in sorted(src["samples"].items()):
                        if name != fam:
                            continue
                        lines.append(
                            fam + self._labels_str(labels, src["label"])
                            + f" {_format_value(v)}"
                        )
                        totals[labels] = totals.get(labels, 0.0) + v
                for labels in sorted(totals):
                    lines.append(
                        fam + self._labels_str(labels, cluster)
                        + f" {_format_value(totals[labels])}"
                    )
            else:  # gauge / untyped: labelled pass-through, no aggregate
                for src in srcs:
                    for (name, labels), v in sorted(src["samples"].items()):
                        if name != fam:
                            continue
                        lines.append(
                            fam + self._labels_str(labels, src["label"])
                            + f" {_format_value(v)}"
                        )
        return "\n".join(lines) + "\n"

    def _render_summary(
        self, lines: List[str], fam: str, srcs: List[Dict[str, Any]],
        cluster: str, local_ex: Dict[Tuple[str, Tuple], str],
    ) -> None:
        # per-proc pass-through: quantile values, then _count/_sum
        cl_counts: Dict[Tuple, List[float]] = {}
        cl_sketch: Dict[Tuple, QuantileSketch] = {}
        cl_quant: Dict[Tuple, List[float]] = {}
        for src in srcs:
            label = src["label"]
            for (name, labels), v in sorted(src["samples"].items()):
                if name == fam:
                    lines.append(
                        fam + self._labels_str(labels, label)
                        + f" {_format_value(v)}"
                    )
            for (name, labels), v in sorted(src["samples"].items()):
                if name == fam + "_count":
                    ex = local_ex.get((fam, labels), "") if src["local"] else ""
                    lines.append(
                        f"{fam}_count" + self._labels_str(labels, label)
                        + f" {_format_value(v)}{ex}"
                    )
                    cl_counts.setdefault(labels, [0.0, 0.0])[0] += v
                elif name == fam + "_sum":
                    lines.append(
                        f"{fam}_sum" + self._labels_str(labels, label)
                        + f" {_format_value(v)}"
                    )
                    cl_counts.setdefault(labels, [0.0, 0.0])[1] += v
            fam_sk = src["sketches"].get(fam)
            if fam_sk:
                for series in fam_sk.get("series", ()):
                    lk = tuple(sorted(
                        (str(k), str(v)) for k, v in series["labels"].items()
                    ))
                    try:
                        sk = QuantileSketch.from_dict(series["sketch"])
                    except (KeyError, TypeError, ValueError) as e:
                        log.warning("federation_sketch_invalid",
                                    family=fam, error=repr(e))
                        continue
                    if lk in cl_sketch:
                        cl_sketch[lk].merge(sk)
                    else:
                        cl_sketch[lk] = sk
                    cl_quant.setdefault(
                        lk, list(fam_sk.get("quantiles") or (0.5, 0.95, 0.99))
                    )
        # cluster aggregate: merged-sketch quantiles (honest cluster p99),
        # summed monotonic count/sum. After a worker restart the counts
        # keep the reset-corrected offset while the sketch restarts with
        # the process — standard counter-vs-distribution semantics.
        for labels in sorted(cl_counts):
            sk = cl_sketch.get(labels)
            if sk is not None and sk.count > 0:
                for q in cl_quant.get(labels, (0.5, 0.95, 0.99)):
                    lines.append(
                        fam + self._labels_str(
                            labels, cluster, extra=("quantile", str(q))
                        )
                        + f" {_format_value(sk.quantile(q))}"
                    )
            cnt, sm = cl_counts[labels]
            lines.append(
                f"{fam}_count" + self._labels_str(labels, cluster)
                + f" {_format_value(cnt)}"
            )
            lines.append(
                f"{fam}_sum" + self._labels_str(labels, cluster)
                + f" {_format_value(sm)}"
            )

    def merged_sketches(self) -> Dict[str, Any]:
        """Cluster-merged sketch state in the `export_sketches` shape, so
        a gateway can itself be scraped by a parent federator
        (hierarchical federation) without losing mergeability."""
        merged: Dict[str, Any] = {}
        for src in self.sources():
            for fam, fam_sk in src["sketches"].items():
                slot = merged.setdefault(fam, {
                    "help": fam_sk.get("help", ""),
                    "labelnames": fam_sk.get("labelnames", []),
                    "quantiles": fam_sk.get("quantiles", [0.5, 0.95, 0.99]),
                    "_series": {},
                })
                for series in fam_sk.get("series", ()):
                    lk = tuple(sorted(series["labels"].items()))
                    try:
                        sk = QuantileSketch.from_dict(series["sketch"])
                    except (KeyError, TypeError, ValueError):
                        continue
                    cur = slot["_series"].get(lk)
                    if cur is None:
                        slot["_series"][lk] = {
                            "labels": dict(series["labels"]),
                            "sketch": sk,
                            "sum": float(series.get("sum", 0.0)),
                        }
                    else:
                        cur["sketch"].merge(sk)
                        cur["sum"] += float(series.get("sum", 0.0))
        out: Dict[str, Any] = {}
        for fam, slot in merged.items():
            out[fam] = {
                "help": slot["help"],
                "labelnames": slot["labelnames"],
                "quantiles": slot["quantiles"],
                "series": [
                    {
                        "labels": s["labels"],
                        "sketch": s["sketch"].to_dict(),
                        "sum": s["sum"],
                    }
                    for _lk, s in sorted(slot["_series"].items())
                ],
            }
        return out

    def render_scrape(self, query: str = "") -> Tuple[bytes, str]:
        """(body, content_type) for the federated ``GET /metrics``.
        Refreshes due targets first, so a quiet gateway still serves a
        current cluster view. ``?sketches=1`` answers with the federation
        JSON payload (identity + exposition + cluster-merged sketches);
        ``?exemplars=1`` appends gateway-local exemplars."""
        opts = urllib.parse.parse_qs(query or "")

        def flag(name: str) -> bool:
            return opts.get(name, ["0"])[-1].lower() in ("1", "true")

        if flag("probe"):
            # identity-only liveness answer for an in-process parent
            # federator (see scrape_payload): no refresh, no render
            body = json.dumps(
                scrape_payload(probe=True), sort_keys=True
            ).encode("utf-8")
            return body, "application/json"
        self.scrape_all()
        exemplars = flag("exemplars")
        text = self.render_text(exemplars=exemplars)
        if flag("sketches"):
            body = json.dumps({
                "proc_identity": proc_identity(),
                "exposition": text,
                "sketches": self.merged_sketches(),
            }, sort_keys=True).encode("utf-8")
            return body, "application/json"
        ct = (EXEMPLAR_CONTENT_TYPE if exemplars
              else "text/plain; version=0.0.4")
        return text.encode("utf-8"), ct

    # -- cluster SLO feed ------------------------------------------------------

    def _feed_slo(self) -> None:
        """Replay worker-side request outcomes into the local SLOMonitor
        under the cluster engine label, from the federated count/sum
        deltas — a cluster SLOSpec burns at the gateway on failures it
        never forwarded. First sight of a SOURCE primes its baselines
        without replaying history (pre-federation counts have no
        timestamps to honestly replay); a series first appearing LATER
        from an already-baselined source accumulated entirely under
        federation, so its whole count replays from an implicit zero —
        an error burst mid-incident must not be swallowed as 'history'
        just because code="500" had never been seen before."""
        slo = self._slo
        if slo is None:
            from mmlspark_tpu.obs.slo import slo_monitor

            slo = self._slo = slo_monitor()
        fam = self.config.slo_source_family
        for src in self._slo_sources():
            ident = identity_key(src["identity"]) or ("src", src["label"])
            first_sight = ident not in self._slo_seen
            self._slo_seen.add(ident)
            for (name, labels), count in sorted(src["samples"].items()):
                if name != fam + "_count":
                    continue
                lab = dict(labels)
                engine, code = lab.get("engine"), lab.get("code")
                if engine is None or code is None:
                    continue
                if engine in self._slo_exclude:
                    continue
                total = src["samples"].get((fam + "_sum", labels), 0.0)
                skey = (ident, engine, code)
                base = self._slo_base.get(skey)
                self._slo_base[skey] = (count, total)
                if base is None:
                    if first_sight:
                        continue  # prime, don't replay pre-fed history
                    base = (0.0, 0.0)  # new series under federation
                delta = count - base[0]
                if delta <= 0:
                    continue
                latency_ms = max(0.0, (total - base[1]) / delta)
                n = int(min(delta, self.config.slo_max_events_per_scrape))
                try:
                    code_i = int(float(code))
                except ValueError:
                    continue
                slo.observe_batch(
                    self.slo_engine, code_i, latency_ms, n
                )

    def _slo_sources(self) -> List[Dict[str, Any]]:
        """Identity-deduped sources for the SLO feed only. Runs every
        background scrape round, so the local side reads count/sum
        straight off the one family's child objects instead of the
        render→parse detour `sources()` pays (which the feed would then
        throw 99% of away) — the full path stays for the render
        surfaces, which need every family."""
        from mmlspark_tpu.obs.metrics import Histogram

        fam_name = self.config.slo_source_family
        local: Dict[Tuple[str, Tuple], float] = {}
        if self._reg.enabled:
            for fam in self._reg.families():
                if fam.name != fam_name or not isinstance(fam, Histogram):
                    continue
                for key, child in fam.children():
                    labels = tuple(sorted(zip(fam.labelnames, key)))
                    local[(fam_name + "_count", labels)] = float(
                        child.count()
                    )
                    local[(fam_name + "_sum", labels)] = float(child.sum())
        out = [{
            "label": self.config.gateway_proc_label,
            "identity": proc_identity(),
            "samples": local,
        }]
        seen = {identity_key(out[0]["identity"])}
        with self._lock:
            for name, tgt in self._targets.items():
                if not tgt.samples and tgt.identity is None:
                    continue
                key = identity_key(tgt.identity)
                if key is not None and key in seen:
                    continue
                if key is not None:
                    seen.add(key)
                out.append({
                    "label": name,
                    "identity": tgt.identity,
                    "samples": tgt.samples,
                })
        return out

    # -- debug fan-out ---------------------------------------------------------

    def fanout_debug(
        self, path: str, local_payload: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """``?scope=cluster`` fan-out for a /debug/* endpoint: fetch every
        target's payload (per-worker timeout; a dead worker yields an
        explicit ``{"worker": i, "error": ...}`` entry under "errors",
        never a hang), merged keyed by process identity — same-process
        payloads (today's in-process workers) collapse into one entry."""
        procs: Dict[str, Any] = {}
        errors: List[Dict[str, Any]] = []
        seen = set()
        if local_payload is not None:
            procs[self.config.gateway_proc_label] = local_payload
            key = identity_key(local_payload.get("proc_identity"))
            if key is not None:
                seen.add(key)
        with self._lock:
            targets = list(self._targets.items())
        for idx, (name, tgt) in enumerate(targets):
            try:
                status, body = tgt.fetch(path)
                if status != 200:
                    raise RuntimeError(f"HTTP {status}")
                payload = json.loads(body.decode("utf-8"))
            except Exception as e:  # partial results, never a dead scrape
                log.warning("federation_fanout_failed", worker=name,
                            path=path, error=repr(e))
                errors.append({"worker": idx, "error": repr(e)})
                continue
            key = (
                identity_key(payload.get("proc_identity"))
                if isinstance(payload, dict) else None
            )
            if key is not None and key in seen:
                continue
            if key is not None:
                seen.add(key)
            procs[name] = payload
        return {"scope": "cluster", "procs": procs, "errors": errors}

    # -- background loop / lifecycle -------------------------------------------

    def start(self) -> "Federator":
        """Start the interval scrape thread (daemon). Tests that inject a
        clock drive `scrape_all` directly instead."""
        if self._thread is not None:
            return self
        self._stop_evt.clear()
        t = threading.Thread(
            target=self._loop, name="obs-federation", daemon=True
        )
        self._thread = t
        t.start()
        return self

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.config.scrape_interval_s):
            try:
                self.scrape_all()
            except Exception as e:  # the loop must survive any one round
                log.warning("federation_loop_error", error=repr(e))

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        """Stop the loop and unhook the per-worker staleness callbacks so
        the process registry doesn't pin a stopped gateway (same teardown
        contract as ServingFabric.close). Cumulative scrape counters and
        duration histograms stay, as counters should."""
        self.stop()
        with self._lock:
            for name in list(self._targets):
                self._stale_gauge.remove(gateway=self._gw, worker=name)
            self._targets.clear()
