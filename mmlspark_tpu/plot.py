"""Plot helpers: confusion matrix and ROC visualization.

Reference: src/plot/src/main/python/plot.py — confusionMatrix/roc over a
scored frame via matplotlib. Metrics compute here with the framework's own
numpy math (no sklearn); matplotlib imports lazily so headless/serving
deployments never pay for it.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame


def confusion_matrix_data(
    df: DataFrame, y_col: str, y_hat_col: str
) -> Tuple[np.ndarray, np.ndarray, float]:
    """(matrix, class labels, accuracy) — counts[i, j] = true class i
    predicted as class j."""
    y = np.asarray(df[y_col], np.float64)
    y_hat = np.asarray(df[y_hat_col], np.float64)
    labels = np.unique(np.concatenate([y, y_hat]))
    index = {v: i for i, v in enumerate(labels)}
    cm = np.zeros((len(labels), len(labels)), np.int64)
    for t, p in zip(y, y_hat):
        cm[index[t], index[p]] += 1
    acc = float((y == y_hat).mean()) if len(y) else 0.0
    return cm, labels, acc


def roc_data(
    df: DataFrame, y_col: str, score_col: str, thresh: float = 0.5
) -> Tuple[np.ndarray, np.ndarray]:
    """(fpr, tpr) curve points sorted by descending score threshold."""
    y = (np.asarray(df[y_col], np.float64) > thresh).astype(np.int64)
    s = np.asarray(df[score_col], np.float64)
    order = np.argsort(-s, kind="stable")
    y = y[order]
    tp = np.cumsum(y)
    fp = np.cumsum(1 - y)
    n_pos = max(int(y.sum()), 1)
    n_neg = max(int((1 - y).sum()), 1)
    tpr = np.concatenate([[0.0], tp / n_pos])
    fpr = np.concatenate([[0.0], fp / n_neg])
    return fpr, tpr


def confusion_matrix(
    df: DataFrame,
    y_col: str,
    y_hat_col: str,
    labels: Optional[Sequence] = None,
    ax=None,
):
    """Render the confusion matrix (reference plot.confusionMatrix)."""
    import matplotlib.pyplot as plt

    cm, found, acc = confusion_matrix_data(df, y_col, y_hat_col)
    labels = list(labels) if labels is not None else [str(v) for v in found]
    ax = ax or plt.gca()
    cmn = cm.astype(float) / np.maximum(cm.sum(axis=1, keepdims=True), 1)
    ax.imshow(cmn, interpolation="nearest", cmap="Blues", vmin=0, vmax=1)
    ax.set_xticks(range(len(labels)), labels)
    ax.set_yticks(range(len(labels)), labels)
    for i in range(cm.shape[0]):
        for j in range(cm.shape[1]):
            ax.text(j, i, str(cm[i, j]), ha="center",
                    color="white" if cmn[i, j] > 0.5 else "black")
    ax.set_xlabel("Predicted Label")
    ax.set_ylabel("True Label")
    ax.set_title(f"Accuracy = {acc * 100:.1f}%")
    return ax


def roc(df: DataFrame, y_col: str, score_col: str, thresh: float = 0.5, ax=None):
    """Render the ROC curve (reference plot.roc)."""
    import matplotlib.pyplot as plt

    fpr, tpr = roc_data(df, y_col, score_col, thresh)
    ax = ax or plt.gca()
    ax.plot(fpr, tpr)
    ax.plot([0, 1], [0, 1], linestyle="--", linewidth=0.8)
    ax.set_xlabel("False Positive Rate")
    ax.set_ylabel("True Positive Rate")
    return ax
