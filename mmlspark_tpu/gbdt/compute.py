"""Device-side GBDT kernels: histogram scatter-add, leaf assignment, tree walk.

These are the ops that touch all n rows; everything else in the grower works
on KB-sized histograms on host. All functions are jit-compiled with static
(F, B) so one program serves the whole fit, and all row-dim inputs may be
sharded over a mesh "data" axis — XLA's SPMD partitioner inserts the
cross-chip reduction for the replicated histogram output, which is exactly
the per-feature histogram allreduce the reference gets from LightGBM's
native TCP ring (SURVEY.md §2.7 item 2, TrainUtils.scala:217).
"""

from __future__ import annotations

import functools

import jax
import numpy as np


@functools.partial(jax.jit, static_argnames=("num_bins",))
def leaf_histogram(bins, grad, hess, mask, *, num_bins: int):
    """Histogram of (grad, hess, count) per (feature, bin) over masked rows.

    bins: (n, F) int32 in [0, num_bins); grad/hess: (n,) f32; mask: (n,) bool.
    -> (F, num_bins, 3) float32.
    """
    import jax.numpy as jnp

    n, f = bins.shape
    g = jnp.where(mask, grad, 0.0).astype(jnp.float32)
    h = jnp.where(mask, hess, 0.0).astype(jnp.float32)
    c = mask.astype(jnp.float32)
    # flat scatter index per (row, feature): feature*B + bin
    idx = bins + jnp.arange(f, dtype=jnp.int32)[None, :] * num_bins
    updates = jnp.stack(
        [jnp.broadcast_to(g[:, None], (n, f)),
         jnp.broadcast_to(h[:, None], (n, f)),
         jnp.broadcast_to(c[:, None], (n, f))],
        axis=-1,
    )
    flat = jnp.zeros((f * num_bins, 3), jnp.float32)
    flat = flat.at[idx.reshape(-1)].add(updates.reshape(-1, 3))
    return flat.reshape(f, num_bins, 3)


@functools.partial(jax.jit, donate_argnums=(0,))
def split_rows(assign, feature_bins, member, slot, new_slot):
    """Send rows of leaf `slot` whose feature bin is NOT in `member` to
    `new_slot` (right child). member: (B,) bool — True = go left.

    assign: (n,) int32; feature_bins: (n,) int32.
    """
    import jax.numpy as jnp

    go_left = member[feature_bins]
    return jnp.where((assign == slot) & ~go_left, new_slot, assign).astype(jnp.int32)


@functools.partial(jax.jit, donate_argnums=(0,))
def add_leaf_outputs(raw, assign, leaf_values):
    """raw += leaf_values[assign] — the training-time prediction update:
    `assign` already holds each row's final leaf, so scoring the new tree is
    one gather (no tree walk)."""
    return raw + leaf_values[assign]


@functools.partial(jax.jit, static_argnames=("max_depth",))
def walk_trees_binned(bins, feats, members, lefts, rights, is_leaf, values,
                      *, max_depth: int):
    """Score rows through a stack of trees using BINNED features.

    bins: (n, F) int32. Tree arrays are padded to (T, m):
    feats (T,m) int32, members (T,m,B) bool (True=left), lefts/rights (T,m),
    is_leaf (T,m) bool, values (T,m) f32. -> (n, T) leaf outputs.
    """
    import jax.numpy as jnp

    def one_tree(feat, member, left, right, leaf, value):
        node = jnp.zeros(bins.shape[0], jnp.int32)

        def step(node, _):
            f = feat[node]                      # (n,)
            b = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0]
            go_left = member[node, b]
            nxt = jnp.where(go_left, left[node], right[node])
            node = jnp.where(leaf[node], node, nxt)
            return node, None

        node, _ = jax.lax.scan(step, node, None, length=max_depth)
        return value[node]

    outs = jax.vmap(one_tree)(feats, members, lefts, rights, is_leaf, values)
    return outs.T  # (n, T)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def walk_trees_raw(x, feats, thresholds, is_cat, cat_masks, lefts, rights,
                   is_leaf, values, *, max_depth: int):
    """Score rows through trees from RAW float features (no binner needed —
    the standalone-model path, like LGBM_BoosterPredictForMat).

    x: (n, F) f32 (NaN allowed). thresholds (T,m) f32; is_cat (T,m) bool;
    cat_masks (T,m,C) bool over integer category values. -> (n, T).
    """
    import jax.numpy as jnp

    n = x.shape[0]
    cat_size = cat_masks.shape[-1]

    def one_tree(feat, thr, cat, cmask, left, right, leaf, value):
        node = jnp.zeros(n, jnp.int32)

        def step(node, _):
            f = feat[node]
            v = jnp.take_along_axis(x, f[:, None], axis=1)[:, 0]
            nan = jnp.isnan(v)
            num_left = nan | (v <= thr[node])
            vi = jnp.clip(jnp.where(nan, -1, v).astype(jnp.int32), 0, cat_size - 1)
            cat_left = cmask[node, vi] & ~nan
            go_left = jnp.where(cat[node], cat_left, num_left)
            nxt = jnp.where(go_left, left[node], right[node])
            node = jnp.where(leaf[node], node, nxt)
            return node, None

        node, _ = jax.lax.scan(step, node, None, length=max_depth)
        return value[node]

    outs = jax.vmap(one_tree)(
        feats, thresholds, is_cat, cat_masks, lefts, rights, is_leaf, values
    )
    return outs.T
