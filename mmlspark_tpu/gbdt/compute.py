"""Device-side GBDT kernels: histogram scatter-add, leaf assignment, tree walk.

These are the ops that touch all n rows; everything else in the grower works
on KB-sized histograms on host. All functions are jit-compiled with static
(F, B) so one program serves the whole fit, and all row-dim inputs may be
sharded over a mesh "data" axis — XLA's SPMD partitioner inserts the
cross-chip reduction for the replicated histogram output, which is exactly
the per-feature histogram allreduce the reference gets from LightGBM's
native TCP ring (SURVEY.md §2.7 item 2, TrainUtils.scala:217).
"""

from __future__ import annotations

import functools

import jax
import numpy as np


@functools.partial(jax.jit, static_argnames=("num_bins",))
def leaf_histogram(bins, grad, hess, mask, *, num_bins: int):
    """Histogram of (grad, hess, count) per (feature, bin) over masked rows.

    bins: (n, F) int32 in [0, num_bins); grad/hess: (n,) f32; mask: (n,) bool.
    -> (F, num_bins, 3) float32. Single-dispatch wrapper over _hist_masked.
    """
    return _hist_masked(bins, grad, hess, mask, num_bins)


@functools.partial(jax.jit, donate_argnums=(0,))
def split_rows(assign, feature_bins, member, slot, new_slot):
    """Send rows of leaf `slot` whose feature bin is NOT in `member` to
    `new_slot` (right child). member: (B,) bool — True = go left.

    assign: (n,) int32; feature_bins: (n,) int32.
    """
    import jax.numpy as jnp

    go_left = member[feature_bins]
    return jnp.where((assign == slot) & ~go_left, new_slot, assign).astype(jnp.int32)


@functools.partial(jax.jit, donate_argnums=(0,))
def add_leaf_outputs(raw, assign, leaf_values):
    """raw += leaf_values[assign] — the training-time prediction update:
    `assign` already holds each row's final leaf, so scoring the new tree is
    one gather (no tree walk)."""
    return raw + leaf_values[assign]


@functools.partial(jax.jit, static_argnames=("col",), donate_argnums=(0,))
def add_leaf_outputs_col(raw, assign, leaf_values, *, col: int):
    """Multiclass add_leaf_outputs: raw[:, col] += leaf_values[assign] for
    one class column of a (n, k) raw-score shard (the data-parallel
    engine's per-device update; `col` is static so the compiled program is
    transfer-free on warm dispatch)."""
    return raw.at[:, col].add(leaf_values[assign])


@functools.partial(jax.jit, static_argnames=("col",))
def take_class_column(arr, *, col: int):
    """arr[:, col] as a compiled program — the data-parallel engine slices
    per-class gradient columns out of a device-resident (m, k) shard
    without promoting index scalars host->device per call."""
    return arr[:, col]


@functools.partial(jax.jit, donate_argnums=(0,))
def reset_assign(assign):
    """Fresh all-zeros leaf assignment for a resident shard (every tree
    starts with all rows in leaf 0); donation reuses the shard's buffer on
    its own device — no host round trip, no reallocation."""
    import jax.numpy as jnp

    return jnp.zeros_like(assign)


# Features whose bin count fits this width join the narrow one-hot group
# (categoricals and low-cardinality numerics); the rest pay the full B.
_SMALL_HIST_B = 64

# Pallas histogram kernel: rows per grid step. Size-adaptive (measured on
# the v5e: 8192 is ~10% faster at 800k rows, 2048 ~25% faster at 40k —
# short grids don't amortize big blocks). trainer.py pads rows with the
# same rule so the block always divides n.
_HIST_BLK_SMALL = 2048
_HIST_BLK_LARGE = 8192
_HIST_BLK_CUTOVER = 262144
# Stats rows padded to the bf16 sublane tile (16): [g, h, count, 13 zeros].
_HIST_STATS = 16


def hist_block(n: int) -> int:
    if n > _HIST_BLK_CUTOVER and n % _HIST_BLK_LARGE == 0:
        return _HIST_BLK_LARGE
    return _HIST_BLK_SMALL


def _pallas_interpret() -> bool:
    """True when Pallas kernels must run in interpret mode: no TPU backend
    is attached, so Mosaic can't compile, but the kernel *body* still runs
    as plain JAX ops. This is how tier-1 (JAX_PLATFORMS=cpu) exercises the
    actual kernel arithmetic instead of only the einsum fallback."""
    return jax.default_backend() != "tpu"


def _route_hist_pallas(binsT, grad, hess, smask_f, assign, memberT,
                       feat, slot, new_slot, small_slot, num_bins: int,
                       n_bins_static=None, interpret=None):
    """Fused row-routing + small-child histogram as ONE Pallas TPU kernel.

    Inputs (device):
      binsT   (F, n) int32 — TRANSPOSED bins: row vectors live on lanes, so
              "take feature f's column" is a contiguous row slice instead of
              the strided gather XLA lowers jnp.take(bins, f, axis=1) to
              (measured 2.2 ms per call at 512k rows — the round-4 grower
              spent more time gathering than histogramming).
      grad/hess/smask_f (1, n) f32; assign (1, n) int32
      memberT (B, 1) f32 — split membership of the chosen leaf (1 = left)
      feat/slot/new_slot/small_slot (1, 1) int32 scalars (SMEM)
    Returns (new_assign (1, n) int32, hist (F, 16, B) f32) where hist rows
    are [g, h, count, 13 zero pads] over rows with
    smask & (new_assign == small_slot).

    Design notes (the hot op of the whole GBDT, SURVEY §7 "fused kernels"):
    - The one-hot never leaves VMEM. The XLA einsum path materializes an
      (n, F, B) bf16 one-hot through HBM — 15 GB at 1M x 30 x 256 (the
      round-4 OOM) — where this kernel's HBM traffic is O(n*F): the bins.
    - dot orientation (16, BLK) x (BLK, B): stats on sublanes (16 = the
      bf16 tile), bins on lanes (B = 2 full 128-lane tiles) — the MXU
      shape the histogram wants. The first pallas cut had stats on lanes
      and ran at 16/128 of peak.
    - Routing (feature-column select + member lookup) rides the same pass
      as one-hot compares + masked sums on the VPU; no gathers anywhere.
    - The hist accumulator block has a constant index_map, so it stays
      VMEM-resident across the whole grid and is written back once.
    - Calling with slot == new_slot == small_slot == 0 and all-ones member
      degenerates to a pure histogram over smask & (assign == 0) with
      assign passed through — the root-histogram path reuses this kernel.
    """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _pallas_interpret()
    F, n = binsT.shape
    B = num_bins
    BLK = hist_block(n)
    assert n % BLK == 0, f"rows {n} not a multiple of {BLK}"
    # Per-feature one-hot widths, rounded up to full 128-lane tiles (Mosaic
    # rejects partial-lane slice writes): the VPU compare work is n x width
    # per feature, and categorical/low-cardinality features only need one
    # lane tile instead of B — on the Adult shape (8 cats of <=43 bins)
    # that removes ~30% of the kernel's dominant cost.
    if n_bins_static is not None:
        widths = tuple(
            min(B, -(-int(nb) // 128) * 128) for nb in n_bins_static
        )
    else:
        widths = (B,) * F

    def kernel(feat_ref, slot_ref, new_ref, small_ref,
               bins_ref, g_ref, h_ref, m_ref, a_ref, mem_ref,
               assign_out, hist_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            hist_ref[:] = jnp.zeros_like(hist_ref)

        bb = bins_ref[:]          # (F, BLK) int32
        a = a_ref[:]              # (1, BLK) int32
        f_star = feat_ref[0, 0]
        s = slot_ref[0, 0]
        new = new_ref[0, 0]
        small = small_ref[0, 0]

        # feature-column select: one-hot over F, masked sum on the VPU
        fsel = (
            jax.lax.broadcasted_iota(jnp.int32, (F, 1), 0) == f_star
        )                          # (F, 1)
        col = jnp.sum(jnp.where(fsel, bb, 0), axis=0, keepdims=True)  # (1, BLK)

        # member lookup without a gather: one-hot over B, masked sum
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (B, BLK), 0)
        ohc = col == iota_b        # (B, BLK)
        gl = jnp.sum(jnp.where(ohc, mem_ref[:], 0.0), axis=0,
                     keepdims=True)            # (1, BLK)
        go_left = gl > 0.5

        na = jnp.where((a == s) & ~go_left, new, a).astype(jnp.int32)
        assign_out[:] = na

        mask = (m_ref[:] > 0.5) & (na == small)   # (1, BLK)
        mf = mask.astype(jnp.bfloat16)
        gm = g_ref[:].astype(jnp.bfloat16) * mf
        hm = h_ref[:].astype(jnp.bfloat16) * mf
        vv = jnp.concatenate(
            [gm, hm, mf,
             jnp.zeros((_HIST_STATS - 3, BLK), jnp.bfloat16)], axis=0
        )                          # (16, BLK)
        # (int16/int8 compares would pack more elements per VPU register,
        # but this target supports neither 16-bit iota nor sub-32-bit
        # compares — int32 one-hot build is the hardware floor here)
        iotas = {
            w: jax.lax.broadcasted_iota(jnp.int32, (w, BLK), 0)
            for w in set(widths)
        }
        for f in range(F):         # static unroll: one MXU dot per feature
            w = widths[f]
            oh = (bb[f:f + 1, :] == iotas[w]).astype(jnp.bfloat16)
            r = jax.lax.dot_general(
                vv, oh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                      # (16, w)
            if w < B:              # pad lanes: Mosaic rejects partial stores
                r = jnp.pad(r, ((0, 0), (0, B - w)))
            hist_ref[f] += r

    smem = pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
    row = lambda i: (0, i)
    new_assign, hist = pl.pallas_call(
        kernel,
        grid=(n // BLK,),
        in_specs=[
            smem, smem, smem, smem,
            pl.BlockSpec((F, BLK), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BLK), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BLK), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BLK), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BLK), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((B, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, BLK), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((F, _HIST_STATS, B), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n), jnp.int32),
            jax.ShapeDtypeStruct((F, _HIST_STATS, B), jnp.float32),
        ],
        interpret=interpret,
    )(
        jnp.reshape(feat, (1, 1)).astype(jnp.int32),
        jnp.reshape(slot, (1, 1)).astype(jnp.int32),
        jnp.reshape(new_slot, (1, 1)).astype(jnp.int32),
        jnp.reshape(small_slot, (1, 1)).astype(jnp.int32),
        binsT,
        jnp.reshape(grad, (1, n)),
        jnp.reshape(hess, (1, n)),
        jnp.reshape(smask_f, (1, n)),
        jnp.reshape(assign, (1, n)).astype(jnp.int32),
        memberT,
    )
    return jnp.reshape(new_assign, (n,)), hist


def _hist_masked(bins, grad, hess, mask, num_bins: int, n_bins_static=None,
                 hist_impl: str = "einsum"):
    """(F, B, 3) histogram over masked rows — leaf_histogram's body, usable
    inside a larger jit program.

    Implemented as a one-hot einsum, not a scatter-add: TPU scatters with
    colliding indices serialize (~4.6 ms per call on the Adult shape,
    BASELINE.md round-4 ablation) while the MXU eats the one-hot contraction
    at ~0.2 ms. The one-hot is bf16 (0/1 — exact); grad/hess are rounded to
    bf16 but accumulate in f32 (preferred_element_type), and counts stay
    exact because the count operand is also exact 0/1. The ~0.4% relative
    rounding on individual g/h entries is far below split-decision noise.

    n_bins_static (hashable per-feature bin counts, known at trace time)
    splits the contraction into a narrow (<= _SMALL_HIST_B) group and a
    full-width group: on the Adult shape (6 numeric x 255 + 8 categorical
    x <=43 bins) that drops per-split one-hot work from n x 3570 to
    n x 2042 cells. Cell values are identical either way — each (f, b)
    reduction is the same sum, just batched with different neighbors.
    """
    import jax.numpy as jnp

    g = jnp.where(mask, grad, 0.0).astype(jnp.bfloat16)
    h = jnp.where(mask, hess, 0.0).astype(jnp.bfloat16)
    c = mask.astype(jnp.bfloat16)

    if hist_impl == "pallas":
        n = bins.shape[0]
        zero = jnp.int32(0)
        _, hist = _route_hist_pallas(
            bins.T, grad.astype(jnp.float32), hess.astype(jnp.float32),
            mask.astype(jnp.float32),
            jnp.zeros(n, jnp.int32),
            jnp.ones((num_bins, 1), jnp.float32),
            zero, zero, zero, zero, num_bins, n_bins_static,
        )
        return hist[:, :3, :].transpose(0, 2, 1)

    vals = jnp.stack([g, h, c], axis=1)  # (n, 3)

    def onehot_hist(sub_bins, width):
        oh = (
            sub_bins[:, :, None] == jnp.arange(width, dtype=jnp.int32)
        ).astype(jnp.bfloat16)
        return jnp.einsum(
            "nfb,nv->fbv", oh, vals, preferred_element_type=jnp.float32
        )

    small_w = min(_SMALL_HIST_B, num_bins)
    if n_bins_static is not None:
        small_idx = tuple(
            f for f, nb in enumerate(n_bins_static) if nb <= small_w
        )
        large_idx = tuple(
            f for f, nb in enumerate(n_bins_static) if nb > small_w
        )
    else:
        small_idx = large_idx = ()
    if not small_idx or not large_idx:
        return onehot_hist(bins, num_bins)
    F = bins.shape[1]
    hs = onehot_hist(bins[:, small_idx], small_w)
    hs = jnp.pad(hs, ((0, 0), (0, num_bins - small_w), (0, 0)))
    hl = onehot_hist(bins[:, large_idx], num_bins)
    out = jnp.zeros((F, num_bins, 3), jnp.float32)
    return (
        out.at[jnp.asarray(small_idx, jnp.int32)].set(hs)
        .at[jnp.asarray(large_idx, jnp.int32)].set(hl)
    )


def _best_split_impl(
    hist,            # (F, B, 3) f32 — this leaf's histogram
    depth_ok,        # traced bool — depth constraint for this leaf
    n_bins_arr,      # (F,) int32
    categorical_arr, # (F,) bool
    feature_mask,    # (F,) bool
    min_data, min_hess, l1, l2,  # traced f32 scalars
    *,
    num_bins: int,
    max_cat_threshold: int,
    n_bins_static=None,
    cat_static=None,
):
    """Best split for one leaf from its (F, B, 3) histogram — THE split
    rule of the fused grower, extracted so the streamed out-of-core grower
    (trainer.py `_stream_grow_tree`) decides splits with the exact same
    traced arithmetic from chunk-accumulated histograms.

    Returns (gain, feat, thr_bin, is_cat, member(B,), left(3,), right(3,));
    gain == -inf when no valid split. Semantics documented on
    _grow_tree_body (this is its former `best_split` closure, verbatim,
    with the closure state passed as arguments)."""
    import jax.numpy as jnp

    F = hist.shape[0]
    B = num_bins
    NEG = jnp.float32(-jnp.inf)

    def thresh(g):
        return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)

    def score(g, h):
        t = thresh(g)
        return t * t / jnp.maximum(h + l2, 1e-35)

    g, h, c = hist[..., 0], hist[..., 1], hist[..., 2]
    tg, th, tc = g.sum(1), h.sum(1), c.sum(1)          # (F,)
    parent = score(tg, th)
    leaf_ok = (tc >= 2.0 * min_data) & feature_mask & depth_ok

    # -- numerical: left = bins [0..t], t in [1, nb-2] ------------------
    cg, ch, cc = jnp.cumsum(g, 1), jnp.cumsum(h, 1), jnp.cumsum(c, 1)
    tpos = jnp.arange(B)[None, :]
    gl, hl, cl = cg, ch, cc
    gr, hr, cr = tg[:, None] - gl, th[:, None] - hl, tc[:, None] - cl
    nvalid = (
        (tpos >= 1)
        & (tpos <= n_bins_arr[:, None] - 2)
        & (cl >= min_data) & (cr >= min_data)
        & (hl >= min_hess) & (hr >= min_hess)
        & (~categorical_arr)[:, None]
        & leaf_ok[:, None]
    )
    ngain = jnp.where(
        nvalid, score(gl, hl) + score(gr, hr) - parent[:, None], NEG
    )
    nbest_t = jnp.argmax(ngain, axis=1)                 # (F,) first max
    nbest_gain = jnp.take_along_axis(ngain, nbest_t[:, None], 1)[:, 0]

    # -- categorical: prefix cuts in g/h-ratio order, both directions ---
    # Argsort-free: the cut "after element i of the stable sort" is the
    # set {j : key_j < key_i or (key_j == key_i and j <= i)}. Building
    # that as a (Fc, Bc, Bc) comparison matrix and taking prefix stats
    # with a small einsum keeps the work on the MXU — the former double
    # argsort + gather chain cost ~1 ms per best_split on TPU
    # (BASELINE.md round-4 ablation). Cut SETS are identical to the
    # sorted-prefix formulation; only the tie-break among equal-gain
    # cuts differs (first original bin vs first sorted position).
    #
    # When the categorical layout is known at trace time (cat_static +
    # n_bins_static), the whole section shrinks to the CATEGORICAL
    # features at their true bin width: Adult's (14, 255, 255)
    # comparison tensors become (8, 48, 48) — ~50x fewer cells per
    # best_split, the dominant per-iteration cost after the histogram
    # grouping.
    if cat_static is not None:
        cat_idx = tuple(f for f, yes in enumerate(cat_static) if yes)
    else:
        cat_idx = tuple(range(F))
    if not cat_idx:
        # all-numeric (known at trace time): skip the categorical
        # machinery entirely — nothing to compute, nothing to mask
        f_star = jnp.argmax(nbest_gain)
        gain = nbest_gain[f_star]
        t_star = nbest_t[f_star]
        member = jnp.arange(B) <= t_star
        left = jnp.stack(
            [cg[f_star, t_star], ch[f_star, t_star], cc[f_star, t_star]]
        )
        total = jnp.stack([tg[f_star], th[f_star], tc[f_star]])
        return (
            gain, f_star.astype(jnp.int32), t_star.astype(jnp.int32),
            jnp.asarray(False), member, left, total - left,
        )
    if n_bins_static is not None and cat_static is not None:
        bc_needed = max(n_bins_static[f] for f in cat_idx)
        Bc = min(B, -(-bc_needed // 8) * 8)
    else:
        Bc = B
    Fc = len(cat_idx)
    ci_arr = jnp.asarray(cat_idx, jnp.int32)
    g_c = g[ci_arr, :Bc]
    h_c = h[ci_arr, :Bc]
    c_c = c[ci_arr, :Bc]
    tg_c, th_c, tc_c = tg[ci_arr], th[ci_arr], tc[ci_arr]
    parent_c = parent[ci_arr]
    nb_c = n_bins_arr[ci_arr]
    leaf_ok_c = leaf_ok[ci_arr]
    catf_c = categorical_arr[ci_arr]

    bpos = jnp.arange(Bc)
    present = (c_c > 0) & (bpos[None, :] >= 1) & (bpos[None, :] < nb_c[:, None])
    ratio = g_c / (h_c + l2 + 1e-12)
    kcats = present.sum(1)                              # (Fc,)
    lim = jnp.minimum(kcats - 1, max_cat_threshold)
    stats3 = jnp.stack([g_c, h_c, c_c], axis=-1)        # (Fc, Bc, 3)

    def one_dir(key):
        tie = (key[:, None, :] == key[:, :, None]) & (
            bpos[None, None, :] <= bpos[None, :, None]
        )
        le = (key[:, None, :] < key[:, :, None]) | tie   # (Fc, Bc, Bc)
        pref = jnp.einsum(
            "fij,fjv->fiv", le.astype(jnp.float32), stats3,
            preferred_element_type=jnp.float32,
        )                                                # (Fc, Bc, 3)
        cgl, chl, ccl = pref[..., 0], pref[..., 1], pref[..., 2]
        cgr = tg_c[:, None] - cgl
        chr_ = th_c[:, None] - chl
        ccr = tc_c[:, None] - ccl
        pos = le.sum(-1) - 1                             # sorted position
        cvalid = (
            (pos < lim[:, None])
            & (ccl >= min_data) & (ccr >= min_data)
            & (chl >= min_hess) & (chr_ >= min_hess)
            & catf_c[:, None]
            & leaf_ok_c[:, None]
        )
        cgain = jnp.where(
            cvalid, score(cgl, chl) + score(cgr, chr_) - parent_c[:, None], NEG
        )
        ibest = jnp.argmax(cgain, axis=1)                # original bin id
        return le, ibest, jnp.take_along_axis(cgain, ibest[:, None], 1)[:, 0], pref

    inf = jnp.float32(jnp.inf)
    key_asc = jnp.where(present, ratio, inf)
    key_desc = jnp.where(present, -ratio, inf)
    le1, i1, g1, p1 = one_dir(key_asc)
    le2, i2, g2, p2 = one_dir(key_desc)
    use2 = g2 > g1                                      # strict, host parity
    ci = jnp.where(use2, i2, i1)
    cbest_gain_c = jnp.maximum(g1, g2)                  # (Fc,)
    # scatter reduced gains back to full feature space
    cbest_gain = jnp.full((F,), NEG).at[ci_arr].set(cbest_gain_c)

    # -- combine per feature, then first-argmax over features -----------
    fgain = jnp.maximum(nbest_gain, cbest_gain)
    use_cat_f = cbest_gain > nbest_gain
    f_star = jnp.argmax(fgain)
    gain = fgain[f_star]
    is_cat = use_cat_f[f_star] & categorical_arr[f_star]
    t_star = nbest_t[f_star]
    # member mask, True = left
    num_member = jnp.arange(B) <= t_star
    # f_star's slot in the reduced view (cat_idx is sorted); clamped
    # garbage when f_star is numeric — masked out by is_cat
    fpos = jnp.clip(
        jnp.searchsorted(ci_arr, f_star).astype(jnp.int32), 0, Fc - 1
    )
    cif = ci[fpos]
    cat_member_c = jnp.where(use2[fpos], le2[fpos, cif], le1[fpos, cif])
    cat_member = jnp.zeros(B, bool).at[:Bc].set(cat_member_c)
    member = jnp.where(is_cat, cat_member, num_member)
    # left stats at the chosen cut
    left_num = jnp.stack([cg[f_star, t_star], ch[f_star, t_star], cc[f_star, t_star]])
    left_cat = jnp.where(use2[fpos], p2[fpos, cif], p1[fpos, cif])
    left = jnp.where(is_cat, left_cat, left_num)
    total = jnp.stack([tg[f_star], th[f_star], tc[f_star]])
    right = total - left
    thr_bin = jnp.where(is_cat, -1, t_star).astype(jnp.int32)
    return gain, f_star.astype(jnp.int32), thr_bin, is_cat, member, left, right


def _grow_tree_body(
    bins,            # (n, F) int32
    grad,            # (n,) f32
    hess,            # (n,) f32
    sample_mask,     # (n,) bool
    n_bins_arr,      # (F,) int32
    categorical_arr, # (F,) bool
    feature_mask,    # (F,) bool
    min_data, min_hess, l1, l2, min_gain, learning_rate,  # traced f32 scalars
    *,
    num_bins: int,
    num_leaves: int,
    depth_limit: int,
    max_cat_threshold: int,
    n_bins_static=None,  # hashable per-feature bin counts (hist grouping)
    cat_static=None,     # hashable per-feature categorical flags (cat view)
    hist_impl: str = "einsum",  # "pallas" on single-device TPU (trainer picks)
):
    """Grow ONE leaf-wise tree entirely on device — the SURVEY §7 "fused
    kernels" design. Plain traceable function: call via grow_tree_fused for
    a standalone dispatch, or inline inside a larger program (boost_loop_fused
    scans it across the whole fit). The host grower's per-split device round
    trip (histogram fetch -> host split finder -> row routing) costs ~100 ms
    of transfer latency per split through the chip tunnel, i.e. seconds per
    tree; this program runs the whole best-first loop (num_leaves-1 fixed
    iterations with masked no-ops after convergence) in one dispatch and
    returns a single packed f32 buffer.

    Semantics match tree.find_best_split/grow_tree (LightGBM
    SerialTreeLearner): leaf-wise argmax-gain growth, sibling histogram
    subtraction, numerical splits over cumulative bins (missing bin 0
    left), sorted-categorical prefix scans from both ends, min_data /
    min_hessian / min_gain / depth constraints. Arithmetic is f32 on
    device (the host path computed gains in f64), so split choices can
    differ from the host grower in near-ties; sharded-vs-single
    determinism is unaffected because every device count runs this same
    program with a replicated histogram reduction.

    Returns (packed, leaf_values, assign):
      packed: flat f32 —
        [num_nodes, num_leaves_used,
         feat(L), thr_bin(L), is_cat(L), gain(L), internal_value(L),
         internal_count(L), left_child(L), right_child(L),
         member(L*B) row-major, leaf_value(L), leaf_count(L)]
        child entries >= 0 are node ids, negative are ~leaf_index.
      leaf_values: (L,) f32 shrunk leaf outputs (for the raw-score update)
      assign: (n,) int32 final leaf index per row
    """
    import jax.numpy as jnp

    F = bins.shape[1]
    B = num_bins
    L = num_leaves
    NEG = jnp.float32(-jnp.inf)

    def thresh(g):
        return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)

    def score(g, h):
        t = thresh(g)
        return t * t / jnp.maximum(h + l2, 1e-35)

    def leaf_out(g, h):
        return -thresh(g) / jnp.maximum(h + l2, 1e-35)

    def best_split(hist, depth_ok):
        """hist (F,B,3) -> (gain, feat, thr_bin, is_cat, member(B,),
        left(3,), right(3,)). gain=-inf when no valid split. The shared
        rule lives in _best_split_impl (the streamed grower calls it on
        chunk-accumulated histograms); under the Pallas tier the
        all-numeric case runs the _split_scan_pallas kernel instead
        (categorical features keep the reference rule — einsum fallback)."""
        if hist_impl == "pallas" and cat_static is not None \
                and not any(cat_static):
            out = _best_splits_pallas_numeric(
                hist[None], depth_ok, n_bins_arr, feature_mask,
                min_data, min_hess, l1, l2, num_bins=B,
            )
            return tuple(o[0] for o in out)
        return _best_split_impl(
            hist, depth_ok, n_bins_arr, categorical_arr, feature_mask,
            min_data, min_hess, l1, l2,
            num_bins=B, max_cat_threshold=max_cat_threshold,
            n_bins_static=n_bins_static, cat_static=cat_static,
        )

    # -- root ----------------------------------------------------------------
    use_pallas = hist_impl == "pallas"
    if use_pallas:
        # transposed layout for the fused route+hist kernel (see
        # _route_hist_pallas); loop-invariant, computed once per tree
        binsT = bins.T
        grad_f = grad.astype(jnp.float32)
        hess_f = hess.astype(jnp.float32)
        smask_f = sample_mask.astype(jnp.float32)
        zero = jnp.int32(0)
        _, h16 = _route_hist_pallas(
            binsT, grad_f, hess_f, smask_f,
            jnp.zeros(bins.shape[0], jnp.int32),
            jnp.ones((B, 1), jnp.float32),
            zero, zero, zero, zero, B, n_bins_static,
        )
        hist0 = h16[:, :3, :].transpose(0, 2, 1)
    else:
        hist0 = _hist_masked(bins, grad, hess, sample_mask, B, n_bins_static,
                             hist_impl)
    root_stats = jnp.stack([hist0[0, :, 0].sum(), hist0[0, :, 1].sum(), hist0[0, :, 2].sum()])
    depth_ok0 = jnp.asarray(0 < depth_limit)
    bg0, bf0, bt0, bic0, bm0, bl0, br0 = best_split(hist0, depth_ok0)

    state = dict(
        assign=jnp.zeros(bins.shape[0], jnp.int32),
        hists=jnp.zeros((L, F, B, 3), jnp.float32).at[0].set(hist0),
        stats=jnp.zeros((L, 3), jnp.float32).at[0].set(root_stats),
        depths=jnp.zeros(L, jnp.int32),
        best_gain=jnp.full(L, NEG).at[0].set(bg0),
        best_feat=jnp.zeros(L, jnp.int32).at[0].set(bf0),
        best_bin=jnp.zeros(L, jnp.int32).at[0].set(bt0),
        best_is_cat=jnp.zeros(L, bool).at[0].set(bic0),
        best_member=jnp.zeros((L, B), bool).at[0].set(bm0),
        best_left=jnp.zeros((L, 3), jnp.float32).at[0].set(bl0),
        best_right=jnp.zeros((L, 3), jnp.float32).at[0].set(br0),
        node_feat=jnp.zeros(L, jnp.int32),
        node_bin=jnp.zeros(L, jnp.int32),
        node_is_cat=jnp.zeros(L, bool),
        node_gain=jnp.zeros(L, jnp.float32),
        node_value=jnp.zeros(L, jnp.float32),
        node_count=jnp.zeros(L, jnp.int32),
        node_left=jnp.full(L, -(2 ** 30), jnp.int32),
        node_right=jnp.full(L, -(2 ** 30), jnp.int32),
        node_member=jnp.zeros((L, B), bool),
        slot_parent=jnp.full(L, -1, jnp.int32),
        slot_side=jnp.zeros(L, jnp.int32),
        n_leaves=jnp.int32(1),
        n_nodes=jnp.int32(0),
        done=jnp.asarray(False),
        step=jnp.int32(0),
    )

    gain_floor = jnp.maximum(min_gain, 0.0)

    def body(st):
        s = jnp.argmax(st["best_gain"]).astype(jnp.int32)
        do = (~st["done"]) & (st["best_gain"][s] > gain_floor)

        def sel(new, old):
            return jnp.where(do, new, old)

        node_id = st["n_nodes"]
        new_slot = st["n_leaves"]

        # record node (writes masked by `do` via sel on the whole array)
        st["node_feat"] = sel(st["node_feat"].at[node_id].set(st["best_feat"][s]), st["node_feat"])
        st["node_bin"] = sel(st["node_bin"].at[node_id].set(st["best_bin"][s]), st["node_bin"])
        st["node_is_cat"] = sel(st["node_is_cat"].at[node_id].set(st["best_is_cat"][s]), st["node_is_cat"])
        st["node_gain"] = sel(st["node_gain"].at[node_id].set(st["best_gain"][s]), st["node_gain"])
        st["node_value"] = sel(
            st["node_value"].at[node_id].set(leaf_out(st["stats"][s, 0], st["stats"][s, 1])),
            st["node_value"],
        )
        st["node_count"] = sel(
            st["node_count"].at[node_id].set(st["stats"][s, 2].astype(jnp.int32)),
            st["node_count"],
        )
        st["node_member"] = sel(st["node_member"].at[node_id].set(st["best_member"][s]), st["node_member"])

        # patch parent pointer (skip for root: parent == -1 -> drop)
        p = st["slot_parent"][s]
        side = st["slot_side"][s]
        lidx = jnp.where(do & (p >= 0) & (side == 0), p, L + 7)
        ridx = jnp.where(do & (p >= 0) & (side == 1), p, L + 7)
        st["node_left"] = st["node_left"].at[lidx].set(node_id, mode="drop")
        st["node_right"] = st["node_right"].at[ridx].set(node_id, mode="drop")
        st["slot_parent"] = sel(
            st["slot_parent"].at[s].set(node_id).at[new_slot].set(node_id),
            st["slot_parent"],
        )
        st["slot_side"] = sel(
            st["slot_side"].at[s].set(0).at[new_slot].set(1), st["slot_side"]
        )

        # child histograms: scatter the SMALLER child, subtract for sibling
        lcnt = st["best_left"][s, 2]
        rcnt = st["best_right"][s, 2]
        small_is_left = lcnt <= rcnt
        small_slot = jnp.where(small_is_left, s, new_slot)

        # route rows (member True = stay left, else new_slot) + small-child
        # histogram: ONE fused kernel on the pallas path, two XLA ops
        # otherwise (the gather-based route costs ~2 ms per split at 512k)
        if use_pallas:
            memberT = st["best_member"][s].astype(jnp.float32)[:, None]
            na, h16 = _route_hist_pallas(
                binsT, grad_f, hess_f, smask_f, st["assign"], memberT,
                st["best_feat"][s], s, new_slot, small_slot, B,
                n_bins_static,
            )
            st["assign"] = sel(na, st["assign"])
            small_hist = h16[:, :3, :].transpose(0, 2, 1)
        else:
            fcol = jnp.take(bins, st["best_feat"][s], axis=1)
            go_left = st["best_member"][s][fcol]
            st["assign"] = sel(
                jnp.where(
                    (st["assign"] == s) & ~go_left, new_slot, st["assign"]
                ).astype(jnp.int32),
                st["assign"],
            )
            small_hist = _hist_masked(
                bins, grad, hess,
                sample_mask & (st["assign"] == small_slot), B,
                n_bins_static, hist_impl,
            )
        big_hist = st["hists"][s] - small_hist
        left_hist = jnp.where(small_is_left, small_hist, big_hist)
        right_hist = jnp.where(small_is_left, big_hist, small_hist)
        st["hists"] = sel(
            st["hists"].at[s].set(left_hist).at[new_slot].set(right_hist),
            st["hists"],
        )
        st["stats"] = sel(
            st["stats"].at[s].set(st["best_left"][s]).at[new_slot].set(st["best_right"][s]),
            st["stats"],
        )
        depth = st["depths"][s] + 1
        st["depths"] = sel(
            st["depths"].at[s].set(depth).at[new_slot].set(depth), st["depths"]
        )

        # recompute best splits for the two children (one vmapped instance
        # of best_split keeps the compiled program half the size)
        depth_ok = depth < depth_limit
        cg_, cf_, ct_, cic_, cm_, cl_, cr_ = jax.vmap(
            lambda hh: best_split(hh, depth_ok)
        )(jnp.stack([left_hist, right_hist]))
        st["best_gain"] = sel(st["best_gain"].at[s].set(cg_[0]).at[new_slot].set(cg_[1]), st["best_gain"])
        st["best_feat"] = sel(st["best_feat"].at[s].set(cf_[0]).at[new_slot].set(cf_[1]), st["best_feat"])
        st["best_bin"] = sel(st["best_bin"].at[s].set(ct_[0]).at[new_slot].set(ct_[1]), st["best_bin"])
        st["best_is_cat"] = sel(st["best_is_cat"].at[s].set(cic_[0]).at[new_slot].set(cic_[1]), st["best_is_cat"])
        st["best_member"] = sel(st["best_member"].at[s].set(cm_[0]).at[new_slot].set(cm_[1]), st["best_member"])
        st["best_left"] = sel(st["best_left"].at[s].set(cl_[0]).at[new_slot].set(cl_[1]), st["best_left"])
        st["best_right"] = sel(st["best_right"].at[s].set(cr_[0]).at[new_slot].set(cr_[1]), st["best_right"])

        st["n_leaves"] = sel(st["n_leaves"] + 1, st["n_leaves"])
        st["n_nodes"] = sel(st["n_nodes"] + 1, st["n_nodes"])
        st["done"] = st["done"] | ~do
        st["step"] = st["step"] + 1
        return st

    # while_loop (not fori): a tree that converges at 5 leaves must not pay
    # for num_leaves-1 full-data histogram steps of masked no-ops
    state = jax.lax.while_loop(
        lambda st: (st["step"] < L - 1) & ~st["done"], body, state
    )

    # -- finalize ------------------------------------------------------------
    slots = jnp.arange(L)
    live = slots < state["n_leaves"]
    leaf_values = jnp.where(
        live, leaf_out(state["stats"][:, 0], state["stats"][:, 1]) * learning_rate, 0.0
    ).astype(jnp.float32)
    leaf_counts = jnp.where(live, state["stats"][:, 2], 0.0)

    # patch leaf references (~slot) into the child arrays
    pmask = live & (state["slot_parent"] >= 0)
    lpatch = jnp.where(pmask & (state["slot_side"] == 0), state["slot_parent"], L + 7)
    rpatch = jnp.where(pmask & (state["slot_side"] == 1), state["slot_parent"], L + 7)
    node_left = state["node_left"].at[lpatch].set(~slots, mode="drop")
    node_right = state["node_right"].at[rpatch].set(~slots, mode="drop")

    packed = jnp.concatenate([
        jnp.stack([state["n_nodes"].astype(jnp.float32),
                   state["n_leaves"].astype(jnp.float32)]),
        state["node_feat"].astype(jnp.float32),
        state["node_bin"].astype(jnp.float32),
        state["node_is_cat"].astype(jnp.float32),
        state["node_gain"],
        state["node_value"],
        state["node_count"].astype(jnp.float32),
        node_left.astype(jnp.float32),
        node_right.astype(jnp.float32),
        state["node_member"].astype(jnp.float32).reshape(-1),
        leaf_values,
        leaf_counts,
    ])
    return packed, leaf_values, state["assign"]


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_bins", "num_leaves", "depth_limit", "max_cat_threshold",
        "n_bins_static", "cat_static", "hist_impl",
    ),
)
def grow_tree_fused(bins, *args, **kwargs):
    """Single-dispatch wrapper over _grow_tree_body (legacy per-iteration
    path: dart/goss/early-stopping, and standalone tree growth)."""
    import jax.numpy as jnp

    return _grow_tree_body(bins.astype(jnp.int32), *args, **kwargs)


@functools.partial(
    jax.jit,
    static_argnames=(
        "objective", "num_bins", "num_leaves", "depth_limit",
        "max_cat_threshold", "num_class", "rf", "has_w", "n_bins_static",
        "cat_static", "hist_impl",
    ),
)
def boost_loop_fused(
    bins,            # (n, F) uint8 wire format or int32; cast on device
    y,               # (n,) f32
    w,               # (n,) f32 (ignored when has_w=False)
    raw0,            # (n,) f32 or (n, k) f32
    sample_masks,    # (M, n) bool — deduped bank of bagging masks
    mask_idx,        # (K,) int32 — per-iteration index into the bank
    fmasks,          # (K, F) bool — per-iteration feature_fraction masks
    n_bins_arr,      # (F,) int32
    categorical_arr, # (F,) bool
    min_data, min_hess, l1, l2, min_gain, learning_rate,  # traced f32 scalars
    *,
    objective,       # static: hashable Objective (grad_hess traced inline)
    num_bins: int,
    num_leaves: int,
    depth_limit: int,
    max_cat_threshold: int,
    num_class: int,
    rf: bool,
    has_w: bool,
    n_bins_static=None,
    cat_static=None,
    hist_impl: str = "einsum",
    valid_idx=None,  # (n_v,) int32 — when given, each iteration also emits
                     # raw scores at these rows (early-stopping eval on host)
):
    """The ENTIRE boosting loop in one XLA program: lax.scan over K
    iterations of (gradients -> fused tree growth -> raw-score update).

    This replaces ~3 dispatches x K iterations with ONE dispatch per fit —
    on remote-attached chips each dispatch/sync can cost ~100 ms of tunnel
    latency, which at K=100 was the entire 30 s fit budget (BASELINE.md
    round-4 profile). It is also the hot loop the reference runs natively
    inside LGBM_BoosterUpdateOneIter (TrainUtils.scala:90-98): one call,
    all iterations, nothing leaves the device until the packed trees are
    fetched at the end.

    Returns (packs, raw): packs (K, P) f32 for num_class==1 else
    (K, num_class, P) — each row decodes with tree.unpack_tree — and the
    final raw scores. With valid_idx, returns (packs, raw, valid_raws)
    where valid_raws (K, n_v) or (K, n_v, k) holds each iteration's raw
    scores at the valid rows (host applies the early-stopping rule).

    rf mode: gradients are taken at raw0 for every tree (bagged fits to the
    initial gradients, trainer semantics); raw still accumulates so the
    caller can average. Multiclass grows num_class trees per step from the
    per-class gradient columns, matching the trainer's class-minor order.
    """
    import jax.numpy as jnp

    bins = bins.astype(jnp.int32)  # uint8 wire format -> device int32 once
    w_ = w if has_w else None
    if rf:
        g0, h0 = objective.grad_hess(raw0, y, w_)

    grow_kwargs = dict(
        num_bins=num_bins, num_leaves=num_leaves, depth_limit=depth_limit,
        max_cat_threshold=max_cat_threshold, n_bins_static=n_bins_static,
        cat_static=cat_static, hist_impl=hist_impl,
    )

    def out(raw, packed):
        if valid_idx is None:
            return packed
        return packed, raw[valid_idx]  # per-iteration valid-row snapshot

    def body(raw, xs):
        mi, fmask = xs
        smask = sample_masks[mi]
        if rf:
            g, h = g0, h0
        else:
            g, h = objective.grad_hess(raw, y, w_)
        if num_class > 1:
            packs = []
            for c in range(num_class):
                packed, lv, assign = _grow_tree_body(
                    bins, g[:, c], h[:, c], smask, n_bins_arr,
                    categorical_arr, fmask, min_data, min_hess, l1, l2,
                    min_gain, learning_rate, **grow_kwargs,
                )
                raw = raw.at[:, c].add(lv[assign])
                packs.append(packed)
            return raw, out(raw, jnp.stack(packs))
        packed, lv, assign = _grow_tree_body(
            bins, g, h, smask, n_bins_arr, categorical_arr, fmask,
            min_data, min_hess, l1, l2, min_gain, learning_rate,
            **grow_kwargs,
        )
        raw = raw + lv[assign]
        return raw, out(raw, packed)

    raw, ys = jax.lax.scan(body, raw0, (mask_idx, fmasks))
    if valid_idx is None:
        return ys, raw
    packs, valid_raws = ys
    return packs, raw, valid_raws  # valid_raws: (K, n_v) or (K, n_v, k)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def walk_trees_binned(bins, feats, members, lefts, rights, is_leaf, values,
                      *, max_depth: int):
    """Score rows through a stack of trees using BINNED features.

    bins: (n, F) int32. Tree arrays are padded to (T, m):
    feats (T,m) int32, members (T,m,B) bool (True=left), lefts/rights (T,m),
    is_leaf (T,m) bool, values (T,m) f32. -> (n, T) leaf outputs.
    """
    import jax.numpy as jnp

    def one_tree(feat, member, left, right, leaf, value):
        node = jnp.zeros(bins.shape[0], jnp.int32)

        def step(node, _):
            f = feat[node]                      # (n,)
            b = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0]
            go_left = member[node, b]
            nxt = jnp.where(go_left, left[node], right[node])
            node = jnp.where(leaf[node], node, nxt)
            return node, None

        node, _ = jax.lax.scan(step, node, None, length=max_depth)
        return value[node]

    outs = jax.vmap(one_tree)(feats, members, lefts, rights, is_leaf, values)
    return outs.T  # (n, T)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def walk_trees_raw(x, feats, thresholds, is_cat, cat_masks, lefts, rights,
                   is_leaf, values, *, max_depth: int):
    """Score rows through trees from RAW float features (no binner needed —
    the standalone-model path, like LGBM_BoosterPredictForMat).

    x: (n, F) f32 (NaN allowed). thresholds (T,m) f32; is_cat (T,m) bool;
    cat_masks (T,m,C) bool over integer category values. -> (n, T).
    """
    import jax.numpy as jnp

    n = x.shape[0]
    cat_size = cat_masks.shape[-1]

    def one_tree(feat, thr, cat, cmask, left, right, leaf, value):
        node = jnp.zeros(n, jnp.int32)

        def step(node, _):
            f = feat[node]
            v = jnp.take_along_axis(x, f[:, None], axis=1)[:, 0]
            nan = jnp.isnan(v)
            num_left = nan | (v <= thr[node])
            vi = jnp.clip(jnp.where(nan, -1, v).astype(jnp.int32), 0, cat_size - 1)
            # negative categorical values are missing-like (upstream LightGBM
            # semantics): route right, never alias category 0
            cat_left = cmask[node, vi] & ~nan & (v >= 0)
            go_left = jnp.where(cat[node], cat_left, num_left)
            nxt = jnp.where(go_left, left[node], right[node])
            node = jnp.where(leaf[node], node, nxt)
            return node, None

        node, _ = jax.lax.scan(step, node, None, length=max_depth)
        return value[node]

    outs = jax.vmap(one_tree)(
        feats, thresholds, is_cat, cat_masks, lefts, rights, is_leaf, values
    )
    return outs.T


# Pallas scoring kernel: rows per grid step (lane-oriented — rows live on
# lanes so every per-row quantity is a full-width (1, BLK) vector row).
_WALK_BLK = 512


@functools.partial(jax.jit, static_argnames=("max_depth", "interpret"))
def walk_trees_pallas(x, feats, thresholds, lefts, rights, is_leaf, values,
                      *, max_depth: int, interpret=None):
    """Fused Pallas ensemble scoring from RAW float features — the
    NUMERIC-tree fast path of walk_trees_raw as one kernel.

    Same packed (T, m) layout and traversal rule as walk_trees_raw (NaN
    routes left; leaves absorb), minus the categorical branch — the
    Booster dispatches here only when no node in the ensemble is
    categorical, and falls back to the walk_trees_raw einsum/gather path
    otherwise. Per grid step (row block, tree) the kernel gathers node
    fields with a one-hot MXU matmul over the node table (each row selects
    exactly one node, so the f32 dot IS the gather — bit-exact), selects
    the split feature the same way over the transposed row block, and
    steps `max_depth` times. Outputs are bitwise identical to
    walk_trees_raw: every emitted value is a leaf value copied, never
    accumulated.

    x: (n, F) f32 (NaN allowed); tree arrays (T, m) as in walk_trees_raw.
    -> (n, T) leaf outputs.
    """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = _pallas_interpret()
    n, F = x.shape
    T, m = feats.shape
    BLK = _WALK_BLK
    n_pad = -(-n // BLK) * BLK
    F_pad = -(-F // 8) * 8
    N_pad = -(-m // 128) * 128

    # features on sublanes, rows on lanes: the per-step feature select is a
    # masked sublane reduction into a (1, BLK) row — no transposes in-kernel
    xT = jnp.pad(x.astype(jnp.float32).T, ((0, F_pad - F), (0, n_pad - n)))
    # node table (T, 8, N_pad) f32 rows: [feat, thr, left, right, leaf,
    # value, 0, 0] — int fields are exact in f32 (node/feature ids < 2^24).
    # The one-hot gather multiplies EVERY table cell by 0 or 1, so the
    # packed layout's thr=+inf leaf sentinel would poison the dot
    # (inf * 0 = NaN in every gathered threshold); non-finite thresholds
    # clamp to f32 max instead. Leaf rows are never compared (absorption
    # keeps idx first), and real split thresholds are finite, so routing
    # is unchanged.
    pad_n = lambda a: jnp.pad(a.astype(jnp.float32), ((0, 0), (0, N_pad - m)))
    thr_f = thresholds.astype(jnp.float32)
    thr_f = jnp.where(jnp.isfinite(thr_f), thr_f,
                      jnp.float32(np.finfo(np.float32).max))
    table = jnp.stack(
        [
            pad_n(feats), pad_n(thr_f), pad_n(lefts), pad_n(rights),
            pad_n(is_leaf), pad_n(values),
            jnp.zeros((T, N_pad), jnp.float32),
            jnp.zeros((T, N_pad), jnp.float32),
        ],
        axis=1,
    )

    def kernel(x_ref, t_ref, o_ref):
        tbl = t_ref[0]                                     # (8, N_pad)
        xb = x_ref[:]                                      # (F_pad, BLK)
        iota_n = jax.lax.broadcasted_iota(jnp.int32, (N_pad, BLK), 0)
        iota_f = jax.lax.broadcasted_iota(jnp.int32, (F_pad, BLK), 0)
        idx = jnp.zeros((1, BLK), jnp.int32)

        def gather_fields(node):
            oh = (iota_n == node).astype(jnp.float32)      # (N_pad, BLK)
            return jax.lax.dot_general(
                tbl, oh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                              # (8, BLK)

        for _ in range(max_depth):
            fields = gather_fields(idx)
            feat_v = fields[0:1, :].astype(jnp.int32)
            thr_v = fields[1:2, :]
            left_v = fields[2:3, :]
            right_v = fields[3:4, :]
            leaf_v = fields[4:5, :]
            fone = iota_f == feat_v
            fv = jnp.sum(jnp.where(fone, xb, 0.0), axis=0,
                         keepdims=True)                    # (1, BLK)
            go_left = jnp.isnan(fv) | (fv <= thr_v)
            nxt = jnp.where(go_left, left_v, right_v)
            idx = jnp.where(
                leaf_v > 0.5, idx.astype(jnp.float32), nxt
            ).astype(jnp.int32)
        o_ref[:] = gather_fields(idx)[5:6, :]

    out = pl.pallas_call(
        kernel,
        grid=(n_pad // BLK, T),
        in_specs=[
            pl.BlockSpec((F_pad, BLK), lambda i, t: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, N_pad), lambda i, t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, BLK), lambda i, t: (t, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((T, n_pad), jnp.float32),
        interpret=bool(interpret),
    )(xT, table)
    return out[:, :n].T


def _split_scan_pallas(gT, hT, cT, nbf, fmf, depth_ok, min_data, min_hess,
                       l1, l2, *, interpret: bool):
    """Per-feature best-split prefix scan as ONE Pallas TPU kernel — the
    numeric half of _best_split_impl, computed on-chip per candidate leaf.

    Inputs are bin-major transposed histograms gT/hT/cT (M, Bp, Fp) f32
    (bins on sublanes, features on lanes — reductions and the prefix scan
    run along sublanes, per-feature results land as full-lane rows), plus
    per-feature bin counts / feature mask as (1, Fp) f32 rows and five
    traced scalars in SMEM.

    Per grid step m the kernel computes totals, the bin prefix sums (a
    lower-triangular f32 matmul on the MXU — same sums as jnp.cumsum, MXU
    accumulation order), the reference gain formula, and the FIRST-max
    threshold per feature (max + first-index-of-max, the exact tie rule of
    jnp.argmax(ngain, axis=1)). Outputs per leaf: per-feature best gain
    (M, Fp), best threshold bin (M, Fp) i32, and an (M, 8, Fp) stats block
    [left g/h/c at the best cut, total g/h/c, 0, 0]. Feature selection
    (first-argmax over features) happens outside, in the same jnp ops as
    the reference's all-numeric early return.
    """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, Bp, Fp = gT.shape
    NEG = np.float32(-np.inf)

    def kernel(dok_ref, md_ref, mh_ref, l1_ref, l2_ref,
               g_ref, h_ref, c_ref, nb_ref, fm_ref,
               gain_ref, thr_ref, ls_ref):
        g = g_ref[0]          # (Bp, Fp)
        h = h_ref[0]
        c = c_ref[0]
        nb = nb_ref[:]        # (1, Fp) f32 bin counts
        fm = fm_ref[:] > 0.5  # (1, Fp)
        dok = dok_ref[0, 0] > 0.5
        md = md_ref[0, 0]
        mh = mh_ref[0, 0]
        l1v = l1_ref[0, 0]
        l2v = l2_ref[0, 0]

        def score(gv, hv):
            t = jnp.sign(gv) * jnp.maximum(jnp.abs(gv) - l1v, 0.0)
            return t * t / jnp.maximum(hv + l2v, 1e-35)

        tg = jnp.sum(g, axis=0, keepdims=True)   # (1, Fp)
        th_ = jnp.sum(h, axis=0, keepdims=True)
        tc = jnp.sum(c, axis=0, keepdims=True)
        parent = score(tg, th_)
        leaf_ok = (tc >= 2.0 * md) & fm & dok

        # prefix sums along bins: lower-triangular ones matmul (MXU) —
        # L[i, j] = j <= i, gl = L @ g. Same cell sums as jnp.cumsum, MXU
        # accumulation order (identical whenever the addends' sums are
        # exactly representable; f32-ulp band otherwise).
        ii = jax.lax.broadcasted_iota(jnp.int32, (Bp, Bp), 0)
        jj = jax.lax.broadcasted_iota(jnp.int32, (Bp, Bp), 1)
        L = (jj <= ii).astype(jnp.float32)
        dot = lambda a, b: jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        gl, hl, cl = dot(L, g), dot(L, h), dot(L, c)
        gr, hr, cr = tg - gl, th_ - hl, tc - cl

        tpos = jax.lax.broadcasted_iota(jnp.int32, (Bp, Fp), 0)
        nvalid = (
            (tpos >= 1)
            & (tpos.astype(jnp.float32) <= nb - 2.0)
            & (cl >= md) & (cr >= md)
            & (hl >= mh) & (hr >= mh)
            & leaf_ok
        )
        ngain = jnp.where(nvalid, score(gl, hl) + score(gr, hr) - parent, NEG)
        # first max along bins == jnp.argmax(ngain, axis): max value, then
        # the smallest bin index attaining it
        mx = jnp.max(ngain, axis=0, keepdims=True)          # (1, Fp)
        cand = jnp.where(ngain == mx, tpos, jnp.int32(Bp))
        best_t = jnp.min(cand, axis=0, keepdims=True)       # (1, Fp)
        sel = tpos == best_t                                 # one per column
        pick = lambda a: jnp.sum(jnp.where(sel, a, 0.0), axis=0,
                                 keepdims=True)
        gain_ref[:] = pick(ngain)
        thr_ref[:] = best_t
        ls_ref[0] = jnp.concatenate(
            [pick(gl), pick(hl), pick(cl), tg, th_, tc,
             jnp.zeros((2, Fp), jnp.float32)], axis=0
        )

    smem = pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
    leaf3 = pl.BlockSpec((1, Bp, Fp), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM)
    frow = pl.BlockSpec((1, Fp), lambda i: (0, 0), memory_space=pltpu.VMEM)
    out_row = pl.BlockSpec((1, Fp), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    as_smem = lambda v: jnp.reshape(v, (1, 1)).astype(jnp.float32)
    return pl.pallas_call(
        kernel,
        grid=(M,),
        in_specs=[smem] * 5 + [leaf3, leaf3, leaf3, frow, frow],
        out_specs=[
            out_row, out_row,
            pl.BlockSpec((1, 8, Fp), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, Fp), jnp.float32),
            jax.ShapeDtypeStruct((M, Fp), jnp.int32),
            jax.ShapeDtypeStruct((M, 8, Fp), jnp.float32),
        ],
        interpret=interpret,
    )(
        as_smem(depth_ok), as_smem(min_data), as_smem(min_hess),
        as_smem(l1), as_smem(l2), gT, hT, cT, nbf, fmf,
    )


def _best_splits_pallas_numeric(
    hists, depth_ok, n_bins_arr, feature_mask,
    min_data, min_hess, l1, l2, *, num_bins: int, interpret=None,
):
    """Traced all-numeric split finder over the _split_scan_pallas kernel:
    pad/transpose to the kernel's bin-major layout, scan on-chip, then
    apply the reference's all-numeric feature-selection rule (first argmax
    over features) verbatim outside. Shared by best_splits_for_hists
    (streamed/data-parallel host-driven growers) and the fused grower's
    per-leaf best_split — pure traced code, safe inside an enclosing jit."""
    import jax.numpy as jnp

    if interpret is None:
        interpret = _pallas_interpret()
    M, F = hists.shape[0], hists.shape[1]
    B = num_bins
    Fp = -(-F // 128) * 128
    Bp = -(-B // 8) * 8
    h4 = jnp.pad(
        hists.astype(jnp.float32),
        ((0, 0), (0, Fp - F), (0, Bp - B), (0, 0)),
    )
    gT = h4[..., 0].transpose(0, 2, 1)   # (M, Bp, Fp)
    hT = h4[..., 1].transpose(0, 2, 1)
    cT = h4[..., 2].transpose(0, 2, 1)
    nbf = jnp.zeros((1, Fp), jnp.float32).at[0, :F].set(
        n_bins_arr.astype(jnp.float32)
    )
    fmf = jnp.zeros((1, Fp), jnp.float32).at[0, :F].set(
        feature_mask.astype(jnp.float32)
    )
    gains, thrs, ls = _split_scan_pallas(
        gT, hT, cT, nbf, fmf, depth_ok, min_data, min_hess, l1, l2,
        interpret=bool(interpret),
    )
    gains = gains[:, :F]
    # feature pick: the reference's all-numeric early return, verbatim
    f_star = jnp.argmax(gains, axis=1).astype(jnp.int32)
    gain = jnp.take_along_axis(gains, f_star[:, None], 1)[:, 0]
    t_star = jnp.take_along_axis(
        thrs[:, :F], f_star[:, None], 1
    )[:, 0].astype(jnp.int32)
    member = jnp.arange(B)[None, :] <= t_star[:, None]
    lsf = jnp.take_along_axis(
        ls, f_star[:, None, None], 2
    )[:, :, 0]                                    # (M, 8)
    left = lsf[:, 0:3]
    right = lsf[:, 3:6] - left
    is_cat = jnp.zeros((M,), bool)
    return gain, f_star, t_star, is_cat, member, left, right


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_bins", "max_cat_threshold", "n_bins_static", "cat_static",
        "split_impl", "interpret",
    ),
)
def best_splits_for_hists(
    hists,           # (M, F, B, 3) f32 — one histogram per candidate leaf
    depth_ok,        # traced bool (children of one split share a depth)
    n_bins_arr,      # (F,) int32
    categorical_arr, # (F,) bool
    feature_mask,    # (F,) bool
    min_data, min_hess, l1, l2,  # traced f32 scalars
    *,
    num_bins: int,
    max_cat_threshold: int,
    n_bins_static=None,
    cat_static=None,
    split_impl: str = "reference",
    interpret=None,
):
    """Vectorized best_split over M leaf histograms — the streamed grower's
    split finder. SAME traced arithmetic as the fused grower's per-leaf
    rule (_best_split_impl), so streamed trees decide splits exactly the
    way in-memory trees do; only the histogram accumulation order (fixed
    chunk order vs one whole-n contraction) can differ, in f32 ulps.

    split_impl picks the reduction: "reference" is the jitted-vmap over
    _best_split_impl; "pallas" runs the _split_scan_pallas kernel (per-
    feature prefix scan on-chip) and applies the reference's all-numeric
    feature-selection rule outside — tie-breaking is identical (first max
    over thresholds, first argmax over features). The kernel covers the
    all-numeric case only: any categorical feature falls back to the
    reference impl (the categorical prefix machinery stays XLA einsums).

    Returns (gain (M,), feat (M,), thr_bin (M,), is_cat (M,),
    member (M, B), left (M, 3), right (M, 3))."""
    import jax.numpy as jnp

    all_numeric = cat_static is not None and not any(cat_static)
    if split_impl == "pallas" and all_numeric:
        return _best_splits_pallas_numeric(
            hists, depth_ok, n_bins_arr, feature_mask,
            min_data, min_hess, l1, l2,
            num_bins=num_bins, interpret=interpret,
        )

    def one(h):
        return _best_split_impl(
            h, depth_ok, n_bins_arr, categorical_arr, feature_mask,
            min_data, min_hess, l1, l2,
            num_bins=num_bins, max_cat_threshold=max_cat_threshold,
            n_bins_static=n_bins_static, cat_static=cat_static,
        )

    return jax.vmap(one)(hists.astype(jnp.float32))


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "n_bins_static", "hist_impl"),
)
def route_hist_chunk(
    bins,        # (m, F) uint8/int32 — ONE streamed chunk's binned rows
    grad,        # (m,) f32
    hess,        # (m,) f32
    smask,       # (m,) bool — bagging/sample mask for these rows
    assign,      # (m,) int32 — current leaf assignment of these rows
    member,      # (B,) bool — split membership of leaf `slot` (True = left)
    feat, slot, new_slot, small_slot,  # traced int32 scalars
    *,
    num_bins: int,
    n_bins_static=None,
    hist_impl: str = "einsum",
):
    """One streamed chunk's share of a split step: route the chunk's rows
    of leaf `slot` through the split (member[bin] False -> `new_slot`) and
    return the chunk's (F, B, 3) histogram contribution over rows landing
    in `small_slot` — exactly the per-split routing + small-child histogram
    of _grow_tree_body, at chunk granularity. The host accumulates these
    contributions across chunks in FIXED chunk order (deterministic f32
    sums), so an out-of-core fit is bit-reproducible at a given chunk size.

    The root pass reuses this kernel degenerately: feat=slot=new_slot=
    small_slot=0 with an all-ones member routes nothing and histograms
    smask & (assign == 0).

    Returns (new_assign (m,) int32, hist (F, B, 3) f32)."""
    import jax.numpy as jnp

    bins = bins.astype(jnp.int32)  # uint8 wire format -> device int32 once
    if hist_impl == "pallas":
        # single-device TPU: routing + small-child histogram as ONE fused
        # Pallas pass (the _route_hist_pallas design notes) instead of the
        # XLA gather + one-hot einsum through HBM. Chunk rows must be a
        # hist_block multiple — the streamed trainer pads ragged chunks
        # with masked-out rows (exact: zero-weight rows add 0.0f).
        na, h16 = _route_hist_pallas(
            bins.T, grad.astype(jnp.float32), hess.astype(jnp.float32),
            smask.astype(jnp.float32), assign.astype(jnp.int32),
            member.astype(jnp.float32)[:, None],
            feat, slot, new_slot, small_slot, num_bins, n_bins_static,
        )
        return na, h16[:, :3, :].transpose(0, 2, 1)
    fcol = jnp.take(bins, feat, axis=1)
    go_left = member[fcol]
    new_assign = jnp.where(
        (assign == slot) & ~go_left, new_slot, assign
    ).astype(jnp.int32)
    hist = _hist_masked(
        bins, grad, hess, smask & (new_assign == small_slot), num_bins,
        n_bins_static, hist_impl,
    )
    return new_assign, hist


@functools.partial(
    jax.jit,
    static_argnames=("num_bins", "n_bins_static", "hist_impl"),
    donate_argnums=(4,),
)
def route_hist_shard(
    bins,        # (m, F) uint8/int32 — ONE device-resident row shard
    grad,        # (m,) f32 — this shard's gradient slice (device-resident)
    hess,        # (m,) f32
    smask,       # (m,) bool — bagging/train mask for these rows
    assign,      # (m,) int32 — current leaf assignment (DONATED: the shard
                 #   keeps exactly one assignment buffer on its device)
    member,      # (B,) bool — split membership of leaf `slot` (True = left)
    feat, slot, new_slot, small_slot,  # traced int32 scalars
    *,
    num_bins: int,
    n_bins_static=None,
    hist_impl: str = "einsum",
):
    """One mesh shard's share of a split step — the data-parallel engine's
    per-device kernel. Same routing + small-child histogram semantics as
    route_hist_chunk, but the row data never moves: bins/grad/hess/mask/
    assign are resident on the shard's owning device, the host uploads only
    the (B,) member mask and four scalars per pass, and fetches the (F, B, 3)
    histogram plus TWO int32 counts. The host then sums per-shard histograms
    in FIXED shard order (the documented deterministic accumulation order —
    an explicit fixed-order segment reduction rather than a psum, so sharded
    fits are bit-reproducible at a given shard count; docs/gbdt.md
    "Distributed training").

    The extra `counts` output is [rows now in `slot`, rows now in
    `new_slot`] over ALL shard rows (unmasked — bagging must not hide rows
    from future routing), which is what lets the host skip shards with no
    rows in a leaf on later splits without ever fetching per-row state.

    Returns (new_assign (m,) int32, hist (F, B, 3) f32, counts (2,) int32).
    """
    import jax.numpy as jnp

    bins = bins.astype(jnp.int32)
    if hist_impl == "pallas":
        # per-shard fused routing + histogram (the _route_hist_pallas
        # design): the shard's rows never leave its device either way, but
        # the kernel's one-hot stays in VMEM instead of an (m, F, B) bf16
        # one-hot through HBM. The trainer pads every shard to a
        # hist_block multiple with zero-weight masked-out rows — exact,
        # since they add 0.0f to every cell, and count semantics are
        # unchanged (counts were always over ALL shard rows, pads ride in
        # leaf 0 exactly like the pre-existing nd-alignment pad rows).
        na, h16 = _route_hist_pallas(
            bins.T, grad.astype(jnp.float32), hess.astype(jnp.float32),
            smask.astype(jnp.float32), assign.astype(jnp.int32),
            member.astype(jnp.float32)[:, None],
            feat, slot, new_slot, small_slot, num_bins, n_bins_static,
        )
        counts = jnp.stack(
            [(na == slot).sum(), (na == new_slot).sum()]
        ).astype(jnp.int32)
        return na, h16[:, :3, :].transpose(0, 2, 1), counts
    fcol = jnp.take(bins, feat, axis=1)
    go_left = member[fcol]
    new_assign = jnp.where(
        (assign == slot) & ~go_left, new_slot, assign
    ).astype(jnp.int32)
    hist = _hist_masked(
        bins, grad, hess, smask & (new_assign == small_slot), num_bins,
        n_bins_static, hist_impl,
    )
    counts = jnp.stack(
        [(new_assign == slot).sum(), (new_assign == new_slot).sum()]
    ).astype(jnp.int32)
    return new_assign, hist, counts
